"""graftlint CLI: ``python -m unionml_tpu.analysis [paths] [--json OUT]``.

Exit codes: 0 clean, 1 findings, 2 bad invocation. Findings always fail the
run — ``--fail-on-findings`` exists so CI scripts state the contract
explicitly; ``--no-fail-on-findings`` turns the run advisory (report only).
"""

import argparse
import sys

from unionml_tpu.analysis.core import RULES, run_lint


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m unionml_tpu.analysis",
        description="graftlint: JAX-aware static analysis "
                    "(host-sync, retrace, sharding, lock-discipline)",
    )
    parser.add_argument("paths", nargs="*", default=["unionml_tpu"],
                        help="files or directories to lint (default: unionml_tpu)")
    parser.add_argument("--rules", help="comma-separated rule subset (default: all)")
    parser.add_argument("--json", metavar="OUT", dest="json_out",
                        help="write the machine-readable report to OUT ('-' for stdout)")
    parser.add_argument("--list-rules", action="store_true", help="print the rule catalog and exit")
    parser.add_argument("--fail-on-findings", dest="fail", action="store_true", default=True,
                        help="exit non-zero when findings remain (default)")
    parser.add_argument("--no-fail-on-findings", dest="fail", action="store_false",
                        help="advisory mode: report but exit 0")
    args = parser.parse_args(argv)

    if args.list_rules:
        # import for registration side effects
        from unionml_tpu.analysis import (  # noqa: F401
            rules_host_sync, rules_locks, rules_retrace, rules_sharding,
        )
        for name in sorted(RULES):
            print(f"{name:16s} {RULES[name].summary}")
        print("suppression      (always on) graftlint comments need a known rule and a reason")
        return 0

    rules = [r.strip() for r in args.rules.split(",")] if args.rules else None
    try:
        result = run_lint(args.paths or ["unionml_tpu"], rules)
    except ValueError as exc:
        print(f"graftlint: {exc}", file=sys.stderr)
        return 2

    for finding in result.findings:
        print(finding.format())
    summary = (
        f"graftlint: {len(result.findings)} finding(s), "
        f"{len(result.suppressed)} suppressed, {result.files} file(s)"
    )
    print(summary, file=sys.stderr if result.findings else sys.stdout)

    if args.json_out:
        payload = result.report_json() + "\n"
        if args.json_out == "-":
            sys.stdout.write(payload)
        else:
            with open(args.json_out, "w") as fh:
                fh.write(payload)

    return 1 if (result.findings and args.fail) else 0


if __name__ == "__main__":
    sys.exit(main())
