"""Rule ``swallowed-exception``: broad handlers that bury the error.

The serving core's failure taxonomy (ISSUE 7) only works if failures actually
REACH it: a ``except Exception: pass`` between a device fault and the
supervisor turns a recoverable incident into a silent wedge — the request
hangs, the health state stays green, and the only evidence is a missing
response. This rule mechanically forbids that shape: every broad handler
(``except Exception:``, ``except BaseException:``, or a bare ``except:``)
must do at least one of

- **re-raise** — a ``raise`` anywhere in the handler body (plain or a new,
  typically structured, exception);
- **log** — a call whose method name is a logging verb (``debug``/``info``/
  ``warning``/``error``/``exception``/``critical``/``log``);
- **record** — *use the bound exception* (``except Exception as exc:`` with
  ``exc`` read somewhere in the body): passing it to a sink/callback,
  embedding it in a structured response or message, stashing it on state.

A handler that intentionally does none of these (a best-effort ``__del__``,
an optional-probe fallback) needs the standard reasoned suppression —
``# graftlint: disable=swallowed-exception -- why silence is safe here`` — so
every silenced failure path documents its justification in the diff.

Narrow handlers (``except ValueError:`` etc.) are exempt: naming the expected
exception is itself the evidence that the swallow is deliberate and bounded.
"""

import ast
from typing import Iterator, List

from unionml_tpu.analysis.core import Finding, Project, register

#: method names that count as logging the failure
LOG_METHODS = frozenset(
    {"debug", "info", "warning", "error", "exception", "critical", "log"}
)
#: exception types broad enough to catch arbitrary failures
BROAD_TYPES = frozenset({"Exception", "BaseException"})


def _is_broad(handler: ast.ExceptHandler) -> bool:
    node = handler.type
    if node is None:
        return True  # bare except:
    if isinstance(node, ast.Name):
        return node.id in BROAD_TYPES
    if isinstance(node, ast.Attribute):
        return node.attr in BROAD_TYPES
    if isinstance(node, ast.Tuple):
        return any(
            _is_broad(ast.ExceptHandler(type=el, name=None, body=[])) for el in node.elts
        )
    return False


def _handles_failure(handler: ast.ExceptHandler) -> bool:
    """Whether the handler re-raises, logs, or uses the bound exception."""
    bound = handler.name
    for node in ast.walk(ast.Module(body=handler.body, type_ignores=[])):
        if isinstance(node, ast.Raise):
            return True
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in LOG_METHODS
        ):
            return True
        if (
            bound
            and isinstance(node, ast.Name)
            and node.id == bound
            and isinstance(node.ctx, ast.Load)
        ):
            return True
    return False


class _Visitor(ast.NodeVisitor):
    """Collects offending handlers with their enclosing symbol qualname."""

    def __init__(self) -> None:
        self.stack: List[str] = []
        self.found: List = []  # (handler, qualname)

    def _visit_scope(self, node: ast.AST, name: str) -> None:
        self.stack.append(name)
        self.generic_visit(node)
        self.stack.pop()

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._visit_scope(node, node.name)

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        self._visit_scope(node, node.name)

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self._visit_scope(node, node.name)

    def visit_ExceptHandler(self, node: ast.ExceptHandler) -> None:
        if _is_broad(node) and not _handles_failure(node):
            self.found.append((node, ".".join(self.stack)))
        self.generic_visit(node)


@register(
    "swallowed-exception",
    "broad except handlers that neither re-raise, log, nor record the failure",
)
def check(project: Project) -> Iterator[Finding]:
    for mod in project.modules:
        visitor = _Visitor()
        visitor.visit(mod.tree)
        for handler, symbol in visitor.found:
            what = "bare except" if handler.type is None else "broad except"
            yield Finding(
                "swallowed-exception",
                mod.relpath,
                handler.lineno,
                handler.col_offset,
                f"{what} swallows the error: the handler neither re-raises, "
                f"logs, nor records the exception — a failure here vanishes "
                f"without a trace; narrow the except, handle the failure, or "
                f"suppress with a reason",
                symbol=symbol,
            )
