"""Rule ``swallowed-exception``: broad handlers that bury the error.

The serving core's failure taxonomy (ISSUE 7) only works if failures actually
REACH it: a ``except Exception: pass`` between a device fault and the
supervisor turns a recoverable incident into a silent wedge — the request
hangs, the health state stays green, and the only evidence is a missing
response. This rule mechanically forbids that shape: every broad handler
(``except Exception:``, ``except BaseException:``, or a bare ``except:``)
must do at least one of

- **re-raise** — a ``raise`` anywhere in the handler body (plain or a new,
  typically structured, exception);
- **log** — a call whose method name is a logging verb (``debug``/``info``/
  ``warning``/``error``/``exception``/``critical``/``log``);
- **record** — *use the bound exception* (``except Exception as exc:`` with
  ``exc`` read somewhere in the body): passing it to a sink/callback,
  embedding it in a structured response or message, stashing it on state.

Three shapes are recognized as *handling by construction* (v3, CFG-aware) and
exempted without a suppression:

- **best-effort release** — the ``try`` body is nothing but release-verb
  calls (``close``/``release``/``unpin``/``unregister``/``shutdown``/... )
  and the handler is ``pass``-only: teardown that must never raise
  (``__del__``, ``__exit__``, unsubscribe-on-drift). The error has no
  consumer by definition.
- **cleanup-release handler** — the handler releases resources
  (a release-verb call) and every CFG path from the handler's entry to code
  outside the handler passes through a release call: the handler IS the
  release-on-error path the resource-lifetime rules demand, and flagging it
  would pit one rule family against another.
- **fallback binding** — the handler only assigns names that the ``try``
  body also binds (``raw = probe() ... except Exception: raw = {}``): the
  fallback value is the documented handling; nothing is swallowed.

A handler that intentionally does none of these still needs the standard
reasoned suppression — ``# graftlint: disable=swallowed-exception -- why
silence is safe here`` — so every silenced failure path documents its
justification in the diff.

Narrow handlers (``except ValueError:`` etc.) are exempt: naming the expected
exception is itself the evidence that the swallow is deliberate and bounded.
"""

import ast
from typing import Iterator, List, Set, Tuple

from unionml_tpu.analysis.cfg import ALWAYS_KINDS, build_cfg, reachable
from unionml_tpu.analysis.core import Finding, Project, register

#: method names that count as logging the failure
LOG_METHODS = frozenset(
    {"debug", "info", "warning", "error", "exception", "critical", "log"}
)
#: exception types broad enough to catch arbitrary failures
BROAD_TYPES = frozenset({"Exception", "BaseException"})
#: leaf-name prefixes (leading underscores stripped) that read as "give the
#: resource back" — the vocabulary shared with rules_resources' spec table
RELEASE_VERBS = (
    "close", "release", "unpin", "unregister", "unsubscribe", "unlink",
    "shutdown", "stop", "cancel", "discard", "end_trace", "terminate",
    "disconnect",
)


def _is_broad(handler: ast.ExceptHandler) -> bool:
    node = handler.type
    if node is None:
        return True  # bare except:
    if isinstance(node, ast.Name):
        return node.id in BROAD_TYPES
    if isinstance(node, ast.Attribute):
        return node.attr in BROAD_TYPES
    if isinstance(node, ast.Tuple):
        return any(
            _is_broad(ast.ExceptHandler(type=el, name=None, body=[])) for el in node.elts
        )
    return False


def _handles_failure(handler: ast.ExceptHandler) -> bool:
    """Whether the handler re-raises, logs, or uses the bound exception."""
    bound = handler.name
    for node in ast.walk(ast.Module(body=handler.body, type_ignores=[])):
        if isinstance(node, ast.Raise):
            return True
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in LOG_METHODS
        ):
            return True
        if (
            bound
            and isinstance(node, ast.Name)
            and node.id == bound
            and isinstance(node.ctx, ast.Load)
        ):
            return True
    return False


# --------------------------------------------------------------- exemptions


def _is_release_call(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    f = node.func
    leaf = f.attr if isinstance(f, ast.Attribute) else getattr(f, "id", None)
    return leaf is not None and leaf.lstrip("_").startswith(RELEASE_VERBS)


def _pass_only(handler: ast.ExceptHandler) -> bool:
    return all(isinstance(s, ast.Pass) for s in handler.body)


def _best_effort_release(try_node: ast.AST, handler: ast.ExceptHandler) -> bool:
    """``try: <release calls only> except Exception: pass`` — teardown that
    must never raise; there is no consumer for the error."""
    if not _pass_only(handler) or not try_node.body:
        return False
    return all(
        isinstance(stmt, ast.Expr) and _is_release_call(stmt.value)
        for stmt in try_node.body
    )


def _bound_names(stmts) -> Set[str]:
    """Names a statement list binds: assignments (plain/ann/aug), loop and
    ``with`` targets, and import aliases."""
    names: Set[str] = set()

    def targets(t: ast.AST) -> None:
        if isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                targets(e)
        elif isinstance(t, ast.Starred):
            targets(t.value)
        elif isinstance(t, ast.Name):
            names.add(t.id)

    for node in ast.walk(ast.Module(body=list(stmts), type_ignores=[])):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                targets(t)
        elif isinstance(node, (ast.AnnAssign, ast.AugAssign)):
            targets(node.target)
        elif isinstance(node, (ast.For, ast.AsyncFor)):
            targets(node.target)
        elif isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                if item.optional_vars is not None:
                    targets(item.optional_vars)
        elif isinstance(node, (ast.Import, ast.ImportFrom)):
            for alias in node.names:
                names.add(alias.asname or alias.name.split(".", 1)[0])
    return names


def _fallback_binding(try_node: ast.AST, handler: ast.ExceptHandler) -> bool:
    """The handler only assigns fallback values for names the ``try`` body
    binds — the assignment IS the handling."""
    assigned: Set[str] = set()
    for stmt in handler.body:
        if isinstance(stmt, ast.Assign):
            for t in stmt.targets:
                if isinstance(t, ast.Name):
                    assigned.add(t.id)
                else:
                    return False
        elif isinstance(stmt, ast.AnnAssign) and isinstance(stmt.target, ast.Name):
            assigned.add(stmt.target.id)
        else:
            return False
    return bool(assigned) and bool(assigned & _bound_names(try_node.body))


def _releases_then_falls_through(scope: ast.AST, handler: ast.ExceptHandler) -> bool:
    """CFG check: the handler contains a release-verb call, and every path
    from its entry to code outside the handler passes through one — i.e. the
    handler is a release-on-error cleanup, not a swallow."""
    if not any(
        _is_release_call(n)
        for n in ast.walk(ast.Module(body=handler.body, type_ignores=[]))
    ):
        return False
    cfg = build_cfg(scope)
    entry = None
    for block in cfg.blocks.values():
        if block.kind == "handler" and any(n is handler for n, _r in block.items):
            entry = block.id
            break
    if entry is None:  # unreachable in practice: the builder saw the same AST
        return False

    def releases(block) -> bool:
        return any(
            _is_release_call(n)
            for item, role in block.items
            if role == "stmt"
            for n in ast.walk(item)
        )

    parents = reachable(
        cfg, entry,
        follow=lambda _b, e: e.kind in ALWAYS_KINDS,
        stop=lambda b: releases(b),
    )
    for bid in parents:
        block = cfg.blocks[bid]
        if handler not in block.regions and not releases(block):
            return False  # a path leaves the handler without releasing
    return True


class _Visitor(ast.NodeVisitor):
    """Collects offending handlers with their enclosing symbol qualname."""

    def __init__(self, tree: ast.Module) -> None:
        self.tree = tree
        self.stack: List[str] = []
        #: innermost enclosing function node (module tree at top level)
        self.scopes: List[ast.AST] = [tree]
        self.found: List[Tuple[ast.ExceptHandler, str]] = []

    def _visit_scope(self, node: ast.AST, name: str, is_func: bool) -> None:
        self.stack.append(name)
        if is_func:
            self.scopes.append(node)
        self.generic_visit(node)
        if is_func:
            self.scopes.pop()
        self.stack.pop()

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._visit_scope(node, node.name, True)

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        self._visit_scope(node, node.name, True)

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self._visit_scope(node, node.name, False)

    def _check_try(self, node) -> None:
        for handler in node.handlers:
            if not _is_broad(handler) or _handles_failure(handler):
                continue
            if _best_effort_release(node, handler):
                continue
            if _fallback_binding(node, handler):
                continue
            if _releases_then_falls_through(self.scopes[-1], handler):
                continue
            self.found.append((handler, ".".join(self.stack)))
        self.generic_visit(node)

    def visit_Try(self, node: ast.Try) -> None:
        self._check_try(node)

    if hasattr(ast, "TryStar"):  # pragma: no branch - version-dependent
        def visit_TryStar(self, node) -> None:  # noqa: N802 - ast API
            self._check_try(node)


@register(
    "swallowed-exception",
    "broad except handlers that neither re-raise, log, nor record the failure",
)
def check(project: Project) -> Iterator[Finding]:
    for mod in project.modules:
        visitor = _Visitor(mod.tree)
        visitor.visit(mod.tree)
        for handler, symbol in visitor.found:
            what = "bare except" if handler.type is None else "broad except"
            yield Finding(
                "swallowed-exception",
                mod.relpath,
                handler.lineno,
                handler.col_offset,
                f"{what} swallows the error: the handler neither re-raises, "
                f"logs, nor records the exception — a failure here vanishes "
                f"without a trace; narrow the except, handle the failure, or "
                f"suppress with a reason",
                symbol=symbol,
            )
