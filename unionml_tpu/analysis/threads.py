"""Thread-role inference for graftlint v4: who can execute each function.

The serving stack is concurrent by construction — a batcher worker thread, a
supervisor watchdog, a backend loop thread, HTTP handler threads, and
subscriber callbacks fired across all of them — but the v2/v3 rule families
reason about locks and lifetimes without knowing WHICH threads reach a
function. This pass closes that gap: it discovers thread entry points,
propagates *roles* through the resolved call graph, and hands
:mod:`unionml_tpu.analysis.rules_races` the per-function role sets its
lock-set analysis intersects.

**Role vocabulary.**

- ``thread:<name>`` — the body of ``threading.Thread(target=f, name="<name>")``
  (falling back to the target's qualname when the name is not a literal), and
  ``threading.Timer(t, f)`` bodies.
- ``pool:<qualname>`` — a callable handed to ``executor.submit(f, ...)``; each
  submitted target is its own role (two different pooled tasks can interleave;
  a pooled task racing *itself* is out of static reach and documented as such).
- ``api`` — the ambient caller's thread. Every non-traced function with no
  resolved in-project caller that is not itself a thread/pool/callback target
  seeds this role: module entry points, FastAPI endpoints (sync endpoints run
  on the server threadpool, one handler thread per in-flight request), test
  bodies, and public methods the graph cannot see callers for. They all share
  ONE role — the analysis deliberately under-approximates api-side
  concurrency and leans on the explicit thread roles for the second role a
  race needs.

Roles flow down resolved call edges (caller's roles reach every callee) and
across **callback-registration edges**: a method that appends its callable
parameter into instance state (``self._subscribers.append(callback)``) is a
*registration method*; methods of the same class that invoke elements of that
attribute (``for cb in list(self._subscribers): cb(...)``) are its *firing
methods*; any callable passed to a resolved call of the registration method
inherits the firing methods' roles — the supervisor-subscriber protocol,
statically. Lambdas register the functions their bodies call.

Every (function, role) pair keeps a witness chain from the role's entry point
so findings can say *how* a thread reaches the access, not just that it does.
Best-effort like the rest of graftlint: unresolvable targets drop out, and a
function with an empty role set is simply invisible to the race rules.
"""

import ast
from typing import Dict, List, Optional, Set, Tuple

from unionml_tpu.analysis.callgraph import CallGraph, FunctionInfo, ModuleIndex, dotted
from unionml_tpu.analysis.dataflow import own_nodes, resolved_edges

#: (module, qualname) — one function's identity, as in the call graph
FnKey = Tuple[str, str]

#: container-mutating method names a registration method may use to store its
#: callable parameter
_STORE_METHODS = {"append", "add", "appendleft", "insert"}

#: iterable-wrapping callables a firing loop may apply to the registry
#: (``for cb in list(self._subscribers)``)
_ITER_WRAPPERS = {"list", "tuple", "sorted", "reversed", "set"}


def _self_attr_of(node: ast.AST) -> Optional[str]:
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


class Registry:
    """One callback registry: ``self.<attr>`` filled by registration methods
    and invoked by firing methods of the same class."""

    def __init__(self, module: str, cls: str, attr: str) -> None:
        self.module = module
        self.cls = cls
        self.attr = attr
        self.register_methods: List[FunctionInfo] = []
        #: (firing function, the ``cb(...)`` Call node)
        self.fire_sites: List[Tuple[FunctionInfo, ast.Call]] = []
        #: scanned callables observed being registered
        self.registered: List[FunctionInfo] = []

    @property
    def key(self) -> Tuple[str, str, str]:
        return (self.module, self.cls, self.attr)


class ThreadModel:
    """Per-function thread-role sets with entry-point witnesses."""

    def __init__(self, graph: CallGraph) -> None:
        self.graph = graph
        self.roles: Dict[FnKey, Set[str]] = {}
        #: (fn key, role) -> qualname chain from the role's entry point
        self.witness: Dict[Tuple[FnKey, str], Tuple[str, ...]] = {}
        #: functions that are thread/pool/callback targets (never api roots)
        self.entry_targets: Set[FnKey] = set()
        self.registries: Dict[Tuple[str, str, str], Registry] = {}
        #: extra role-flow edges beyond the call graph (firing fn -> callback)
        self._callback_edges: List[Tuple[FnKey, FnKey]] = []
        self._collect_registries()
        self._collect_entries()
        self._seed_ambient()
        self._propagate()

    # ------------------------------------------------------------- entry points

    def _collect_entries(self) -> None:
        seeds: List[Tuple[FunctionInfo, str]] = []
        for idx in self.graph.indexes:
            for fn in idx.functions.values():
                for node in own_nodes(fn.node):
                    if not isinstance(node, ast.Call):
                        continue
                    target, role = self._thread_entry(node, idx, fn)
                    if target is None:
                        target, role = self._pool_entry(node, idx, fn)
                    if target is not None:
                        seeds.append((target, role))
                        self.entry_targets.add(target.key)
        # callback targets: arguments of registration-method calls. Resolved
        # edges carry most sites; an unresolved receiver (``sup.subscribe(...)``
        # where ``sup`` came out of a zip/tuple unpacking the per-function type
        # tracking cannot see) falls back to the bare method name when exactly
        # one FIRING registry tree-wide registers under that name.
        reg_by_key: Dict[FnKey, List[Registry]] = {}
        reg_by_name: Dict[str, List[Registry]] = {}
        for reg in self.registries.values():
            for m in reg.register_methods:
                reg_by_key.setdefault(m.key, []).append(reg)
                if reg.fire_sites:
                    lst = reg_by_name.setdefault(m.node.name, [])
                    if reg not in lst:
                        lst.append(reg)
        for idx in self.graph.indexes:
            for fn in idx.functions.values():
                resolved = {
                    id(call): callee for callee, call in resolved_edges(self.graph, fn)
                }
                for _cands, call in fn.calls:
                    if not call.args:
                        continue
                    callee = resolved.get(id(call))
                    if callee is not None:
                        regs = reg_by_key.get(callee.key, [])
                    elif isinstance(call.func, ast.Attribute):
                        regs = reg_by_name.get(call.func.attr, [])
                        if len(regs) != 1:
                            regs = []
                    else:
                        regs = []
                    if not regs:
                        continue
                    for cb in self._callables_of(call.args[0], idx, fn):
                        for reg in regs:
                            reg.registered.append(cb)
                        self.entry_targets.add(cb.key)
        for reg in self.registries.values():
            for fire_fn, _call in reg.fire_sites:
                for cb in reg.registered:
                    self._callback_edges.append((fire_fn.key, cb.key))
        for target, role in seeds:
            self.roles.setdefault(target.key, set()).add(role)
            self.witness.setdefault((target.key, role), (target.qualname,))

    def _thread_entry(
        self, call: ast.Call, idx: ModuleIndex, fn: FunctionInfo
    ) -> Tuple[Optional[FunctionInfo], str]:
        """(target, role) for ``threading.Thread(target=..., name=...)`` and
        ``threading.Timer(interval, f)`` constructions."""
        name = dotted(call.func) or ""
        leaf = name.rsplit(".", 1)[-1]
        if leaf not in ("Thread", "Timer"):
            return None, ""
        root = name.split(".", 1)[0]
        if leaf != root and idx.imports.get(root, root) != "threading":
            return None, ""
        target_expr: Optional[ast.AST] = None
        if leaf == "Thread":
            for kw in call.keywords:
                if kw.arg == "target":
                    target_expr = kw.value
        elif len(call.args) >= 2:  # Timer(interval, f)
            target_expr = call.args[1]
        if target_expr is None:
            return None, ""
        target = self._resolve_callable(target_expr, idx, fn)
        if target is None:
            return None, ""
        thread_name = next(
            (
                kw.value.value
                for kw in call.keywords
                if kw.arg == "name"
                and isinstance(kw.value, ast.Constant)
                and isinstance(kw.value.value, str)
            ),
            target.qualname,
        )
        return target, f"thread:{thread_name}"

    def _pool_entry(
        self, call: ast.Call, idx: ModuleIndex, fn: FunctionInfo
    ) -> Tuple[Optional[FunctionInfo], str]:
        """(target, role) for ``executor.submit(f, ...)`` — only when the first
        argument resolves to a scanned function (``scheduler.submit(ticket)``
        and friends fall out naturally: a ticket is not a callable)."""
        if not (
            isinstance(call.func, ast.Attribute)
            and call.func.attr == "submit"
            and call.args
        ):
            return None, ""
        target = self._resolve_callable(call.args[0], idx, fn, calls_only=False)
        if target is None:
            return None, ""
        return target, f"pool:{target.qualname}"

    def _resolve_callable(
        self,
        expr: ast.AST,
        idx: ModuleIndex,
        fn: FunctionInfo,
        *,
        calls_only: bool = True,
    ) -> Optional[FunctionInfo]:
        """The scanned function a callable expression denotes: ``self.m``, a
        lexically visible name, or ``x.m`` through recorded instance types."""
        attr = _self_attr_of(expr)
        if attr is not None and fn.class_name is not None:
            return self.graph.by_key.get((idx.name, f"{fn.class_name}.{attr}"))
        if isinstance(expr, ast.Name):
            scope = fn.qualname.split(".")
            for i in range(len(scope), -1, -1):
                cand = idx.functions.get(".".join(scope[:i] + [expr.id]))
                if cand is not None:
                    return cand
        if isinstance(expr, ast.Attribute) and isinstance(expr.value, ast.Name):
            key = fn.instance_types.get(expr.value.id)
            if key is not None:
                return self.graph.by_key.get((key[0], f"{key[1]}.{expr.attr}"))
        return None

    def _callables_of(
        self, expr: ast.AST, idx: ModuleIndex, fn: FunctionInfo
    ) -> List[FunctionInfo]:
        """Scanned functions a registration argument hands over — a direct
        callable reference, or (for a lambda) every scanned function its body
        calls: ``subscribe(lambda old, new: self._on_state(old, new))``
        registers ``_on_state`` for role purposes."""
        direct = self._resolve_callable(expr, idx, fn)
        if direct is not None:
            return [direct]
        if isinstance(expr, ast.Lambda):
            out = []
            call_ids = {id(node) for node in ast.walk(expr) if isinstance(node, ast.Call)}
            for callee, call in resolved_edges(self.graph, fn):
                if id(call) in call_ids:
                    out.append(callee)
            return out
        return []

    # --------------------------------------------------------------- registries

    def _collect_registries(self) -> None:
        for idx in self.graph.indexes:
            for cls_name in idx.classes:
                self._collect_class_registries(idx, cls_name)

    def _collect_class_registries(self, idx: ModuleIndex, cls_name: str) -> None:
        methods = [
            fn
            for fn in idx.functions.values()
            if fn.class_name == cls_name
            and fn.qualname == f"{cls_name}.{fn.node.name}"
        ]
        # registration methods: a callable PARAMETER stored into self.<attr>
        for fn in methods:
            params = {a.arg for a in fn.node.args.args if a.arg != "self"}
            if not params:
                continue
            for node in own_nodes(fn.node):
                attr = None
                if (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _STORE_METHODS
                    and node.args
                    and isinstance(node.args[0], ast.Name)
                    and node.args[0].id in params
                ):
                    attr = _self_attr_of(node.func.value)
                elif (
                    isinstance(node, ast.Assign)
                    and isinstance(node.value, ast.Name)
                    and node.value.id in params
                    and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Subscript)
                ):
                    attr = _self_attr_of(node.targets[0].value)
                if attr is None:
                    continue
                reg = self.registries.setdefault(
                    (idx.name, cls_name, attr), Registry(idx.name, cls_name, attr)
                )
                if fn not in reg.register_methods:
                    reg.register_methods.append(fn)
        if not any(k[0] == idx.name and k[1] == cls_name for k in self.registries):
            return
        # firing methods: invoke elements of the registry attribute
        for fn in methods:
            for attr, call in _fire_sites(fn):
                reg = self.registries.get((idx.name, cls_name, attr))
                if reg is not None:
                    reg.fire_sites.append((fn, call))

    # -------------------------------------------------------------- propagation

    def _seed_ambient(self) -> None:
        """Seed the ambient ``api`` role at every plausible external surface."""
        called: Set[FnKey] = set()
        for fn in self.graph.by_key.values():
            for callee, _call in resolved_edges(self.graph, fn):
                if callee.key != fn.key:
                    called.add(callee.key)
        for idx in self.graph.indexes:
            for fn in idx.functions.values():
                name = fn.qualname.rsplit(".", 1)[-1]
                parent = fn.qualname.rsplit(".", 1)[0] if "." in fn.qualname else ""
                if (
                    fn.key in called
                    or fn.key in self.entry_targets
                    or fn.traced
                    or (name.startswith("__") and name.endswith("__"))
                    or (parent and parent in idx.functions)  # nested def
                ):
                    continue
                self.roles.setdefault(fn.key, set()).add("api")
                self.witness.setdefault((fn.key, "api"), (fn.qualname,))

    def _propagate(self) -> None:
        edges: Dict[FnKey, List[FnKey]] = {}
        for fn in self.graph.by_key.values():
            for callee, _call in resolved_edges(self.graph, fn):
                if callee.key != fn.key:
                    edges.setdefault(fn.key, []).append(callee.key)
        for src, dst in self._callback_edges:
            if src != dst:
                edges.setdefault(src, []).append(dst)
        frontier = list(self.roles)
        while frontier:
            src = frontier.pop()
            src_roles = self.roles.get(src, ())
            for dst in edges.get(src, ()):
                have = self.roles.setdefault(dst, set())
                grew = False
                for role in src_roles:
                    if role not in have:
                        have.add(role)
                        chain = self.witness.get((src, role), ())
                        if len(chain) < 8:
                            self.witness[(dst, role)] = chain + (dst[1],)
                        else:
                            self.witness[(dst, role)] = chain
                        grew = True
                if grew:
                    frontier.append(dst)

    # ------------------------------------------------------------------ queries

    def roles_of(self, fn: FunctionInfo) -> Set[str]:
        return self.roles.get(fn.key, set())

    def witness_of(self, fn: FunctionInfo, role: str) -> str:
        """``role (via a -> b -> c)`` — the entry chain that carries ``role``
        to ``fn`` (just the role name when the chain is trivial)."""
        chain = self.witness.get((fn.key, role), ())
        if len(chain) > 1:
            return f"{role} (via {' -> '.join(chain)})"
        return role


def _fire_sites(fn: FunctionInfo) -> List[Tuple[str, ast.Call]]:
    """(registry attr, Call) for invocations of registry elements in ``fn``:
    ``for cb in self._subs: cb(...)`` (through list()/tuple()/sorted() wraps)
    and direct ``self._subs[k](...)`` subscript calls."""
    out: List[Tuple[str, ast.Call]] = []
    loop_vars: Dict[str, str] = {}  # loop variable -> registry attr
    for node in own_nodes(fn.node):
        if isinstance(node, (ast.For, ast.AsyncFor)) and isinstance(node.target, ast.Name):
            attr = _registry_iter_attr(node.iter)
            if attr is not None:
                loop_vars[node.target.id] = attr
    for node in own_nodes(fn.node):
        if not isinstance(node, ast.Call):
            continue
        if isinstance(node.func, ast.Name) and node.func.id in loop_vars:
            out.append((loop_vars[node.func.id], node))
        elif isinstance(node.func, ast.Subscript):
            attr = _self_attr_of(node.func.value)
            if attr is not None:
                out.append((attr, node))
    return out


def _registry_iter_attr(iter_expr: ast.AST) -> Optional[str]:
    """The ``self.<attr>`` a firing loop iterates, unwrapping ``list(...)``-
    style copies and ``.values()`` views."""
    expr = iter_expr
    if (
        isinstance(expr, ast.Call)
        and isinstance(expr.func, ast.Name)
        and expr.func.id in _ITER_WRAPPERS
        and expr.args
    ):
        expr = expr.args[0]
    if (
        isinstance(expr, ast.Call)
        and isinstance(expr.func, ast.Attribute)
        and expr.func.attr in ("values", "copy")
        and not expr.args
    ):
        expr = expr.func.value
    return _self_attr_of(expr)


def thread_model(graph: CallGraph) -> ThreadModel:
    """One :class:`ThreadModel` per call graph, cached like the dataflow
    summaries — the four rules_races families all read it."""
    cached = getattr(graph, "_graftlint_threads", None)
    if cached is None:
        cached = ThreadModel(graph)
        graph._graftlint_threads = cached
    return cached
