"""Rule ``sharding``: PartitionSpec axis names and NamedSharding mesh hygiene.

The mesh axis vocabulary is collected from the scanned tree itself, so the rule
follows the code instead of a hard-coded list:

- module-level ``*_AXIS = "name"`` string constants (``parallel/mesh.py`` owns
  the canonical four: data / fsdp / tensor / sequence);
- literal dict keys passed to ``make_mesh`` / ``MeshSpec.from_dict`` /
  ``make_hybrid_mesh`` and literal ``Mesh(..., ("a", "b"))`` axis-name tuples.

Checks:

- **unknown axis** — a string literal inside ``PartitionSpec(...)`` / ``P(...)``
  that names an axis no mesh in the tree declares. A typo here does not error
  at runtime on a mesh without the axis — GSPMD just replicates, silently
  giving up the sharding the spec promised.
- **foreign mesh** — ``NamedSharding(X, ...)`` where the enclosing function has
  mesh-like bindings (a ``mesh`` parameter/local or ``*_mesh`` names) and ``X``
  is none of them: the sharding is built off a different mesh than the
  enclosing context, which breaks the single-mesh invariant that every array
  in one program family must share (mixing meshes forces XLA resharding or
  fails downstream where the arrays meet).
"""

import ast
from typing import Iterator, List, Set

from unionml_tpu.analysis.callgraph import dotted
from unionml_tpu.analysis.core import Finding, Project, register

_MESH_BUILDERS = {"make_mesh", "make_hybrid_mesh", "from_dict", "Mesh"}
_SPEC_NAMES = {"PartitionSpec", "P"}


def _axis_vocabulary(project: Project) -> Set[str]:
    vocab: Set[str] = set()
    for idx in project.graph.indexes:
        for name, value in idx.str_constants.items():
            if name.endswith("_AXIS"):
                vocab.add(value)
        for node in ast.walk(idx.source.tree):
            if not isinstance(node, ast.Call):
                continue
            leaf = (dotted(node.func) or "").rsplit(".", 1)[-1]
            if leaf not in _MESH_BUILDERS:
                continue
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                if isinstance(arg, ast.Dict):
                    for key in arg.keys:
                        if isinstance(key, ast.Constant) and isinstance(key.value, str):
                            vocab.add(key.value)
                elif isinstance(arg, (ast.Tuple, ast.List)):
                    for el in arg.elts:
                        if isinstance(el, ast.Constant) and isinstance(el.value, str):
                            vocab.add(el.value)
    return vocab


def _spec_axis_literals(call: ast.Call) -> List[ast.Constant]:
    out: List[ast.Constant] = []
    for arg in list(call.args) + [kw.value for kw in call.keywords]:
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            out.append(arg)
        elif isinstance(arg, (ast.Tuple, ast.List)):
            out.extend(
                el for el in arg.elts
                if isinstance(el, ast.Constant) and isinstance(el.value, str)
            )
    return out


def _mesh_like_names(fn_node: ast.AST) -> Set[str]:
    names: Set[str] = set()
    args = fn_node.args
    for a in list(args.args) + list(args.kwonlyargs) + list(getattr(args, "posonlyargs", [])):
        if a.arg == "mesh" or a.arg.endswith("_mesh"):
            names.add(a.arg)
    for node in ast.walk(fn_node):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and (t.id == "mesh" or t.id.endswith("_mesh")):
                    names.add(t.id)
                elif isinstance(t, ast.Name) and isinstance(node.value, ast.Call):
                    leaf = (dotted(node.value.func) or "").rsplit(".", 1)[-1]
                    if leaf in ("make_mesh", "make_hybrid_mesh", "Mesh", "build"):
                        names.add(t.id)
        elif isinstance(node, ast.withitem):
            ctx = node.context_expr
            if isinstance(ctx, ast.Call):
                leaf = (dotted(ctx.func) or "").rsplit(".", 1)[-1]
                if leaf in ("Mesh", "make_mesh") and isinstance(node.optional_vars, ast.Name):
                    names.add(node.optional_vars.id)
    return names


@register("sharding", "PartitionSpec axes checked against declared mesh axes; mesh-variable hygiene")
def check(project: Project) -> Iterator[Finding]:
    vocab = _axis_vocabulary(project)
    for idx in project.graph.indexes:
        relpath = idx.source.relpath
        for fn in idx.functions.values():
            mesh_names = None  # computed lazily per function
            for node in ast.walk(fn.node):
                if not isinstance(node, ast.Call):
                    continue
                leaf = (dotted(node.func) or "").rsplit(".", 1)[-1]
                if leaf in _SPEC_NAMES:
                    for lit in _spec_axis_literals(node):
                        if vocab and lit.value not in vocab:
                            yield Finding(
                                "sharding", relpath, lit.lineno, lit.col_offset,
                                f"PartitionSpec axis '{lit.value}' is not declared by any "
                                f"mesh in the tree (known axes: {', '.join(sorted(vocab))}); "
                                "a typo'd axis silently replicates instead of sharding",
                                symbol=fn.qualname,
                            )
                elif leaf == "NamedSharding" and node.args:
                    first = node.args[0]
                    if not isinstance(first, ast.Name):
                        continue  # self._mesh / call results: out of static reach
                    if mesh_names is None:
                        mesh_names = _mesh_like_names(fn.node)
                    if mesh_names and first.id not in mesh_names \
                            and not first.id.endswith("mesh"):
                        yield Finding(
                            "sharding", relpath, first.lineno, first.col_offset,
                            f"NamedSharding built off '{first.id}' while the enclosing "
                            f"context binds mesh variable(s) {', '.join(sorted(mesh_names))}; "
                            "mixing meshes in one program family forces resharding or "
                            "fails where the arrays meet",
                            symbol=fn.qualname,
                        )
