"""Intra-procedural control-flow graphs with exception edges (graftlint v3).

The v2 rule families walk statements linearly; that cannot answer the question
the resource rules ask — "is there an *execution path* from this acquire to a
function exit that skips the release?". This module builds a per-function CFG
whose edges make every such path explicit, including the ones Python hides:

- **Exception edges.** Every content block carries exactly one ``except`` edge
  to the innermost construct that would see an exception raised there: the
  enclosing ``try``'s handler-dispatch block, a ``finally`` copy, or the
  function's exceptional exit (``rexit``). The edge is *explicit* when the
  block's statement is a ``raise`` (it WILL fire) and *implicit* otherwise (it
  MAY fire — a call or subscript could throw). Rules choose which implicit
  edges to believe; ``assert`` is deliberately implicit so test files stay
  quiet.
- **Handler dispatch.** A ``try`` with handlers gets a synthetic ``dispatch``
  block: ``handler`` edges fan out to each handler's entry, and a
  ``propagate`` edge continues to the outer context for the unmatched case —
  unless some handler is broad (bare / ``Exception`` / ``BaseException``),
  which provably terminates propagation.
- **``finally`` duplication.** A ``finally`` body runs on normal completion,
  on every ``return``/``break``/``continue`` that jumps over it, and on
  exception propagation — each with a different continuation. The body is
  built once per *continuation* (blocks duplicated, AST nodes shared) and
  memoized, so ``return`` inside nested ``try/finally`` chains the copies
  innermost-first exactly as the interpreter does.
- **Loops.** ``while``/``for`` headers are branch blocks (``true`` enters the
  body, ``false`` leaves); the body's fall-through returns on a ``back`` edge,
  which is how a loop-carried acquire (re-acquired before the previous
  iteration released) becomes plain graph reachability.
- **Granularity.** One simple statement per block. Compound headers contribute
  ``(node, role)`` items: ``("test")`` for ``if``/``while`` conditions,
  ``("for")`` for loop headers (binds the target, iterates the iterable),
  ``("with")`` for ``with`` headers, ``("handler")`` for ``except`` clauses.
  Nested ``def``/``class`` statements are opaque single items (analyzed under
  their own frame); ``match`` is opaque too.
- **Regions.** Every block records the tuple of ``except`` handlers lexically
  enclosing it, so the swallowed-exception rule can ask "does this handler
  fall through into code outside itself?" without re-walking the AST.

``with`` is modeled without ``__exit__`` edges (context managers release their
own resource; the resource rules skip acquires in ``withitem.context_expr``
entirely). The graph is best-effort in the graftlint tradition: anything it
cannot model precisely errs toward *fewer* paths, so rules stay silent rather
than guessing.
"""

import ast
from typing import Callable, Dict, List, Optional, Tuple

__all__ = ["Block", "Edge", "CFG", "build_cfg", "reachable", "path_to"]

#: edge kinds every rule follows unconditionally (``except`` is the only
#: conditional kind: explicit edges fire for sure, implicit ones only may)
ALWAYS_KINDS = frozenset({"flow", "true", "false", "back", "handler", "propagate", "return"})


class Edge:
    """One directed CFG edge. ``kind`` ∈ {flow, true, false, back, handler,
    propagate, return, except}; ``explicit`` is meaningful for ``except`` only
    (True: the source block is a ``raise`` statement)."""

    __slots__ = ("dst", "kind", "explicit")

    def __init__(self, dst: int, kind: str, explicit: bool = False) -> None:
        self.dst = dst
        self.kind = kind
        self.explicit = explicit

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        mark = "!" if self.explicit else ""
        return f"-{self.kind}{mark}->{self.dst}"


class Block:
    """One CFG node: at most one simple statement (or one compound header).

    ``kind`` ∈ {entry, exit, rexit, normal, branch, join, dispatch, handler,
    finally}; ``items`` is a list of ``(ast node, role)`` pairs with role ∈
    {stmt, test, for, with, handler}; ``regions`` the enclosing
    ``ast.ExceptHandler`` nodes, innermost last.
    """

    __slots__ = ("id", "kind", "items", "edges", "regions")

    def __init__(self, bid: int, kind: str, regions: Tuple[ast.ExceptHandler, ...]) -> None:
        self.id = bid
        self.kind = kind
        self.items: List[Tuple[ast.AST, str]] = []
        self.edges: List[Edge] = []
        self.regions = regions

    @property
    def line(self) -> int:
        """First source line of the block's content (0 for synthetic blocks)."""
        for node, _role in self.items:
            ln = getattr(node, "lineno", None)
            if ln is not None:
                return ln
        return 0

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"<block {self.id} {self.kind} L{self.line} {self.edges}>"


class CFG:
    """A function's (or module's) control-flow graph."""

    def __init__(self, func: ast.AST) -> None:
        self.func = func
        self.blocks: Dict[int, Block] = {}
        self._next = 0
        self.entry = self._new("entry", ()).id
        self.exit = self._new("exit", ()).id
        self.rexit = self._new("rexit", ()).id
        self._preds: Optional[Dict[int, List[Tuple[int, Edge]]]] = None

    def _new(self, kind: str, regions: Tuple[ast.ExceptHandler, ...]) -> Block:
        b = Block(self._next, kind, regions)
        self._next += 1
        self.blocks[b.id] = b
        return b

    def preds(self) -> Dict[int, List[Tuple[int, Edge]]]:
        """Reverse adjacency: block id -> [(source block id, edge)]."""
        if self._preds is None:
            p: Dict[int, List[Tuple[int, Edge]]] = {bid: [] for bid in self.blocks}
            for b in self.blocks.values():
                for e in b.edges:
                    p[e.dst].append((b.id, e))
            self._preds = p
        return self._preds


def _handler_is_broad(handler: ast.ExceptHandler) -> bool:
    """Bare ``except`` / ``Exception`` / ``BaseException`` (incl. in tuples):
    provably terminates propagation, so the dispatch gets no outward edge."""
    t = handler.type
    if t is None:
        return True
    for n in t.elts if isinstance(t, ast.Tuple) else [t]:
        leaf = n.attr if isinstance(n, ast.Attribute) else getattr(n, "id", "")
        if leaf in ("Exception", "BaseException"):
            return True
    return False


_LOOPS = (ast.For, ast.AsyncFor, ast.While)
_TRYS = (ast.Try,) + ((ast.TryStar,) if hasattr(ast, "TryStar") else ())


class _Builder:
    """Sequential CFG construction with a dangling-edge cursor.

    ``frames`` is the stack of enclosing constructs that reroute nonlocal
    exits (raise / return / break / continue): ``("trybody", try_node,
    dispatch_block_or_None, snapshot)`` while building a ``try`` body,
    ``("tryrest", try_node, snapshot)`` in its handlers/else (where a raise
    runs the ``finally`` and propagates OUTWARD, not into this try's own
    handlers), and ``("loop", after_id, header_id)``. ``snapshot`` captures
    the (frames, regions) surrounding the try — ``finally`` copies are built
    under it, because code in a ``finally`` raises into the try's *outer*
    context.
    """

    def __init__(self, func: ast.AST) -> None:
        self.cfg = CFG(func)
        self.frames: List[tuple] = []
        self.regions: Tuple[ast.ExceptHandler, ...] = ()
        #: (source block, edge kind, explicit) awaiting the next block
        self.dangling: List[Tuple[int, str, bool]] = []
        self._fin_memo: Dict[Tuple[int, int], int] = {}

    def build(self) -> CFG:
        self.dangling = [(self.cfg.entry, "flow", False)]
        self._build_stmts(self.cfg.func.body)
        self._connect(self.cfg.exit, "flow")
        return self.cfg

    # ------------------------------------------------------------------ cursor

    def _connect(self, target: int, kind: Optional[str] = None) -> None:
        for src, k, ex in self.dangling:
            self.cfg.blocks[src].edges.append(Edge(target, kind or k, ex))
        self.dangling = []

    def _start_block(self, kind: str = "normal") -> Block:
        b = self.cfg._new(kind, self.regions)
        self._connect(b.id)
        return b

    # ----------------------------------------------------------------- routing

    def _route(self, kind: str) -> int:
        """Target block for a nonlocal exit of ``kind`` from here, chaining
        ``finally`` copies innermost-first like the interpreter."""
        fins: List[tuple] = []
        base: Optional[int] = None
        for frame in reversed(self.frames):
            tag = frame[0]
            if tag == "trybody":
                _, tnode, dispatch, snap = frame
                if kind == "raise" and dispatch is not None:
                    base = dispatch.id  # handlers first; finally runs later
                    break
                if tnode.finalbody:
                    fins.append((tnode, snap))
            elif tag == "tryrest":
                _, tnode, snap = frame
                if tnode.finalbody:
                    fins.append((tnode, snap))
            elif tag == "loop" and kind in ("break", "continue"):
                base = frame[1] if kind == "break" else frame[2]
                break
        if base is None:
            base = self.cfg.rexit if kind == "raise" else self.cfg.exit
        for tnode, snap in reversed(fins):  # outermost copy built first
            base = self._finally_copy(tnode, base, snap)
        return base

    def _finally_copy(self, node: ast.AST, continuation: int, snapshot: tuple) -> int:
        """Blocks for ``node.finalbody`` ending in an edge to ``continuation``
        — one copy per continuation, memoized (AST nodes shared between
        copies). Built under the try's OUTER context: a raise inside the
        ``finally`` replaces the in-flight exception and propagates outward."""
        key = (id(node), continuation)
        got = self._fin_memo.get(key)
        if got is not None:
            return got
        saved = (self.frames, self.regions, self.dangling)
        self.frames, self.regions = list(snapshot[0]), snapshot[1]
        entry = self.cfg._new("finally", self.regions)
        self._fin_memo[key] = entry.id  # before building: recursion guard
        self.dangling = [(entry.id, "flow", False)]
        self._build_stmts(node.finalbody)
        self._connect(continuation)
        self.frames, self.regions, self.dangling = saved
        return entry.id

    # -------------------------------------------------------------- statements

    def _build_stmts(self, stmts) -> None:
        for stmt in stmts:
            self._build_stmt(stmt)

    def _build_stmt(self, stmt: ast.stmt) -> None:
        if isinstance(stmt, ast.If):
            self._build_if(stmt)
        elif isinstance(stmt, _LOOPS):
            self._build_loop(stmt)
        elif isinstance(stmt, _TRYS):
            self._build_try(stmt)
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            self._build_with(stmt)
        else:
            self._build_simple(stmt)

    def _build_simple(self, stmt: ast.stmt) -> None:
        b = self._start_block()
        b.items.append((stmt, "stmt"))
        b.edges.append(Edge(self._route("raise"), "except", isinstance(stmt, ast.Raise)))
        if isinstance(stmt, ast.Raise):
            return  # the except edge is the only successor
        if isinstance(stmt, ast.Return):
            b.edges.append(Edge(self._route("return"), "return"))
            return
        if isinstance(stmt, ast.Break):
            b.edges.append(Edge(self._route("break"), "flow"))
            return
        if isinstance(stmt, ast.Continue):
            b.edges.append(Edge(self._route("continue"), "flow"))
            return
        self.dangling = [(b.id, "flow", False)]

    def _build_if(self, node: ast.If) -> None:
        b = self._start_block("branch")
        b.items.append((node.test, "test"))
        b.edges.append(Edge(self._route("raise"), "except", False))
        self.dangling = [(b.id, "true", False)]
        self._build_stmts(node.body)
        then_d = self.dangling
        self.dangling = [(b.id, "false", False)]
        self._build_stmts(node.orelse)
        self.dangling = self.dangling + then_d

    def _build_loop(self, node) -> None:
        header = self._start_block("branch")
        if isinstance(node, ast.While):
            header.items.append((node.test, "test"))
        else:
            header.items.append((node, "for"))
        header.edges.append(Edge(self._route("raise"), "except", False))
        after = self.cfg._new("join", self.regions)
        self.frames.append(("loop", after.id, header.id))
        self.dangling = [(header.id, "true", False)]
        self._build_stmts(node.body)
        self._connect(header.id, "back")
        self.frames.pop()
        self.dangling = [(header.id, "false", False)]
        self._build_stmts(node.orelse)  # runs on exhaustion, skipped by break
        self._connect(after.id)
        self.dangling = [(after.id, "flow", False)]

    def _build_with(self, node) -> None:
        b = self._start_block()
        b.items.append((node, "with"))
        b.edges.append(Edge(self._route("raise"), "except", False))
        self.dangling = [(b.id, "flow", False)]
        self._build_stmts(node.body)

    def _build_try(self, node) -> None:
        snapshot = (list(self.frames), self.regions)
        has_fin = bool(node.finalbody)
        dispatch = self.cfg._new("dispatch", self.regions) if node.handlers else None
        self.frames.append(("trybody", node, dispatch, snapshot))
        self._build_stmts(node.body)
        self.frames.pop()
        if node.orelse:
            # exceptions in ``else`` are NOT caught by this try's handlers
            self.frames.append(("tryrest", node, snapshot))
            self._build_stmts(node.orelse)
            self.frames.pop()
        body_d = self.dangling
        handler_d: List[Tuple[int, str, bool]] = []
        if dispatch is not None:
            for h in node.handlers:
                self.frames.append(("tryrest", node, snapshot))
                self.regions = self.regions + (h,)
                self.dangling = [(dispatch.id, "handler", False)]
                hb = self._start_block("handler")
                hb.items.append((h, "handler"))
                hb.edges.append(Edge(self._route("raise"), "except", False))
                self.dangling = [(hb.id, "flow", False)]
                self._build_stmts(h.body)
                handler_d.extend(self.dangling)
                self.dangling = []
                self.regions = self.regions[:-1]
                self.frames.pop()
            if not any(_handler_is_broad(h) for h in node.handlers):
                # unmatched exception: runs the finally, then propagates
                saved = (self.frames, self.regions)
                self.frames, self.regions = list(snapshot[0]), snapshot[1]
                target = self._route("raise")
                if has_fin:
                    target = self._finally_copy(node, target, snapshot)
                self.frames, self.regions = saved
                dispatch.edges.append(Edge(target, "propagate"))
        self.dangling = body_d + handler_d
        if has_fin:
            # the normal-completion finally is built inline (the canonical
            # copy); nonlocal exits got their own copies via _route
            saved = (self.frames, self.regions)
            self.frames, self.regions = list(snapshot[0]), snapshot[1]
            self._build_stmts(node.finalbody)
            self.frames, self.regions = saved


def build_cfg(func: ast.AST) -> CFG:
    """CFG for a ``FunctionDef`` / ``AsyncFunctionDef`` / ``Module`` (any node
    with a statement-list ``body``)."""
    return _Builder(func).build()


def reachable(
    cfg: CFG,
    start: int,
    *,
    follow: Callable[[Block, Edge], bool],
    stop: Optional[Callable[[Block], bool]] = None,
) -> Dict[int, Optional[int]]:
    """BFS parent map from ``start``. ``follow(block, edge)`` gates each edge;
    a block matching ``stop`` is visited but not expanded (its successors stay
    unreachable through it). ``start`` itself is always expanded."""
    parents: Dict[int, Optional[int]] = {start: None}
    frontier = [start]
    while frontier:
        bid = frontier.pop()
        block = cfg.blocks[bid]
        if stop is not None and bid != start and stop(block):
            continue
        for e in block.edges:
            if e.dst not in parents and follow(block, e):
                parents[e.dst] = bid
                frontier.append(e.dst)
    return parents


def path_to(parents: Dict[int, Optional[int]], target: int) -> List[int]:
    """Block-id path from the BFS start to ``target`` (inclusive)."""
    out: List[int] = []
    cur: Optional[int] = target
    while cur is not None:
        out.append(cur)
        cur = parents.get(cur)
    out.reverse()
    return out
