"""Project-wide AST index: functions, call edges, jit/shard_map trace roots.

Static call resolution is deliberately best-effort (a linter, not a compiler):

- ``f(...)`` resolves through the lexical scope chain — enclosing function's
  nested defs, then module-level defs, then imports into other scanned modules.
- ``self.m(...)`` resolves to the enclosing class's method ``m``.
- ``alias.f(...)`` resolves when ``alias`` imports a scanned module.
- anything else (callables from parameters, attributes of objects, returns of
  factories) is skipped — unresolvable edges drop out of the walk rather than
  producing noise.

Trace roots (functions whose bodies run under tracing) are discovered from:
``@jax.jit`` / ``@jit`` / ``@partial(jax.jit, ...)`` decorators, and first
arguments of ``jax.jit(f, ...)`` / ``shard_map(f, ...)`` / ``pjit(f, ...)``
calls. When a jit call's result is bound (``g = jax.jit(f)`` or
``self._g = jax.jit(f)``), the binding is recorded as a *jitted callable* with
its ``static_argnums`` / ``static_argnames`` / ``donate_argnums`` so call
sites can be checked.

Instance types: ``x = ClassName(...)`` (locals, lexically visible to nested
defs) and ``self.attr = ClassName(...)`` in ``__init__`` are recorded when
``ClassName`` is a scanned class — same module or imported from one — so
``x.m(...)`` and ``self.attr.m(...)`` resolve to ``ClassName.m`` across
modules. This is what lets the dataflow rules follow a lock acquisition or a
blocking call into another module's class.
"""

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

#: wrapper callables whose first argument becomes a traced body
_TRACING_WRAPPERS = {"jit", "shard_map", "pjit", "checkify", "grad", "value_and_grad", "vmap", "pmap"}
#: of those, the ones that produce a *compiled, cached* callable (retrace rule)
_JIT_WRAPPERS = {"jit", "pjit"}


def _const(node: ast.AST):
    """Literal value of a constant / tuple-of-constants node, else None."""
    if isinstance(node, ast.Constant):
        return node.value
    if isinstance(node, (ast.Tuple, ast.List)):
        vals = [_const(e) for e in node.elts]
        if all(v is not None for v in vals):
            return tuple(vals)
    return None


def dotted(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for a Name/Attribute chain, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


class FunctionInfo:
    """One function/method definition and its outgoing call edges."""

    def __init__(self, module: "ModuleIndex", qualname: str, node: ast.AST,
                 class_name: Optional[str]) -> None:
        self.module = module
        self.qualname = qualname
        self.node = node
        self.class_name = class_name
        self.traced = False  # body runs under jax tracing
        self.marker: Optional[str] = None  # "hot-path" | "off-path"
        self.is_async = isinstance(node, ast.AsyncFunctionDef)
        #: raw call sites: (callee key candidates, Call node)
        self.calls: List[Tuple[List[Tuple[str, str]], ast.Call]] = []
        #: local name -> (module, ClassName) for ``x = ClassName(...)`` bindings
        self.instance_types: Dict[str, Tuple[str, str]] = {}

    @property
    def key(self) -> Tuple[str, str]:
        return (self.module.name, self.qualname)

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"<fn {self.module.name}:{self.qualname}{' traced' if self.traced else ''}>"


class JitBinding:
    """A name bound to a compiled callable: ``g = jax.jit(f, static_...)``."""

    def __init__(self, name: str, target: Optional[FunctionInfo],
                 static_argnums: Tuple[int, ...], static_argnames: Tuple[str, ...],
                 node: ast.Call, donate_argnums: Tuple[int, ...] = (),
                 donate_configured: bool = False) -> None:
        self.name = name  # binding name ("g" or "self._g" normalized to "_g")
        self.target = target
        self.static_argnums = static_argnums
        self.static_argnames = static_argnames
        #: positional args whose buffers XLA may invalidate at each call
        self.donate_argnums = donate_argnums
        #: donate_argnums passed but not a literal: may donate, positions unknown
        self.donate_configured = donate_configured
        self.node = node
        #: observed literal values per static position across call sites
        self.call_sites: List[ast.Call] = []


class ModuleIndex(ast.NodeVisitor):
    """Per-module symbol table (functions, imports, aliases, jit bindings)."""

    def __init__(self, source) -> None:
        self.source = source
        self.name = source.name
        self.functions: Dict[str, FunctionInfo] = {}
        #: local name -> imported dotted target ("np" -> "numpy",
        #: "init_cache" -> "unionml_tpu.models.gpt.init_cache")
        self.imports: Dict[str, str] = {}
        self.jit_bindings: Dict[str, JitBinding] = {}
        #: string constants at module scope (axis-name vocabulary etc.)
        self.str_constants: Dict[str, str] = {}
        #: class name -> ClassDef node (instance-type resolution)
        self.classes: Dict[str, ast.ClassDef] = {}
        #: class name -> {attr: (module, ClassName)} for ``self.x = Cls(...)``
        #: bindings in ``__init__`` (cross-module method resolution)
        self.attr_types: Dict[str, Dict[str, Tuple[str, str]]] = {}
        self._scope: List[str] = []
        self._class: List[str] = []
        self._loops = 0
        #: jax.jit/partial(jax.jit) Call nodes seen inside loops (retrace rule)
        self.jit_in_loop: List[ast.Call] = []
        self.visit(source.tree)
        self._attach_markers()

    # ---------------------------------------------------------------- helpers

    def alias_of(self, root: str) -> Optional[str]:
        """The dotted import target a bare name refers to (None if local)."""
        return self.imports.get(root)

    def resolves_to(self, node: ast.AST, *targets: str) -> bool:
        """True when the call's func node denotes any of the dotted ``targets``
        (through import aliases: ``np.asarray`` -> ``numpy.asarray``)."""
        name = dotted(node)
        if name is None:
            return False
        root, _, rest = name.partition(".")
        expanded = name
        if root in self.imports:
            expanded = self.imports[root] + (("." + rest) if rest else "")
        return expanded in targets or name in targets

    def _qual(self, name: str) -> str:
        return ".".join(self._scope + [name]) if self._scope else name

    def _attach_markers(self) -> None:
        for line, marker in self.source.markers.items():
            for fn in self.functions.values():
                start = min(
                    [fn.node.lineno] + [d.lineno for d in fn.node.decorator_list]
                )
                if start <= line <= fn.node.body[0].lineno - 1 or line == fn.node.lineno:
                    fn.marker = marker
                    break

    # ---------------------------------------------------------------- imports

    def visit_Import(self, node: ast.Import) -> None:
        for a in node.names:
            self.imports[a.asname or a.name.split(".")[0]] = a.name

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        if node.module is None or node.level:
            return  # relative imports: out of scope for a best-effort graph
        for a in node.names:
            self.imports[a.asname or a.name] = f"{node.module}.{a.name}"

    # -------------------------------------------------------------- definitions

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        if not self._scope:
            self.classes[node.name] = node
        self._scope.append(node.name)
        self._class.append(node.name)
        self.generic_visit(node)
        self._class.pop()
        self._scope.pop()

    def _visit_function(self, node) -> None:
        qual = self._qual(node.name)
        info = FunctionInfo(self, qual, node, self._class[-1] if self._class else None)
        self.functions[qual] = info
        for dec in node.decorator_list:
            if self._is_jit_expr(dec):
                info.traced = True
                static_nums, static_names = self._static_info(dec)
                self.jit_bindings[qual] = JitBinding(
                    qual, info, static_nums, static_names,
                    dec if isinstance(dec, ast.Call) else node,
                    donate_argnums=self.donate_info(dec),
                    donate_configured=self.donate_configured(dec),
                )
        self._scope.append(node.name)
        self.generic_visit(node)
        self._scope.pop()

    visit_FunctionDef = _visit_function
    visit_AsyncFunctionDef = _visit_function

    # ------------------------------------------------------------- module consts

    def visit_Assign(self, node: ast.Assign) -> None:
        if not self._scope and len(node.targets) == 1 and isinstance(node.targets[0], ast.Name):
            if isinstance(node.value, ast.Constant) and isinstance(node.value.value, str):
                self.str_constants[node.targets[0].id] = node.value.value
        self._bind_jit_result(node)
        self._bind_instance_type(node)
        self.generic_visit(node)

    def _class_key_of(self, value: ast.AST) -> Optional[Tuple[str, str]]:
        """(module, ClassName) when ``value`` constructs a (possibly) scanned
        class — ``Cls(...)``, ``mod.Cls(...)``, or a conditional expression with
        such an arm. Liberal: non-class callees simply never resolve later."""
        if isinstance(value, ast.IfExp):
            return self._class_key_of(value.body) or self._class_key_of(value.orelse)
        if not isinstance(value, ast.Call):
            return None
        name = dotted(value.func)
        if name is None:
            return None
        root, _, rest = name.partition(".")
        if rest:  # mod.Cls(...): resolve the module alias
            target = self.imports.get(root)
            if target is not None:
                return (target, rest)
            return None
        if name in self.classes:
            return (self.name, name)
        target = self.imports.get(name)
        if target is not None and "." in target:
            mod, _, cls = target.rpartition(".")
            return (mod, cls)
        return None

    def _bind_instance_type(self, node: ast.Assign) -> None:
        """Record ``x = Cls(...)`` (function locals) and ``self.a = Cls(...)``
        (``__init__`` attrs) so method calls resolve across modules."""
        key = self._class_key_of(node.value)
        if key is None or len(node.targets) != 1:
            return
        target = node.targets[0]
        owner = self._enclosing_function()
        if isinstance(target, ast.Name) and owner is not None:
            owner.instance_types[target.id] = key
        elif (
            isinstance(target, ast.Attribute)
            and isinstance(target.value, ast.Name)
            and target.value.id == "self"
            and self._class
            and owner is not None
            and owner.qualname.endswith("__init__")
        ):
            self.attr_types.setdefault(self._class[-1], {})[target.attr] = key

    # ------------------------------------------------------------------- loops

    def visit_For(self, node):  # noqa: N802 - ast API
        self._loops += 1
        self.generic_visit(node)
        self._loops -= 1

    visit_AsyncFor = visit_For

    def visit_While(self, node: ast.While) -> None:
        self._loops += 1
        self.generic_visit(node)
        self._loops -= 1

    # ------------------------------------------------------------------- calls

    def visit_Call(self, node: ast.Call) -> None:
        wrapper = self._tracing_wrapper_name(node.func)
        if wrapper:
            self._register_traced_arg(node)
            if wrapper in _JIT_WRAPPERS and self._loops:
                self.jit_in_loop.append(node)
        if self._scope:
            owner = self._enclosing_function()
            if owner is not None:
                owner.calls.append((self._callee_candidates(node.func), node))
        self.generic_visit(node)

    def _enclosing_function(self) -> Optional[FunctionInfo]:
        # innermost enclosing def in the qualname chain
        for i in range(len(self._scope), 0, -1):
            info = self.functions.get(".".join(self._scope[:i]))
            if info is not None:
                return info
        return None

    def _callee_candidates(self, func: ast.AST) -> List[Tuple[str, str]]:
        """(module, qualname) candidates for one call's callee."""
        out: List[Tuple[str, str]] = []
        if isinstance(func, ast.Name):
            # nested defs visible from the current scope, innermost first
            for i in range(len(self._scope), -1, -1):
                out.append((self.name, ".".join(self._scope[:i] + [func.id])))
            target = self.imports.get(func.id)
            if target and "." in target:
                mod, _, fn = target.rpartition(".")
                out.append((mod, fn))
        elif isinstance(func, ast.Attribute):
            base = func.value
            if isinstance(base, ast.Name) and base.id == "self" and self._class:
                out.append((self.name, f"{self._class[-1]}.{func.attr}"))
                # self.attr.m(...) is handled below; self.m(...) may also be an
                # attr holding an instance of a scanned class — not expressible
            elif isinstance(base, ast.Name):
                key = self._instance_type_of(base.id)
                if key is not None:
                    out.append((key[0], f"{key[1]}.{func.attr}"))
                if base.id in self.imports:
                    out.append((self.imports[base.id], func.attr))
            elif (
                isinstance(base, ast.Attribute)
                and isinstance(base.value, ast.Name)
                and base.value.id == "self"
                and self._class
            ):
                # self.attr.m(...): attr's class recorded from __init__
                key = self.attr_types.get(self._class[-1], {}).get(base.attr)
                if key is not None:
                    out.append((key[0], f"{key[1]}.{func.attr}"))
        return out

    def _instance_type_of(self, name: str) -> Optional[Tuple[str, str]]:
        """``name``'s recorded instance class, searching the lexical chain of
        enclosing functions innermost-first (a nested def sees its enclosing
        function's locals)."""
        for i in range(len(self._scope), 0, -1):
            info = self.functions.get(".".join(self._scope[:i]))
            if info is not None and name in info.instance_types:
                return info.instance_types[name]
        return None

    # --------------------------------------------------------------- jit plumbing

    def _tracing_wrapper_name(self, func: ast.AST) -> Optional[str]:
        """'jit'/'shard_map'/... when ``func`` denotes a tracing wrapper."""
        name = dotted(func)
        if name is None:
            return None
        leaf = name.rsplit(".", 1)[-1]
        if leaf in _TRACING_WRAPPERS:
            return leaf
        # partial(jax.jit, ...) used as a decorator factory is handled by
        # _is_jit_expr; a bare partial call is not a wrapper
        return None

    def _is_jit_expr(self, node: ast.AST) -> bool:
        """True for ``@jax.jit`` / ``@jit`` / ``@partial(jax.jit, ...)``."""
        if isinstance(node, ast.Call):
            leaf = (dotted(node.func) or "").rsplit(".", 1)[-1]
            if leaf in _JIT_WRAPPERS:
                return True
            if leaf == "partial" and node.args:
                return (dotted(node.args[0]) or "").rsplit(".", 1)[-1] in _JIT_WRAPPERS
            return False
        return (dotted(node) or "").rsplit(".", 1)[-1] in _JIT_WRAPPERS

    def _static_info(self, node: ast.AST) -> Tuple[Tuple[int, ...], Tuple[str, ...]]:
        nums: Tuple[int, ...] = ()
        names: Tuple[str, ...] = ()
        if isinstance(node, ast.Call):
            for kw in node.keywords:
                val = _const(kw.value)
                if kw.arg == "static_argnums" and val is not None:
                    nums = tuple(val) if isinstance(val, tuple) else (val,)
                if kw.arg == "static_argnames" and val is not None:
                    names = tuple(val) if isinstance(val, tuple) else (val,)
        return nums, names

    @staticmethod
    def donate_info(node: ast.AST) -> Tuple[int, ...]:
        """Literal ``donate_argnums`` of a jit call expression, else ()."""
        if isinstance(node, ast.Call):
            for kw in node.keywords:
                if kw.arg == "donate_argnums":
                    val = _const(kw.value)
                    if val is not None:
                        return tuple(val) if isinstance(val, tuple) else (val,)
        return ()

    @staticmethod
    def donate_configured(node: ast.AST) -> bool:
        """True when a jit call passes ``donate_argnums=`` whose value is NOT a
        literal (``donate_argnums=self._donate_argnums``): the callable MAY
        donate, at positions unknowable statically."""
        if isinstance(node, ast.Call):
            for kw in node.keywords:
                if kw.arg == "donate_argnums" and _const(kw.value) is None:
                    return True
        return False

    def _register_traced_arg(self, call: ast.Call) -> None:
        """Mark ``f`` traced for ``jit(f, ...)``-style calls."""
        args = call.args
        leaf = (dotted(call.func) or "").rsplit(".", 1)[-1]
        if leaf == "partial":
            args = call.args[1:]
        if not args or not isinstance(args[0], ast.Name):
            return
        fname = args[0].id
        for i in range(len(self._scope), -1, -1):
            info = self.functions.get(".".join(self._scope[:i] + [fname]))
            if info is not None:
                info.traced = True
                return

    def _bind_jit_result(self, node: ast.Assign) -> None:
        """Record ``g = jax.jit(f, ...)`` / ``self._g = jax.jit(f, ...)``."""
        call = node.value
        if not isinstance(call, ast.Call) or not self._is_jit_expr(call):
            return
        target = node.targets[0]
        bind_name = None
        if isinstance(target, ast.Name):
            bind_name = target.id
        elif isinstance(target, ast.Attribute) and isinstance(target.value, ast.Name) \
                and target.value.id == "self":
            bind_name = target.attr
        if bind_name is None:
            return
        fn_info = None
        args = call.args
        if (dotted(call.func) or "").rsplit(".", 1)[-1] == "partial":
            args = call.args[1:]
        if args and isinstance(args[0], ast.Name):
            for i in range(len(self._scope), -1, -1):
                cand = self.functions.get(".".join(self._scope[:i] + [args[0].id]))
                if cand is not None:
                    fn_info = cand
                    break
        nums, names = self._static_info(call)
        self.jit_bindings[bind_name] = JitBinding(
            bind_name, fn_info, nums, names, call,
            donate_argnums=self.donate_info(call),
            donate_configured=self.donate_configured(call),
        )


class CallGraph:
    """All modules' indexes plus reachability over resolved call edges."""

    def __init__(self, modules: Sequence) -> None:
        self.indexes: List[ModuleIndex] = [ModuleIndex(m) for m in modules]
        self.by_key: Dict[Tuple[str, str], FunctionInfo] = {}
        for idx in self.indexes:
            for fn in idx.functions.values():
                self.by_key[fn.key] = fn

    def index_for(self, source) -> Optional[ModuleIndex]:
        for idx in self.indexes:
            if idx.source is source:
                return idx
        return None

    def trace_roots(self) -> List[FunctionInfo]:
        return [fn for fn in self.by_key.values() if fn.traced]

    def hot_roots(self) -> List[FunctionInfo]:
        return [fn for fn in self.by_key.values() if fn.marker == "hot-path"]

    def reachable(self, roots: Sequence[FunctionInfo], *,
                  stop_markers: Sequence[str] = (),
                  skip_traced: bool = False) -> Set[Tuple[str, str]]:
        """BFS over resolved call edges from ``roots``.

        ``stop_markers`` prunes functions carrying those graftlint markers
        (e.g. ``off-path`` branches of a hot root); ``skip_traced`` keeps a
        host-side walk from descending into device-traced bodies.
        """
        seen: Set[Tuple[str, str]] = set()
        frontier = [fn for fn in roots]
        while frontier:
            fn = frontier.pop()
            if fn.key in seen:
                continue
            seen.add(fn.key)
            for candidates, _node in fn.calls:
                callee = self._resolve(candidates)
                if callee is None or callee.key in seen:
                    continue
                if callee.marker in stop_markers:
                    continue
                if skip_traced and callee.traced:
                    continue
                frontier.append(callee)
        return seen

    def _resolve(self, candidates: Sequence[Tuple[str, str]]) -> Optional[FunctionInfo]:
        for key in candidates:
            fn = self.by_key.get(key)
            if fn is not None:
                return fn
        return None
