"""graftlint — JAX-aware static analysis over the serving stack.

PRs 1–3 each shipped a hand-written regression test for a whole *class* of
bug: the transfer-guard test for host→device leaks in ``DecodeEngine.step``,
the threefry-partitionable parity pin, the cancel-mid-chunked-prefill race.
This package is the mechanical version of those reviews: an AST linter that
checks the invariants on every CI run instead of re-discovering them one
incident at a time.

v2 adds an interprocedural dataflow engine (:mod:`.dataflow`): a def-use/alias
pass over the call graph assigning values a provenance lattice
(host / device / traced / donated) and propagating it through assignments,
attribute stores, and call boundaries — plus three rule families built on it.

v3 adds an intra-procedural control-flow graph with explicit exception edges
(:mod:`.cfg`) and a path-sensitive resource-lifetime family on top of it
(:mod:`.rules_resources`): paired acquire/release tracking for kv-pins,
kv-refs, traces, slots, tickets, and file handles, with per-function
summaries propagated over the resolved call graph and ``# owns:`` /
``# transfers:`` / ``# holds:`` contract annotations.

Rules (see ``docs/analysis.md`` for the catalog):

- ``host-sync`` — host syncs / implicit transfers inside jit-traced bodies or
  on ``# graftlint: hot-path`` host paths (call-graph walk; v2 follows
  aliases of device-resident values, not just ``_dev`` spellings).
- ``retrace`` — jitted-callable usage that retraces or recompiles per call.
- ``sharding`` — ``PartitionSpec`` axis names checked against the mesh axes
  the tree declares; ``NamedSharding`` built off a foreign mesh variable.
- ``lock-discipline`` — writes to ``# guarded-by: <lock>`` host state outside
  the owning lock.
- ``use-after-donate`` — reads of a buffer after it was passed in a
  ``donate_argnums`` position (factories resolved cross-module).
- ``lock-order`` — lock-acquisition cycles (potential deadlocks) and blocking
  calls held under a lock, interprocedural.
- ``async-blocking`` — blocking calls inside ``async def`` handlers that
  stall the event loop.
- ``resource-leak`` — an acquired resource (pin/ref/trace/slot/ticket/handle)
  with a CFG path — normal or exceptional — out of the function that skips
  every release, escape, and transfer.
- ``double-release`` — two releases of the same resource key on one path.
- ``unbalanced-transfer`` — ``# owns:`` / ``# transfers:`` contract comments
  whose bodies don't release / whose callers drop the handed-over resource.
- ``suppression`` — always-on hygiene: every ``# graftlint: disable=`` needs a
  known rule name and a reason string.

Run it as ``python -m unionml_tpu.analysis unionml_tpu/`` (exit 1 on findings)
or programmatically via :func:`run_lint`. CI surfaces: ``--sarif`` (GitHub
code scanning), ``--baseline`` (land widened scopes incrementally),
``--budget`` (lint-runtime contract).
"""

from unionml_tpu.analysis.core import (  # noqa: F401
    REPORT_VERSION,
    Finding,
    LintResult,
    Project,
    RULES,
    baseline_payload,
    load_baseline,
    run_lint,
)

__all__ = [
    "Finding",
    "LintResult",
    "Project",
    "RULES",
    "REPORT_VERSION",
    "baseline_payload",
    "load_baseline",
    "run_lint",
]
