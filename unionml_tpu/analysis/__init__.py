"""graftlint — a JAX-aware static-analysis pass over the serving stack.

PRs 1–3 each shipped a hand-written regression test for a whole *class* of
bug: the transfer-guard test for host→device leaks in ``DecodeEngine.step``,
the threefry-partitionable parity pin, the cancel-mid-chunked-prefill race.
This package is the mechanical version of those reviews: an AST linter that
checks the invariants on every CI run instead of re-discovering them one
incident at a time.

Rules (see :mod:`docs/analysis.md <docs.analysis>` for the catalog):

- ``host-sync`` — host syncs / implicit transfers inside jit-traced bodies or
  on ``# graftlint: hot-path`` host paths (call-graph walk).
- ``retrace`` — jitted-callable usage that retraces or recompiles per call.
- ``sharding`` — ``PartitionSpec`` axis names checked against the mesh axes
  the tree declares; ``NamedSharding`` built off a foreign mesh variable.
- ``lock-discipline`` — writes to ``# guarded-by: <lock>`` host state outside
  the owning lock.
- ``suppression`` — always-on hygiene: every ``# graftlint: disable=`` needs a
  known rule name and a reason string.

Run it as ``python -m unionml_tpu.analysis unionml_tpu/`` (exit 1 on findings)
or programmatically via :func:`run_lint`.
"""

from unionml_tpu.analysis.core import (  # noqa: F401
    REPORT_VERSION,
    Finding,
    LintResult,
    Project,
    RULES,
    run_lint,
)

__all__ = ["Finding", "LintResult", "Project", "RULES", "REPORT_VERSION", "run_lint"]
