"""SARIF 2.1.0 output for graftlint (GitHub code-scanning compatible).

One ``run`` per lint invocation: the tool section carries the full rule
catalog (so code-scanning renders per-rule help), each active finding becomes
a ``level: error`` result, each baselined finding a ``level: note`` result,
and each suppressed finding a result carrying an ``inSource`` suppression with
the author's reason — the reasoned-suppression inventory survives into the
code-scanning UI instead of vanishing at the CLI boundary.

``partialFingerprints`` uses the same line-independent fingerprint as the
``--baseline`` mechanism, so code-scanning alert identity is stable across
unrelated edits.
"""

from pathlib import Path
from typing import Dict, List

SARIF_VERSION = "2.1.0"
SARIF_SCHEMA = "https://json.schemastore.org/sarif-2.1.0.json"

#: always present in the catalog even though it registers no check() (the
#: comment parser emits it directly)
_META_RULES = {
    "suppression": "graftlint comments must name known rules and carry a reason",
    "parse": "files that do not parse cannot be linted",
}


def _artifact_uri(path: str) -> str:
    """Repo-relative forward-slash URI; absolute paths keep their tail."""
    p = Path(path)
    if p.is_absolute():
        try:
            p = p.relative_to(Path.cwd())
        except ValueError:
            pass
    return p.as_posix()


def _result(finding, rule_index: Dict[str, int], level: str, occurrence: int) -> Dict:
    out = {
        "ruleId": finding.rule,
        "ruleIndex": rule_index.get(finding.rule, -1),
        "level": level,
        "message": {"text": finding.message + (f" [{finding.symbol}]" if finding.symbol else "")},
        "locations": [
            {
                "physicalLocation": {
                    "artifactLocation": {"uri": _artifact_uri(finding.path)},
                    "region": {
                        "startLine": max(1, finding.line),
                        # SARIF columns are 1-based; ast's are 0-based
                        "startColumn": finding.col + 1,
                    },
                }
            }
        ],
        "partialFingerprints": {"graftlint/v1": finding.fingerprint(occurrence)},
    }
    if finding.suppressed:
        out["suppressions"] = [
            {"kind": "inSource", "justification": finding.reason or ""}
        ]
    return out


def to_sarif(result) -> Dict:
    """Build the SARIF document for one :class:`~...core.LintResult`."""
    from unionml_tpu.analysis.core import REPORT_VERSION, RULES

    catalog: List[Dict] = []
    rule_index: Dict[str, int] = {}
    names = sorted(set(RULES) | set(_META_RULES))
    for i, name in enumerate(names):
        rule_index[name] = i
        summary = RULES[name].summary if name in RULES else _META_RULES[name]
        catalog.append(
            {
                "id": name,
                "name": name.replace("-", "_"),
                "shortDescription": {"text": summary},
                "defaultConfiguration": {"level": "error"},
            }
        )

    results: List[Dict] = []
    occurrences: Dict = {}

    def occ(finding) -> int:
        key = (finding.rule, finding.path, finding.symbol)
        n = occurrences.get(key, 0)
        occurrences[key] = n + 1
        return n

    for finding in result.findings:
        results.append(_result(finding, rule_index, "error", occ(finding)))
    for finding in result.baselined:
        results.append(_result(finding, rule_index, "note", occ(finding)))
    for finding in result.suppressed:
        results.append(_result(finding, rule_index, "note", occ(finding)))

    return {
        "$schema": SARIF_SCHEMA,
        "version": SARIF_VERSION,
        "runs": [
            {
                "tool": {
                    "driver": {
                        "name": "graftlint",
                        "informationUri": "https://github.com/unionai-oss/unionml",
                        "version": str(REPORT_VERSION),
                        "rules": catalog,
                    }
                },
                "columnKind": "utf16CodeUnits",
                "results": results,
            }
        ],
    }
