"""Interprocedural dataflow for graftlint v2: provenance, donation, blocking.

The v1 rules were syntactic and per-statement: a donated buffer read through an
alias, a lock acquired three calls below another lock's ``with`` block, or a
device value renamed before its ``bool()`` all sailed through. This module is
the shared machinery the v2 rule families build on:

- **Provenance lattice.** Every tracked value sits in a small lattice::

        unknown  (top: nothing provable — rules stay silent)
        host     (numpy / python scalars / fetched values)
        device   (jnp/jax call results, ``*_dev`` mirrors, known device attrs)
        traced   (values inside a jit-traced body — owned by rules_host_sync)
        donated  (passed in a ``donate_argnums`` position; the buffer is dead)

  ``donated`` and ``traced`` are *taints* layered over host/device; joins go to
  ``unknown`` — the analysis is deliberately best-effort, and an unprovable
  provenance produces silence, never a guess. The practical consequences:
  aliasing is tracked through plain assignments and attribute loads only;
  values that round-trip containers, comprehensions, or unscanned callees
  drop to ``unknown``.

- **Donation environment** (:class:`DonationEnv`): which callables donate
  which positional args. Sources: direct jit bindings with ``donate_argnums``
  (``self._save_fn = jax.jit(_save, donate_argnums=(0,))``), decorator forms,
  and **factories** — functions whose returns are donating jit callables
  (``make_classifier_train_step`` -> ``_wrap_step`` -> ``jax.jit(step,
  donate_argnums=(0,))``), resolved cross-module through the call graph with a
  fixpoint, so ``step = make_lm_train_step(...)``'s call sites are checked in
  bench scripts too.

- **Blocking summaries** (:class:`Summaries`): per-function "does calling this
  stall the calling thread" — direct primitives (``time.sleep``, unbounded
  ``.wait()``/``.join()``/``.result()``/``.acquire()``, ``subprocess.run``,
  ``jax.device_get``, ``.block_until_ready()``) propagated up resolved call
  edges to a fixpoint, with the call chain kept for the finding message.

- **Lock model** (:class:`LockModel`): lock identities ((module, class, attr)
  for ``self._lock = threading.Lock()`` in ``__init__``, (module, None, name)
  for module-level locks) and per-function acquisition summaries, again
  propagated interprocedurally so ``with self._lock: self.scheduler.submit()``
  yields the cross-class edge ``batcher._lock -> scheduler._lock``.

- **Device aliasing** (:func:`device_locals` / :func:`device_attrs`): the
  host-sync retrofit — ``x = self._tokens`` followed by ``bool(x)`` is caught
  because ``self._tokens`` was assigned a ``jnp`` result in ``__init__`` and
  the local ``x`` inherits its provenance.
"""

import ast
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

from unionml_tpu.analysis.callgraph import CallGraph, FunctionInfo, ModuleIndex, dotted

#: (module, class-or-None, attribute/name) — one lock's identity
LockKey = Tuple[str, Optional[str], str]

#: threading constructors that create a mutual-exclusion (``with``-able) lock
_LOCK_CTORS = {"Lock", "RLock", "Condition", "Semaphore", "BoundedSemaphore"}

#: subprocess entry points that wait for the child
_SUBPROCESS_BLOCKING = {"run", "call", "check_call", "check_output", "communicate"}


def own_nodes(root: ast.AST) -> List[ast.AST]:
    """Walk ``root`` without descending into nested function/class bodies or
    lambdas — the nodes that execute as part of *this* function's frame.

    Function roots cache the materialized walk on the node: every rule family
    sweeps every function at least once, and re-generating the same ~300k
    nodes per family was a measurable slice of the lint budget."""
    is_fn = isinstance(root, (ast.FunctionDef, ast.AsyncFunctionDef))
    if is_fn:
        cached = getattr(root, "_graftlint_own", None)
        if cached is not None:
            return cached
    out: List[ast.AST] = []
    stack: List[ast.AST] = [root]
    first = True
    while stack:
        node = stack.pop()
        if not first and isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda, ast.ClassDef)
        ):
            continue
        first = False
        out.append(node)
        stack.extend(ast.iter_child_nodes(node))
    if is_fn:
        root._graftlint_own = out
    return out


def _call_map(fn: FunctionInfo) -> Dict[int, List[Tuple[str, str]]]:
    """id(Call node) -> callee candidates, cached per function (rules resolve
    individual sites; the list scan would be quadratic)."""
    cache = getattr(fn, "_graftlint_call_map", None)
    if cache is None:
        cache = {id(node): cands for cands, node in fn.calls}
        fn._graftlint_call_map = cache
    return cache


def resolved_edges(graph: CallGraph, fn: FunctionInfo) -> List[Tuple[FunctionInfo, ast.Call]]:
    """``fn``'s call sites with a scanned callee, resolved ONCE and cached —
    every interprocedural fixpoint iterates call edges repeatedly, and
    re-running candidate resolution each sweep dominated the lint wall time."""
    cache = getattr(fn, "_graftlint_edges", None)
    if cache is None:
        cache = []
        for candidates, call in fn.calls:
            callee = graph._resolve(candidates)
            if callee is not None:
                cache.append((callee, call))
        fn._graftlint_edges = cache
    return cache


def _has_timeout(call: ast.Call) -> bool:
    """True when the call passes any positional arg or a ``timeout=`` kwarg —
    bounded waits are stalls, not deadlocks, and stay out of scope."""
    return bool(call.args) or any(kw.arg == "timeout" for kw in call.keywords)


def blocking_reason(call: ast.Call, idx: ModuleIndex) -> Optional[str]:
    """Why this call blocks the current thread indefinitely (None if it
    doesn't, or if we cannot prove it does)."""
    name = dotted(call.func)
    if name is not None:
        root, _, rest = name.partition(".")
        expanded = idx.imports.get(root, root) + (("." + rest) if rest else "")
        if expanded == "time.sleep":
            return "time.sleep() sleeps the thread"
        if expanded in ("jax.device_get",):
            return "jax.device_get() blocks on the device stream"
        leaf = expanded.rsplit(".", 1)[-1]
        if expanded.startswith("subprocess.") and leaf in _SUBPROCESS_BLOCKING:
            return f"subprocess.{leaf}() waits for the child process"
    if isinstance(call.func, ast.Attribute):
        attr = call.func.attr
        if attr == "block_until_ready":
            return ".block_until_ready() blocks on the device stream"
        if attr == "result" and not call.args and not call.keywords:
            return ".result() without a timeout blocks until the future resolves"
        if attr == "join" and not _has_timeout(call):
            # str.join always takes an iterable argument, so a zero-arg join is
            # a thread/process join
            return ".join() without a timeout blocks until the worker exits"
        if attr == "wait" and not _has_timeout(call):
            return ".wait() without a timeout blocks unboundedly"
        if attr == "acquire" and not _has_timeout(call):
            if not any(
                isinstance(kw.value, ast.Constant) and kw.value.value is False
                for kw in call.keywords
                if kw.arg == "blocking"
            ):
                return ".acquire() without a timeout blocks until the lock frees"
    return None


def _wait_receiver(call: ast.Call) -> Optional[ast.AST]:
    """The receiver of a ``.wait()`` call (condition-variable exemption)."""
    if isinstance(call.func, ast.Attribute) and call.func.attr == "wait":
        return call.func.value
    return None


# --------------------------------------------------------------------- locks


class LockModel:
    """Every lock the tree declares, plus helpers to name an acquisition."""

    def __init__(self, graph: CallGraph) -> None:
        self.locks: Set[LockKey] = set()
        #: parsed ``# lock-order: a < b`` hints: (module, line, a, b)
        self.hints: List[Tuple[str, int, str, str]] = []
        for idx in graph.indexes:
            self._collect_module(idx)

    def _collect_module(self, idx: ModuleIndex) -> None:
        for node in idx.source.tree.body:
            if isinstance(node, ast.Assign) and self._is_lock_ctor(node.value, idx):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        self.locks.add((idx.name, None, t.id))
        for cls_name, cls_node in idx.classes.items():
            for sub in ast.walk(cls_node):
                if isinstance(sub, ast.Assign) and self._is_lock_ctor(sub.value, idx):
                    for t in sub.targets:
                        if (
                            isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id == "self"
                        ):
                            self.locks.add((idx.name, cls_name, t.attr))

    @staticmethod
    def _is_lock_ctor(value: ast.AST, idx: ModuleIndex) -> bool:
        if not isinstance(value, ast.Call):
            return False
        name = dotted(value.func) or ""
        leaf = name.rsplit(".", 1)[-1]
        if leaf not in _LOCK_CTORS:
            return False
        root = name.split(".", 1)[0]
        target = idx.imports.get(root, root)
        # threading.Lock() / Lock() (from threading import Lock) /
        # multiprocessing.Lock(); a same-named user class would need the
        # ``# lock-order:`` hint instead
        return leaf == root or target in ("threading", "multiprocessing")

    def lock_of(self, expr: ast.AST, idx: ModuleIndex, cls: Optional[str]) -> Optional[LockKey]:
        """The lock an acquisition expression names, or None."""
        if isinstance(expr, ast.Call):  # with self._lock.acquire_timeout(...)
            expr = expr.func
            if isinstance(expr, ast.Attribute):
                expr = expr.value
        if (
            isinstance(expr, ast.Attribute)
            and isinstance(expr.value, ast.Name)
            and expr.value.id == "self"
            and cls is not None
        ):
            key = (idx.name, cls, expr.attr)
            return key if key in self.locks else None
        if isinstance(expr, ast.Name):
            key = (idx.name, None, expr.id)
            return key if key in self.locks else None
        return None

    def by_attr(self, module: str, attr: str) -> List[LockKey]:
        """Locks in ``module`` whose attribute/name is ``attr`` (hint lookup)."""
        return [k for k in self.locks if k[0] == module and k[2] == attr]


# ----------------------------------------------------------------- summaries


class BlockInfo:
    """Why a function blocks: the primitive's reason plus the call chain."""

    def __init__(self, reason: str, line: int, chain: Tuple[str, ...]) -> None:
        self.reason = reason
        self.line = line  # line of the primitive in ITS function
        self.chain = chain  # qualnames from this function down to the primitive

    def via(self, qualname: str) -> "BlockInfo":
        return BlockInfo(self.reason, self.line, (qualname,) + self.chain)


class Summaries:
    """Per-function interprocedural facts: blocking, lock acquisition.

    Both are least-fixpoints over resolved call edges; unresolvable calls
    contribute nothing (best-effort: silence over noise).
    """

    def __init__(self, graph: CallGraph, locks: LockModel) -> None:
        self.graph = graph
        self.locks = locks
        self.blocking: Dict[Tuple[str, str], BlockInfo] = {}
        self.acquires: Dict[Tuple[str, str], Set[LockKey]] = {}
        self._compute_direct()
        self._fixpoint()

    def _compute_direct(self) -> None:
        for idx in self.graph.indexes:
            for fn in idx.functions.values():
                acquired: Set[LockKey] = set()
                for node in own_nodes(fn.node):
                    if isinstance(node, (ast.With, ast.AsyncWith)):
                        for item in node.items:
                            key = self.locks.lock_of(item.context_expr, idx, fn.class_name)
                            if key is not None:
                                acquired.add(key)
                    elif isinstance(node, ast.Call) and fn.key not in self.blocking:
                        reason = blocking_reason(node, idx)
                        if reason is not None and not self._is_condition_wait(node, idx, fn):
                            self.blocking[fn.key] = BlockInfo(
                                reason, node.lineno, (fn.qualname,)
                            )
                if acquired:
                    self.acquires[fn.key] = acquired

    def _is_condition_wait(self, call: ast.Call, idx: ModuleIndex, fn: FunctionInfo) -> bool:
        """``cond.wait()`` where ``cond`` is a declared lock: the wait RELEASES
        the lock while parked (the condition-variable protocol), so it is not
        a blocking primitive for the under-lock rule; the surrounding loop's
        progress is the scheduler's business, not the linter's."""
        recv = _wait_receiver(call)
        return recv is not None and self.locks.lock_of(recv, idx, fn.class_name) is not None

    def _fixpoint(self) -> None:
        changed = True
        while changed:
            changed = False
            for idx in self.graph.indexes:
                for fn in idx.functions.values():
                    for callee, call in resolved_edges(self.graph, fn):
                        if callee.key == fn.key:
                            continue
                        info = self.blocking.get(callee.key)
                        if info is not None and fn.key not in self.blocking:
                            if len(info.chain) < 6:  # chains longer than this are noise
                                self.blocking[fn.key] = info.via(fn.qualname)
                                changed = True
                        callee_locks = self.acquires.get(callee.key)
                        if callee_locks:
                            mine = self.acquires.setdefault(fn.key, set())
                            if not callee_locks <= mine:
                                mine |= callee_locks
                                changed = True

    def resolve_call(self, fn: FunctionInfo, call: ast.Call) -> Optional[FunctionInfo]:
        """The scanned callee of one recorded call site of ``fn`` (None when
        unresolved or not this exact node)."""
        candidates = _call_map(fn).get(id(call))
        return self.graph._resolve(candidates) if candidates else None


# ------------------------------------------------------------------ donation


#: sentinel position: "may donate, positions configured at runtime" — e.g.
#: ``jax.jit(fn, donate_argnums=self._donate_argnums)``. Only *args splats can
#: be tainted under it (the tuple whose elements may have been donated).
CONFIGURED_DONATION = (-1,)


class DonationEnv:
    """Which callables donate which positional arguments.

    ``factory_positions`` maps scanned functions that RETURN a donating
    compiled callable to its donate positions (fixpoint: a factory may return
    another factory's result — ``make_lm_train_step`` -> ``_wrap_step``).
    """

    def __init__(self, graph: CallGraph) -> None:
        self.graph = graph
        self.factory_positions: Dict[Tuple[str, str], Tuple[int, ...]] = {}
        #: (module, class, attr) -> positions, for ``self._f = factory_fn``
        self.attr_factories: Dict[Tuple[str, str, str], Tuple[int, ...]] = {}
        self._compute_factories()
        self._compute_attr_factories()

    def _compute_factories(self) -> None:
        # per-function return facts derived in ONE AST walk: either donation
        # positions knowable directly (literal/configured donate_argnums, a
        # returned jit binding) or the resolved callee keys whose factory
        # status the fixpoint below inherits ("return another_factory(...)").
        # The fixpoint then iterates over these small fact lists instead of
        # re-walking every function body per sweep.
        pending: Dict[Tuple[str, str], List[Tuple[str, str]]] = {}
        for idx in self.graph.indexes:
            for fn in idx.functions.values():
                direct: Tuple[int, ...] = ()
                callees: List[Tuple[str, str]] = []
                for node in own_nodes(fn.node):
                    if not isinstance(node, ast.Return) or node.value is None:
                        continue
                    value = node.value
                    if isinstance(value, ast.Call):
                        donate = ModuleIndex.donate_info(value)
                        if donate:
                            direct = donate
                            break
                        if ModuleIndex.donate_configured(value):
                            direct = CONFIGURED_DONATION
                            break
                        # return another_factory(...): inherit its positions
                        callee = self._resolve_value_call(value, idx, fn)
                        if callee is not None:
                            callees.append(callee.key)
                    elif isinstance(value, ast.Name):
                        # return jitted — where ``jitted = jax.jit(..., donate_...)``
                        binding = idx.jit_bindings.get(value.id)
                        if binding is not None and binding.donate_argnums:
                            direct = binding.donate_argnums
                            break
                        if binding is not None and binding.donate_configured:
                            direct = CONFIGURED_DONATION
                            break
                if direct:
                    self.factory_positions[fn.key] = direct
                elif callees:
                    pending[fn.key] = callees
        changed = True
        while changed:
            changed = False
            for key, callees in pending.items():
                if key in self.factory_positions:
                    continue
                for ck in callees:
                    pos = self.factory_positions.get(ck)
                    if pos:
                        self.factory_positions[key] = pos
                        changed = True
                        break

    def _resolve_value_call(
        self, call: ast.Call, idx: ModuleIndex, fn: FunctionInfo
    ) -> Optional[FunctionInfo]:
        candidates = _call_map(fn).get(id(call))
        return self.graph._resolve(candidates) if candidates else None

    def _compute_attr_factories(self) -> None:
        """``self._make_step = _make_step`` in ``__init__``-like methods binds
        a factory to an attribute; later ``self._make_step(...)`` calls build
        donating callables."""
        for idx in self.graph.indexes:
            for fn in idx.functions.values():
                if fn.class_name is None:
                    continue
                for node in own_nodes(fn.node):
                    if not isinstance(node, ast.Assign) or not isinstance(node.value, ast.Name):
                        continue
                    # the factory must be lexically resolvable from here
                    for i in range(fn.qualname.count(".") + 1, -1, -1):
                        parts = fn.qualname.split(".")[:i] + [node.value.id]
                        cand = idx.functions.get(".".join(parts))
                        if cand is not None and cand.key in self.factory_positions:
                            for t in node.targets:
                                if (
                                    isinstance(t, ast.Attribute)
                                    and isinstance(t.value, ast.Name)
                                    and t.value.id == "self"
                                ):
                                    self.attr_factories[
                                        (idx.name, fn.class_name, t.attr)
                                    ] = self.factory_positions[cand.key]
                            break

    def donating_positions(
        self,
        call: ast.Call,
        idx: ModuleIndex,
        fn: FunctionInfo,
        local_factories: Dict[str, Tuple[int, ...]],
    ) -> Tuple[Tuple[int, ...], str]:
        """(positions, callee label) when ``call`` invokes a donating callable;
        ``((), "")`` otherwise. ``local_factories`` carries names the caller's
        linear walk bound to factory-call results (``step = make_step(...)``).
        """
        func = call.func
        # direct double call: make_lm_train_step(...)(state, batch)
        if isinstance(func, ast.Call):
            donate = ModuleIndex.donate_info(func)
            if donate:
                return donate, "jax.jit(...)"
            callee = self._resolve_value_call(func, idx, fn)
            if callee is not None and callee.key in self.factory_positions:
                return self.factory_positions[callee.key], callee.qualname
        name = dotted(func)
        if name is None:
            return (), ""
        leaf = name.rsplit(".", 1)[-1]
        if name in local_factories:
            return local_factories[name], name
        binding = idx.jit_bindings.get(leaf)
        if binding is not None and binding.donate_argnums:
            return binding.donate_argnums, leaf
        if binding is not None and binding.donate_configured:
            return CONFIGURED_DONATION, leaf
        return (), ""

    def factory_call_positions(
        self, call: ast.Call, idx: ModuleIndex, fn: FunctionInfo
    ) -> Tuple[int, ...]:
        """Positions when ``call`` invokes a FACTORY (its result is a donating
        callable) — for tracking ``step = make_classifier_train_step(...)``."""
        func = call.func
        if (
            isinstance(func, ast.Attribute)
            and isinstance(func.value, ast.Name)
            and func.value.id == "self"
            and fn.class_name is not None
        ):
            key = (idx.name, fn.class_name, func.attr)
            if key in self.attr_factories:
                return self.attr_factories[key]
        callee = self._resolve_value_call(call, idx, fn)
        if callee is not None and callee.key in self.factory_positions:
            return self.factory_positions[callee.key]
        return ()


def donated_arg_exprs(call: ast.Call, positions: Sequence[int]) -> List[Tuple[str, ast.AST]]:
    """(normalized source, node) of each donated argument that names a
    REUSABLE value (Name/Attribute/Subscript); fresh temporaries (call results,
    literals) have nothing to use after the donation and are skipped.

    Positions at or past a ``*args`` splat — and every position under
    :data:`CONFIGURED_DONATION` — cannot be pinned to one argument, so the
    SPLAT NAME itself is tainted instead: the tuple may hold donated buffers,
    and forwarding it again (``self._fn(*args)`` retry patterns) reuses them.
    """
    out: List[Tuple[str, ast.AST]] = []
    star_at = next(
        (i for i, a in enumerate(call.args) if isinstance(a, ast.Starred)), len(call.args)
    )

    def taint_splats() -> None:
        for a in call.args:
            if isinstance(a, ast.Starred) and isinstance(a.value, ast.Name):
                out.append((a.value.id, a.value))

    if tuple(positions) == CONFIGURED_DONATION:
        taint_splats()
        return out
    for p in positions:
        if p >= min(star_at, len(call.args)):
            if p >= star_at:
                taint_splats()
            continue
        arg = call.args[p]
        if isinstance(arg, (ast.Name, ast.Attribute, ast.Subscript)):
            try:
                out.append((ast.unparse(arg), arg))
            except ValueError:  # pragma: no cover - unparse is total on these
                continue
    return out


def shared_analyses(graph: CallGraph) -> Tuple[LockModel, "Summaries"]:
    """One (LockModel, Summaries) pair per call graph — the lock-order and
    async-blocking rules share the fixpoint instead of recomputing it."""
    cached = getattr(graph, "_graftlint_analyses", None)
    if cached is None:
        locks = LockModel(graph)
        cached = (locks, Summaries(graph, locks))
        graph._graftlint_analyses = cached
    return cached


# ------------------------------------------------------------ device aliasing


def _expr_is_device(node: ast.AST, idx: ModuleIndex, dev_attrs: Set[str],
                    dev_locals: Set[str]) -> bool:
    """Best-effort: does this expression yield a device-resident value?"""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name):
            if sub.id.endswith("_dev") or sub.id in dev_locals:
                return True
        elif isinstance(sub, ast.Attribute):
            if sub.attr.endswith("_dev"):
                return True
            if (
                isinstance(sub.value, ast.Name)
                and sub.value.id == "self"
                and sub.attr in dev_attrs
            ):
                return True
        elif isinstance(sub, ast.Call):
            name = dotted(sub.func) or ""
            root = name.split(".", 1)[0]
            target = idx.imports.get(root, root)
            if target in ("jax.numpy", "jax") or target.startswith("jax.numpy"):
                leaf = name.rsplit(".", 1)[-1]
                if leaf not in ("device_get",):  # fetches produce HOST values
                    return True
            leaf = name.rsplit(".", 1)[-1]
            if leaf in idx.jit_bindings:
                return True
    return False


def device_attrs(idx: ModuleIndex, cls_name: str) -> Set[str]:
    """Attributes of ``cls_name`` assigned device-provenance values anywhere in
    the class body (``self._tokens = jnp.zeros(...)`` in ``__init__`` makes
    ``self._tokens`` device-resident for every method)."""
    cls = idx.classes.get(cls_name)
    if cls is None:
        return set()
    out: Set[str] = set()
    for node in ast.walk(cls):
        if not isinstance(node, ast.Assign):
            continue
        if not _expr_is_device(node.value, idx, out, set()):
            continue
        for t in node.targets:
            targets = t.elts if isinstance(t, (ast.Tuple, ast.List)) else [t]
            for el in targets:
                if (
                    isinstance(el, ast.Attribute)
                    and isinstance(el.value, ast.Name)
                    and el.value.id == "self"
                    and not el.attr.endswith("_host")
                ):
                    out.add(el.attr)
    return out


def _mentions_shape(node: ast.AST, shape_names: Set[str]) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Attribute) and sub.attr in ("shape", "ndim", "size", "dtype"):
            return True
        if isinstance(sub, ast.Call) and isinstance(sub.func, ast.Name) and sub.func.id == "len":
            return True
        if isinstance(sub, ast.Name) and sub.id in shape_names:
            return True
    return False


def shape_locals(fn: FunctionInfo) -> Set[str]:
    """Local names carrying trace-time shape arithmetic: assigned from
    expressions mentioning ``.shape``/``.ndim``/``.size``/``len()`` or other
    shape locals (``num_tokens, num_experts = gates.shape``). Conversions of
    these are python ints at trace time, never host syncs."""
    out: Set[str] = set()
    for _ in range(3):
        before = len(out)
        for node in own_nodes(fn.node):
            if not isinstance(node, ast.Assign):
                continue
            if not _mentions_shape(node.value, out):
                continue
            for t in node.targets:
                targets = t.elts if isinstance(t, (ast.Tuple, ast.List)) else [t]
                for el in targets:
                    if isinstance(el, ast.Name):
                        out.add(el.id)
        if len(out) == before:
            break
    return out


def device_locals(fn: FunctionInfo, idx: ModuleIndex) -> Set[str]:
    """Local names aliasing device values in ``fn`` — one forward pass over
    its own assignments (``x = self._tokens``; ``y = x`` chains converge in at
    most a couple of iterations)."""
    dev_attrs = device_attrs(idx, fn.class_name) if fn.class_name else set()
    out: Set[str] = set()
    for _ in range(3):  # alias chains are short; bounded fixpoint
        before = len(out)
        for node in own_nodes(fn.node):
            if not isinstance(node, ast.Assign):
                continue
            if not _expr_is_device(node.value, idx, dev_attrs, out):
                continue
            for t in node.targets:
                targets = t.elts if isinstance(t, (ast.Tuple, ast.List)) else [t]
                for el in targets:
                    if isinstance(el, ast.Name):
                        out.add(el.id)
        if len(out) == before:
            break
    return out
