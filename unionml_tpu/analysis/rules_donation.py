"""Rule ``use-after-donate``: reading a buffer after XLA was told to reuse it.

``donate_argnums`` hands an argument's HBM to the compiled program: after the
call, the caller-side array is **deleted** — any later read raises (jax checks)
or, under some paths, silently reads freed memory. The serving engine leans on
donation everywhere (the decode step donates the KV cache and logits, block
saves donate the pool, training steps donate the optimizer state), so the
discipline "every donated input is rebound from the call's outputs, in the
same statement" is load-bearing. This rule checks it with the dataflow layer's
donation environment (:class:`~unionml_tpu.analysis.dataflow.DonationEnv`):

- **use-after-donate** — a donated Name/Attribute/Subscript expression is read
  again before being rebound. Aliases die with the source: only rebinding the
  exact expression (or its base name) clears the taint.
- **loop-carried donation** — the donating call sits in a loop and the donated
  expression is not rebound in the loop body: iteration N+1 reads the buffer
  iteration N donated (``for b in batches: step(state, b)`` — the classic).
  Detected by replaying the loop body once with the surviving taints.
- **donated attribute never rebound** — a donated ``self.X`` that is not
  reassigned anywhere later in the method outlives the frame on the instance;
  any OTHER method's read then sees a deleted buffer. Flagged at the donation
  site (cross-method read ordering is beyond static reach; the rebind is not).

Factories are resolved interprocedurally: ``step = make_lm_train_step(...)``
marks ``step`` donating-at-position-0 because the factory's returns chain to
``jax.jit(train_step, donate_argnums=(0,))`` through ``_wrap_step``.
"""

import ast
import dataclasses
from typing import Dict, List, Optional, Tuple

from unionml_tpu.analysis.callgraph import FunctionInfo, ModuleIndex
from unionml_tpu.analysis.core import Finding, Project, register
from unionml_tpu.analysis.dataflow import DonationEnv, donated_arg_exprs


@dataclasses.dataclass
class _Taint:
    expr: str
    line: int  # donation site
    callee: str
    loop_pass: bool = False  # created during a loop replay pass


class _FunctionWalk:
    """One function's linear donation walk (statements in program order)."""

    def __init__(self, fn: FunctionInfo, idx: ModuleIndex, env: DonationEnv) -> None:
        self.fn = fn
        self.idx = idx
        self.env = env
        self.tainted: Dict[str, _Taint] = {}
        #: names bound to factory-call results: ``step = make_step(...)``
        self.local_factories: Dict[str, Tuple[int, ...]] = {}
        self.findings: List[Finding] = []
        self._reported: set = set()

    # ------------------------------------------------------------------ driver

    def run(self) -> List[Finding]:
        body = getattr(self.fn.node, "body", [])
        self._process_block(body)
        # donated self-attributes never rebound in this method outlive the call
        for taint in self.tainted.values():
            if taint.expr.startswith("self."):
                self._report(
                    taint.line,
                    0,
                    f"{taint.expr} is donated to '{taint.callee}' and never rebound in "
                    f"this method: the attribute now holds a deleted buffer, and any "
                    f"later read (from any method) is a use-after-donate; rebind it "
                    f"from the call's outputs",
                )
        return self.findings

    def _process_block(self, stmts) -> None:
        for stmt in stmts:
            self._process_stmt(stmt)

    def _process_stmt(self, stmt: ast.stmt) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            return  # nested defs run later, under their own frame
        if isinstance(stmt, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            self._process_assign(stmt)
        elif isinstance(stmt, ast.Delete):
            for t in stmt.targets:
                self._kill_target(t)
        elif isinstance(stmt, (ast.For, ast.AsyncFor)):
            self._check_reads(stmt.iter)
            self._collect_donations(stmt.iter)
            self._kill_target(stmt.target)
            self._process_loop(stmt.body)
            self._process_block(stmt.orelse)
        elif isinstance(stmt, ast.While):
            self._check_reads(stmt.test)
            self._process_loop(stmt.body)
            self._process_block(stmt.orelse)
        elif isinstance(stmt, ast.If):
            self._check_reads(stmt.test)
            self._collect_donations(stmt.test)
            self._process_block(stmt.body)
            self._process_block(stmt.orelse)
        elif isinstance(stmt, ast.Try):
            self._process_block(stmt.body)
            for handler in stmt.handlers:
                self._process_block(handler.body)
            self._process_block(stmt.orelse)
            self._process_block(stmt.finalbody)
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                self._check_reads(item.context_expr)
                self._collect_donations(item.context_expr)
                if item.optional_vars is not None:
                    self._kill_target(item.optional_vars)
            self._process_block(stmt.body)
        else:
            # Expr / Return / Raise / Assert / aug-free statements: reads, then
            # any donations they perform
            for value in ast.iter_child_nodes(stmt):
                if isinstance(value, ast.expr):
                    self._check_reads(value)
                    self._collect_donations(value)

    def _process_assign(self, stmt) -> None:
        value = getattr(stmt, "value", None)
        if value is not None:
            self._check_reads(value)
            self._collect_donations(value)
        targets = stmt.targets if isinstance(stmt, ast.Assign) else [stmt.target]
        if isinstance(stmt, ast.AugAssign):
            self._check_reads(stmt.target)  # x += 1 reads x first
        for t in targets:
            self._kill_target(t)
        if value is not None:
            # AFTER the kills: ``s = make_factory()`` must survive its own
            # statement's rebinding of ``s``
            self._bind_factories(stmt, value)

    def _process_loop(self, body) -> None:
        """Process a loop body twice: the second pass starts from the taints
        the first pass left alive, so a donation whose rebind happens EARLIER
        in the body (next iteration kills before the read) stays silent while
        a genuine loop-carried donation is read at its own call site."""
        self._process_block(body)
        survivors = {k: t for k, t in self.tainted.items()}
        for t in survivors.values():
            t.loop_pass = True
        self._process_block(body)
        # taints re-created by the replay are duplicates of pass one
        for key, taint in list(self.tainted.items()):
            if taint.loop_pass:
                taint.loop_pass = False

    # ----------------------------------------------------------------- helpers

    def _bind_factories(self, stmt, value: ast.AST) -> None:
        """Track ``step = make_lm_train_step(...)`` so later ``step(...)``
        call sites donate."""
        if not isinstance(value, ast.Call):
            return
        positions = self.env.factory_call_positions(value, self.idx, self.fn)
        if not positions:
            return
        targets = stmt.targets if isinstance(stmt, ast.Assign) else [stmt.target]
        for t in targets:
            if isinstance(t, ast.Name):
                self.local_factories[t.id] = positions

    def _collect_donations(self, expr: ast.AST) -> None:
        for node in ast.walk(expr):
            if not isinstance(node, ast.Call):
                continue
            positions, callee = self.env.donating_positions(
                node, self.idx, self.fn, self.local_factories
            )
            if not positions:
                continue
            for expr_str, _arg in donated_arg_exprs(node, positions):
                self.tainted[expr_str] = _Taint(expr_str, node.lineno, callee or "jitted callable")

    def _check_reads(self, expr: ast.AST) -> None:
        if not self.tainted:
            return
        for node in ast.walk(expr):
            if not isinstance(node, (ast.Name, ast.Attribute, ast.Subscript)):
                continue
            try:
                key = ast.unparse(node)
            except ValueError:  # pragma: no cover
                continue
            taint = self.tainted.get(key)
            if taint is None:
                continue
            self._report(
                node.lineno,
                node.col_offset,
                f"{key} was donated to '{taint.callee}' at line {taint.line} and read "
                f"again here: the buffer is deleted after the donating call; rebind it "
                f"from the call's outputs before any further use"
                + (
                    " (this read happens on the loop's next iteration)"
                    if taint.loop_pass
                    else ""
                ),
            )

    def _kill_target(self, target: ast.AST) -> None:
        """An assignment to an expression (or its base name) ends its taint."""
        targets = target.elts if isinstance(target, (ast.Tuple, ast.List)) else [target]
        for t in targets:
            if isinstance(t, ast.Starred):
                t = t.value
            try:
                key = ast.unparse(t)
            except ValueError:  # pragma: no cover
                continue
            self.tainted.pop(key, None)
            self.local_factories.pop(key, None)
            # rebinding the base kills every taint reached through it:
            # ``state = ...`` clears ``state['cache']``
            base = key.split(".", 1)[0].split("[", 1)[0]
            for k in list(self.tainted):
                if k == key:
                    continue
                k_base = k.split(".", 1)[0].split("[", 1)[0]
                if k_base == base and (k.startswith(key) or key == k_base):
                    del self.tainted[k]

    def _report(self, line: int, col: int, message: str) -> None:
        dedup = (line, message.split(";")[0])
        if dedup in self._reported:
            return
        self._reported.add(dedup)
        self.findings.append(
            Finding(
                "use-after-donate",
                self.idx.source.relpath,
                line,
                col,
                message,
                symbol=self.fn.qualname,
            )
        )


@register(
    "use-after-donate",
    "reads of a buffer after it was passed in a donate_argnums position (dataflow)",
)
def check(project: Project):
    env = DonationEnv(project.graph)
    for idx in project.graph.indexes:
        for fn in idx.functions.values():
            yield from _FunctionWalk(fn, idx, env).run()
