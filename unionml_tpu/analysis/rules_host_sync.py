"""Rule ``host-sync``: host synchronization / host↔device traffic in hot code.

Two walks, two severity models:

1. **Traced bodies** (functions reachable from any ``@jax.jit`` / ``shard_map``
   body): here a host sync is a *correctness* hazard — ``np.asarray`` /
   ``np.array`` on a tracer, ``.item()``, ``.block_until_ready()``,
   ``jax.device_get``, explicit ``bool()/int()/float()`` conversions, and
   implicit ``bool()`` via ``if``/``while`` tests built from ``jnp`` calls all
   either fail at trace time or silently bake a constant into the program.
2. **Host hot paths** (functions reachable from a ``# graftlint: hot-path``
   root, pruned at ``# graftlint: off-path``): here the hazard is a *stall* —
   ``.item()``, ``.block_until_ready()`` and ``jax.device_get`` serialize the
   host on the device stream, which is exactly what the pipelined decode
   engine exists to avoid. The designed once-per-tick fused fetch carries a
   reasoned suppression; anything new fails CI.

The walk is a call-graph traversal (``CallGraph.reachable``), not a syntactic
scan: a helper three calls below ``DecodeEngine.step`` is as hot as ``step``.

v2 (dataflow retrofit): the host-hot-path conversion check follows ALIASES,
not just spellings. v1 flagged ``bool(self._active_dev)`` by the ``_dev``
suffix alone, so ``x = self._tokens; bool(x)`` — where ``__init__`` assigned
``self._tokens = jnp.zeros(...)`` — sailed through. The dataflow layer's
provenance pass (:func:`~unionml_tpu.analysis.dataflow.device_locals`) tracks
device-resident class attributes and the locals assigned from them, so the
renamed value is caught.
"""

import ast
from typing import Iterator, List, Set, Tuple

from unionml_tpu.analysis.callgraph import FunctionInfo, dotted
from unionml_tpu.analysis.core import Finding, Project, register
from unionml_tpu.analysis.dataflow import device_attrs, device_locals, shape_locals

#: numpy entry points that force a tracer onto the host
_NP_SYNCS = {"asarray", "array"}
#: conversions that concretize an abstract value
_CONVERSIONS = {"bool", "int", "float"}


def _expr_mentions_shape(node: ast.AST) -> bool:
    """Shape/size arithmetic is trace-time Python — never a sync."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Attribute) and sub.attr in ("shape", "ndim", "size", "dtype"):
            return True
        if isinstance(sub, ast.Call) and isinstance(sub.func, ast.Name) and sub.func.id == "len":
            return True
    return False


def _jnp_call_in(node: ast.AST, idx) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call):
            name = dotted(sub.func) or ""
            root = name.split(".", 1)[0]
            target = idx.imports.get(root, root)
            if target.startswith("jax.numpy") or target == "jax.numpy":
                return True
    return False


def _finding(fn: FunctionInfo, node: ast.AST, message: str) -> Finding:
    return Finding(
        "host-sync", fn.module.source.relpath, node.lineno, node.col_offset,
        message, symbol=fn.qualname,
    )


def _check_traced_body(fn: FunctionInfo) -> Iterator[Finding]:
    idx = fn.module
    shape_derived: Set[str] = None  # lazily: aliases of shape arithmetic
    for node in ast.walk(fn.node):
        if isinstance(node, ast.Call):
            name = dotted(node.func) or ""
            leaf = name.rsplit(".", 1)[-1]
            root = name.split(".", 1)[0]
            root_target = idx.imports.get(root, root)
            if leaf in _NP_SYNCS and root_target == "numpy":
                yield _finding(
                    fn, node,
                    f"{root}.{leaf}() inside a traced body concretizes its argument "
                    "(TracerArrayConversionError on a tracer, baked constant otherwise); "
                    "use jnp equivalents or hoist to the host",
                )
            elif isinstance(node.func, ast.Attribute) and node.func.attr == "item" and not node.args:
                yield _finding(fn, node, ".item() inside a traced body forces a host sync")
            elif isinstance(node.func, ast.Attribute) and node.func.attr == "block_until_ready":
                yield _finding(fn, node, ".block_until_ready() inside a traced body is a host sync")
            elif idx.resolves_to(node.func, "jax.device_get", "jax.device_put"):
                yield _finding(
                    fn, node,
                    f"{dotted(node.func)}() inside a traced body moves data through the host",
                )
            elif isinstance(node.func, ast.Name) and node.func.id in _CONVERSIONS and node.args:
                arg = node.args[0]
                if isinstance(arg, ast.Constant) or _expr_mentions_shape(arg):
                    continue
                if shape_derived is None:
                    shape_derived = shape_locals(fn)
                # dataflow: ``num_tokens, _ = gates.shape`` makes num_tokens
                # trace-time python — int(num_tokens * k) is not a sync. Any
                # shape-derived name in the expression marks it trace-time
                # arithmetic (silence over noise: mixed expressions are rare)
                names = {
                    sub.id for sub in ast.walk(arg) if isinstance(sub, ast.Name)
                }
                if names & shape_derived:
                    continue
                yield _finding(
                    fn, node,
                    f"{node.func.id}() on a traced value concretizes it "
                    "(ConcretizationTypeError or a baked constant)",
                )
        elif isinstance(node, (ast.If, ast.While)) and _jnp_call_in(node.test, idx):
            yield _finding(
                fn, node.test,
                "branching on a jnp expression inside a traced body is an implicit "
                "bool() host sync; use jnp.where / lax.cond",
            )


def _device_names_in(arg: ast.AST, fn: FunctionInfo, aliases: Set[str],
                     dev_attrs: Set[str]) -> List[str]:
    """Names/attrs in ``arg`` provably holding device values: the ``_dev``
    suffix convention, device-aliased locals, and device class attributes."""
    hits: List[str] = []
    for sub in ast.walk(arg):
        if isinstance(sub, ast.Name):
            if sub.id.endswith("_dev") or sub.id in aliases:
                hits.append(sub.id)
        elif isinstance(sub, ast.Attribute):
            if sub.attr.endswith("_dev"):
                hits.append(sub.attr)
            elif (
                isinstance(sub.value, ast.Name)
                and sub.value.id == "self"
                and sub.attr in dev_attrs
            ):
                hits.append(f"self.{sub.attr}")
    return hits


def _check_host_hot_path(fn: FunctionInfo) -> Iterator[Finding]:
    idx = fn.module
    aliases: Set[str] = None  # computed lazily: most hot functions never convert
    dev_attrs: Set[str] = None
    for node in ast.walk(fn.node):
        if not isinstance(node, ast.Call):
            continue
        if isinstance(node.func, ast.Attribute) and node.func.attr == "item" and not node.args:
            yield _finding(
                fn, node,
                ".item() on the steady-state host path blocks on the device stream",
            )
        elif isinstance(node.func, ast.Attribute) and node.func.attr == "block_until_ready":
            yield _finding(
                fn, node,
                ".block_until_ready() on the steady-state host path stalls dispatch-ahead",
            )
        elif idx.resolves_to(node.func, "jax.device_get"):
            yield _finding(
                fn, node,
                "jax.device_get on the steady-state host path serializes host and device; "
                "fuse fetches or move the consumer off-path",
            )
        elif isinstance(node.func, ast.Name) and node.func.id in _CONVERSIONS and node.args:
            # only flag conversions of PROVABLY device-resident state: the
            # `_dev`-suffix convention, plus the dataflow provenance pass
            # (device class attrs and the locals aliasing them)
            if aliases is None:
                aliases = device_locals(fn, idx)
                dev_attrs = device_attrs(idx, fn.class_name) if fn.class_name else set()
            hits = _device_names_in(node.args[0], fn, aliases, dev_attrs)
            if hits:
                yield _finding(
                    fn, node,
                    f"{node.func.id}() on device-resident value(s) {', '.join(sorted(set(hits)))} "
                    "fetches to the host every tick; keep the decision on device "
                    "or batch the fetch",
                )


@register(
    "host-sync",
    "host syncs/transfers inside traced bodies or hot host paths (call-graph walk)",
)
def check(project: Project) -> Iterator[Finding]:
    graph = project.graph
    traced: Set[Tuple[str, str]] = graph.reachable(graph.trace_roots())
    hot: Set[Tuple[str, str]] = graph.reachable(
        graph.hot_roots(), stop_markers=("off-path",), skip_traced=True
    )
    emitted: List[Tuple] = []
    for key in sorted(traced):
        fn = graph.by_key[key]
        for f in _check_traced_body(fn):
            emitted.append(f)
    for key in sorted(hot - traced):
        fn = graph.by_key[key]
        for f in _check_host_hot_path(fn):
            emitted.append(f)
    yield from emitted
