"""Rule ``lock-order``: deadlock cycles and blocking calls held under a lock.

The serving stack runs three kinds of threads against shared locks: asyncio
handler threads (submit paths), the engine worker, and background builders.
Two hazards follow, both checked over the dataflow layer's lock model and
interprocedural summaries (:mod:`unionml_tpu.analysis.dataflow`):

- **lock-order cycles.** Every lexical ``with <lock>:`` acquisition made while
  another lock is held adds an edge ``held -> acquired`` to a project-wide
  graph — including acquisitions made by CALLEES, resolved through the call
  graph (``with self._lock: self.scheduler.submit(...)`` contributes
  ``batcher._lock -> scheduler._lock`` because ``submit`` acquires the
  scheduler's lock). A cycle in that graph means two threads can interleave
  the orders and deadlock; every edge of the cycle is reported at its site.
  ``# lock-order: a < b`` comment hints declare nesting the walker cannot see
  (cross-thread protocols); hint edges participate in cycle detection and are
  reported with the hint's location.

- **blocking-under-lock.** A call that blocks unboundedly — ``.result()`` /
  ``.join()`` / ``.wait()`` without timeouts, ``lock.acquire()``,
  ``time.sleep``, ``subprocess.run``, device fetches (``jax.device_get``,
  ``.block_until_ready()``) — while a lock is held stalls every thread that
  needs the lock for as long as the blocker runs, which is how a "2ms
  critical section" becomes a seconds-long convoy. Interprocedural: a call
  into a scanned function that transitively blocks is flagged with its chain.
  ``cond.wait()`` on the HELD condition is exempt (the wait releases it — the
  condition-variable protocol).

Scope note: nested ``def``s inside a ``with`` block are skipped — they run
later, under their own frames, not under this acquisition.
"""

import ast
from typing import Dict, List, Optional, Set, Tuple

from unionml_tpu.analysis.callgraph import FunctionInfo, ModuleIndex
from unionml_tpu.analysis.core import Finding, Project, register
from unionml_tpu.analysis.dataflow import (
    LockKey,
    LockModel,
    Summaries,
    _wait_receiver,
    blocking_reason,
    shared_analyses,
)


def _fmt(key: LockKey) -> str:
    mod, cls, attr = key
    short = mod.rsplit(".", 1)[-1]
    return f"{short}.{cls}.{attr}" if cls else f"{short}.{attr}"


class _HeldWalker(ast.NodeVisitor):
    """Walks one function with the lexical stack of held locks."""

    def __init__(
        self,
        fn: FunctionInfo,
        idx: ModuleIndex,
        locks: LockModel,
        summaries: Summaries,
        edges: Dict[Tuple[LockKey, LockKey], List[Tuple[str, int, str]]],
    ) -> None:
        self.fn = fn
        self.idx = idx
        self.locks = locks
        self.summaries = summaries
        self.edges = edges
        self.held: List[LockKey] = []
        self.findings: List[Finding] = []
        self._depth = 0

    def visit(self, node):  # noqa: D102 - skip nested frames
        if self._depth and isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda, ast.ClassDef)
        ):
            return
        self._depth += 1
        super().visit(node)
        self._depth -= 1

    def _visit_with(self, node) -> None:
        acquired: List[LockKey] = []
        for item in node.items:
            key = self.locks.lock_of(item.context_expr, self.idx, self.fn.class_name)
            if key is not None:
                for held in self.held:
                    if held != key:
                        self.edges.setdefault((held, key), []).append(
                            (self.idx.source.relpath, node.lineno, self.fn.qualname)
                        )
                acquired.append(key)
        self.held.extend(acquired)
        self.generic_visit(node)
        for _ in acquired:
            self.held.pop()

    visit_With = _visit_with
    visit_AsyncWith = _visit_with

    def visit_Call(self, node: ast.Call) -> None:
        if self.held:
            self._check_call_under_lock(node)
        self.generic_visit(node)

    def _check_call_under_lock(self, node: ast.Call) -> None:
        reason = blocking_reason(node, self.idx)
        if reason is not None:
            recv = _wait_receiver(node)
            if recv is not None:
                key = self.locks.lock_of(recv, self.idx, self.fn.class_name)
                if key is not None and key in self.held:
                    return  # cond.wait() releases the held condition: the protocol
            self.findings.append(
                Finding(
                    "lock-order",
                    self.idx.source.relpath,
                    node.lineno,
                    node.col_offset,
                    f"blocking call under lock {_fmt(self.held[-1])}: {reason} — "
                    f"every thread needing the lock convoys behind it; move the "
                    f"blocking work outside the critical section",
                    symbol=self.fn.qualname,
                )
            )
            return
        callee = self.summaries.resolve_call(self.fn, node)
        if callee is None:
            return
        info = self.summaries.blocking.get(callee.key)
        if info is not None:
            chain = " -> ".join(info.chain)
            self.findings.append(
                Finding(
                    "lock-order",
                    self.idx.source.relpath,
                    node.lineno,
                    node.col_offset,
                    f"call under lock {_fmt(self.held[-1])} blocks: {chain} "
                    f"reaches '{info.reason}' — move the blocking work outside "
                    f"the critical section",
                    symbol=self.fn.qualname,
                )
            )
            return
        callee_locks = self.summaries.acquires.get(callee.key)
        if callee_locks:
            for held in self.held:
                for key in callee_locks:
                    if held != key:
                        self.edges.setdefault((held, key), []).append(
                            (self.idx.source.relpath, node.lineno,
                             f"{self.fn.qualname} via {callee.qualname}")
                        )


def _find_cycles(
    edges: Dict[Tuple[LockKey, LockKey], List[Tuple[str, int, str]]]
) -> List[List[LockKey]]:
    """Elementary cycles in the (tiny) lock graph via DFS; each reported once,
    anchored at its smallest node for determinism."""
    graph: Dict[LockKey, Set[LockKey]] = {}
    for (a, b) in edges:
        graph.setdefault(a, set()).add(b)
    cycles: List[List[LockKey]] = []
    seen_cycles: Set[Tuple[LockKey, ...]] = set()

    def dfs(start: LockKey, node: LockKey, path: List[LockKey]) -> None:
        for nxt in sorted(graph.get(node, ())):
            if nxt == start:
                # canonicalize rotation so each cycle reports once
                cycle = path[:]
                pivot = min(range(len(cycle)), key=lambda i: cycle[i])
                canon = tuple(cycle[pivot:] + cycle[:pivot])
                if canon not in seen_cycles:
                    seen_cycles.add(canon)
                    cycles.append(list(canon))
            elif nxt not in path and nxt > start:
                dfs(start, nxt, path + [nxt])

    for start in sorted(graph):
        dfs(start, start, [start])
    return cycles


@register(
    "lock-order",
    "lock-acquisition cycles (deadlock) and blocking calls held under a lock",
)
def check(project: Project):
    graph = project.graph
    locks, summaries = shared_analyses(graph)
    if not locks.locks:
        return
    edges: Dict[Tuple[LockKey, LockKey], List[Tuple[str, int, str]]] = {}
    walkers: List[_HeldWalker] = []
    for idx in graph.indexes:
        for fn in idx.functions.values():
            walker = _HeldWalker(fn, idx, locks, summaries, edges)
            walker.visit(fn.node)
            walkers.append(walker)
    # declared-order hints contribute edges the walker cannot see
    for idx in graph.indexes:
        for line, a, b in getattr(idx.source, "lock_hints", []):
            for ka in locks.by_attr(idx.name, a):
                for kb in locks.by_attr(idx.name, b):
                    if ka != kb:
                        edges.setdefault((ka, kb), []).append(
                            (idx.source.relpath, line, "# lock-order hint")
                        )
    for walker in walkers:
        yield from walker.findings
    for cycle in _find_cycles(edges):
        order = " -> ".join(_fmt(k) for k in cycle + [cycle[0]])
        # report the cycle at every participating edge site so each side of
        # the inversion sees it in review
        for i, a in enumerate(cycle):
            b = cycle[(i + 1) % len(cycle)]
            for path, line, symbol in edges.get((a, b), [])[:1]:
                yield Finding(
                    "lock-order",
                    path,
                    line,
                    0,
                    f"lock-order cycle {order}: two threads taking these locks "
                    f"in different orders can deadlock; pick one global order "
                    f"(declare it with '# lock-order: a < b') and restructure "
                    f"this acquisition",
                    symbol=symbol,
                )
