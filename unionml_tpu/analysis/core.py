"""graftlint core: findings, suppressions, source model, rule registry, runner.

The linter is pure ``ast`` + source-comment analysis — it never imports the
code it checks, so it runs in CI without a device (and without paying jax
import time per file). Three source-comment conventions drive it:

- ``# graftlint: disable=RULE[,RULE2] -- reason`` suppresses the named rules on
  that line (inline) or on the next code line (standalone comment line). The
  reason string is REQUIRED: a suppression without one is itself a finding
  (rule ``suppression``), so every silenced site documents why it is safe.
- ``# graftlint: hot-path`` on a ``def`` line declares a host-side hot root for
  the host-sync call-graph walk (e.g. ``DecodeEngine.step``); jit/shard_map
  bodies are discovered automatically and need no marker.
- ``# graftlint: off-path`` on a ``def`` line prunes the walk at functions that
  are reachable from a hot root but are not steady-state (admission, error
  recovery, compile paths).
- ``# guarded-by: <lock>`` on a ``self.x = ...`` line in ``__init__`` declares
  the attribute's owning lock for the lock-discipline rule.
- ``# lock-order: a < b`` declares that lock ``a`` may be held while acquiring
  lock ``b`` — a nesting the static walker cannot see (cross-thread
  protocols); the declared edges participate in lock-order cycle detection.
- ``# owns: <resource>`` on a ``def`` line declares that the function takes
  ownership of a resource class (see ``rules_resources``) and must release it;
  ``# transfers: <resource>`` declares that ownership leaves through the
  return value (callers binding the result become owners); ``# holds:
  <resource>`` on a ``self.x = ...`` line in ``__init__`` declares an
  attribute that stores live resources, so overwriting it without a release
  is a leak.
- ``# lock-leaf`` on a lock's ``__init__`` (or module-level) assignment
  declares it a LEAF in the lock hierarchy: no other project lock may be
  acquired and no blocking call made while it is held (rule ``lock-leaf``).
- ``# fires-outside-lock`` on a callback-registration ``def`` (a method that
  appends its callable parameter into instance state) declares that the
  registered callbacks are always invoked OUTSIDE the class's locks; the
  ``callback-under-lock`` rule verifies every firing site.

Suppressions anchor to LOGICAL lines: a finding anywhere inside a multi-line
statement (or on a decorated ``def``'s signature) is silenced by a suppression
on any physical line of that same statement, or on the standalone comment line
above its first line.
"""

import ast
import dataclasses
import hashlib
import json
import re
import time
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Tuple

#: JSON report schema version (bump on any shape change; pinned by tests).
#: v2: interprocedural rule families (use-after-donate / lock-order /
#: async-blocking), the ``baselined`` findings list, and SARIF output.
#: v3: the ``timings`` per-family wall-time map (budget regressions must be
#: attributable to a family, not "environmental").
REPORT_VERSION = 3

#: a comment is a DIRECTIVE only when the linter's name is followed by a
#: colon; prose comments that merely mention the linter by name are not parsed
_DIRECTIVE_RE = re.compile(r"graftlint\s*:")
_SUPPRESS_RE = re.compile(
    r"#\s*graftlint:\s*disable=([A-Za-z0-9_\-]+(?:\s*,\s*[A-Za-z0-9_\-]+)*)"
    r"(?:\s*--\s*(?P<reason>.*\S))?\s*$"
)
_MARKER_RE = re.compile(r"#\s*graftlint:\s*(hot-path|off-path)\b")
_GUARDED_RE = re.compile(r"#\s*guarded-by:\s*([A-Za-z_][A-Za-z0-9_]*)")
_LOCK_ORDER_RE = re.compile(
    r"#\s*lock-order:\s*([A-Za-z_][A-Za-z0-9_]*)\s*(?:<|->)\s*([A-Za-z_][A-Za-z0-9_]*)"
)
#: resource-contract annotations (rules_resources): comma-separated resource
#: class names from the spec table — the ``owns``/``transfers`` forms sit on a
#: def line, the ``holds`` form on an __init__ attribute assignment
_RESOURCE_LIST = r"([A-Za-z][A-Za-z0-9_\-]*(?:\s*,\s*[A-Za-z][A-Za-z0-9_\-]*)*)"
_OWNS_RE = re.compile(r"#\s*owns:\s*" + _RESOURCE_LIST)
_TRANSFERS_RE = re.compile(r"#\s*transfers:\s*" + _RESOURCE_LIST)
_HOLDS_RE = re.compile(r"#\s*holds:\s*" + _RESOURCE_LIST)
#: concurrency contracts (rules_races): ``lock-leaf`` on a lock assignment,
#: ``fires-outside-lock`` on a callback-registration def
_LOCK_LEAF_RE = re.compile(r"#\s*lock-leaf\b")
_FIRES_OUTSIDE_RE = re.compile(r"#\s*fires-outside-lock\b")

#: substrings that gate the tokenize-based comment pass: a file mentioning
#: none of them carries no graftlint annotation, and re-tokenizing every
#: source was a measurable slice of the lint budget
_COMMENT_KEYWORDS = (
    "graftlint", "guarded-by", "lock-order", "lock-leaf",
    "fires-outside-lock", "owns:", "transfers:", "holds:",
)


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation at a source location."""

    rule: str
    path: str
    line: int
    col: int
    message: str
    #: enclosing function/method qualname ("" at module/class level)
    symbol: str = ""
    suppressed: bool = False
    #: the suppression's reason string (suppressed findings only)
    reason: str = ""

    def format(self) -> str:
        where = f" [{self.symbol}]" if self.symbol else ""
        return f"{self.path}:{self.line}:{self.col}: {self.rule}: {self.message}{where}"

    def fingerprint(self, occurrence: int = 0) -> str:
        """Line-independent identity for the baseline mechanism: editing an
        unrelated part of the file must not invalidate recorded findings, so
        the line number stays out and embedded numbers are normalized."""
        normalized = re.sub(r"\d+", "#", self.message)
        # cwd-relative path: absolute and repo-relative invocations (CI runs
        # from the repo root either way) must produce the same fingerprint
        p = Path(self.path)
        if p.is_absolute():
            try:
                p = p.relative_to(Path.cwd())
            except ValueError:
                pass
        payload = "|".join([self.rule, p.as_posix(), self.symbol, normalized, str(occurrence)])
        return hashlib.sha1(payload.encode()).hexdigest()[:20]

    def as_dict(self) -> Dict[str, object]:
        d = {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "message": self.message,
            "symbol": self.symbol,
        }
        if self.suppressed:
            d["reason"] = self.reason
        return d


@dataclasses.dataclass
class Suppression:
    """One ``# graftlint: disable=...`` comment (parsed, usage-tracked)."""

    rules: Tuple[str, ...]
    reason: str
    line: int  # the code line it applies to


class SourceModule:
    """One parsed source file: AST + per-line suppressions/markers/annotations."""

    def __init__(self, path: Path, relpath: str, name: str, text: str) -> None:
        self.path = path
        self.relpath = relpath
        #: dotted module name when under a package root, else the bare stem
        self.name = name
        self.text = text
        self.lines = text.splitlines()
        self.tree = ast.parse(text, filename=str(path))
        #: code line -> Suppression
        self.suppressions: Dict[int, Suppression] = {}
        #: logical-line start -> suppressions anchored to that statement
        self._suppressions_by_anchor: Dict[int, List[Suppression]] = {}
        #: def line -> "hot-path" | "off-path"
        self.markers: Dict[int, str] = {}
        #: code line -> lock attribute name (from ``# guarded-by: <lock>``)
        self.guards: Dict[int, str] = {}
        #: ``# lock-order: a < b`` hints: (line, a, b)
        self.lock_hints: List[Tuple[int, str, str]] = []
        #: code line -> resource classes (``# owns:`` / ``# transfers:`` on a
        #: def line, ``# holds:`` on an ``__init__`` attribute assignment)
        self.owns: Dict[int, Tuple[str, ...]] = {}
        self.transfers: Dict[int, Tuple[str, ...]] = {}
        self.holds: Dict[int, Tuple[str, ...]] = {}
        #: code lines of ``lock-leaf`` lock assignments and
        #: ``fires-outside-lock`` registration defs (rules_races contracts)
        self.lock_leaves: set = set()
        self.fires_outside: set = set()
        #: malformed-comment findings emitted by the parse (rule ``suppression``)
        self.comment_findings: List[Finding] = []
        #: physical line -> first line of its logical statement (suppression
        #: anchoring: a multi-line call or a decorated def is ONE logical line).
        #: Built lazily: only annotated files (and files a finding lands in)
        #: ever consult it, and the full-tree walk it needs was a measurable
        #: slice of the lint budget across ~200 unannotated modules.
        self._anchors: Optional[Dict[int, int]] = None
        self._code_lines: List[int] = []
        self._parse_comments()

    def _build_anchors(self) -> None:
        if self._anchors is not None:
            return
        self._anchors = {}
        # ast.walk is breadth-first: parents before children, so inner
        # statements override the span their compound parent claimed — a line
        # anchors to its INNERMOST statement. A def's decorators and signature
        # continuation lines anchor to the decorated-def start (no body
        # statement claims them), which is the decorated-def anchoring rule.
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.stmt):
                continue
            start = min(
                [node.lineno]
                + [d.lineno for d in getattr(node, "decorator_list", [])]
            )
            end = getattr(node, "end_lineno", None) or node.lineno
            for ln in range(start, end + 1):
                self._anchors[ln] = start
        self._code_lines = sorted(self._anchors)

    def logical_anchor(self, line: int) -> int:
        """First line of the logical statement containing ``line``."""
        self._build_anchors()
        return self._anchors.get(line, line)

    def _next_code_line(self, line: int) -> int:
        """The first statement-covered line after ``line`` (standalone-comment
        targets skip blank lines and further comments)."""
        import bisect

        i = bisect.bisect_right(self._code_lines, line)
        if i < len(self._code_lines):
            return self._code_lines[i]
        return line + 1

    def _iter_comments(self):
        """(line, col, comment_text, standalone) for every REAL comment token —
        tokenize-based so docstrings talking about the conventions never match."""
        import io
        import tokenize

        try:
            for tok in tokenize.generate_tokens(io.StringIO(self.text).readline):
                if tok.type == tokenize.COMMENT:
                    line, col = tok.start
                    standalone = not self.lines[line - 1][:col].strip()
                    yield line, col, tok.string, standalone
        except tokenize.TokenError:  # unterminated constructs: ast already parsed, skip
            return

    def _parse_comments(self) -> None:
        if not any(k in self.text for k in _COMMENT_KEYWORDS):
            return
        self._build_anchors()
        for line, col, comment, standalone in self._iter_comments():
            # a standalone comment line governs the next code line
            target = self._next_code_line(line) if standalone else line
            if _DIRECTIVE_RE.search(comment):
                self._parse_graftlint_comment(line, col, comment, target)
            guarded = _GUARDED_RE.search(comment)
            if guarded:
                self.guards[target] = guarded.group(1)
            order = _LOCK_ORDER_RE.search(comment)
            if order:
                self.lock_hints.append((line, order.group(1), order.group(2)))
            for regex, table in (
                (_OWNS_RE, self.owns),
                (_TRANSFERS_RE, self.transfers),
                (_HOLDS_RE, self.holds),
            ):
                m = regex.search(comment)
                if m:
                    table[target] = tuple(r.strip() for r in m.group(1).split(","))
            if _LOCK_LEAF_RE.search(comment):
                self.lock_leaves.add(target)
            if _FIRES_OUTSIDE_RE.search(comment):
                self.fires_outside.add(target)

    def _parse_graftlint_comment(self, line: int, col: int, comment: str, target: int) -> None:
        marker = _MARKER_RE.search(comment)
        if marker:
            self.markers[line] = marker.group(1)
            return
        m = _SUPPRESS_RE.search(comment)
        if m is None:
            self.comment_findings.append(
                Finding(
                    "suppression", self.relpath, line, col,
                    "unparseable graftlint comment (expected "
                    "'# graftlint: disable=RULE -- reason' or a hot-path/off-path marker)",
                )
            )
            return
        rules = tuple(r.strip() for r in m.group(1).split(","))
        reason = (m.group("reason") or "").strip()
        if not reason:
            self.comment_findings.append(
                Finding(
                    "suppression", self.relpath, line, col,
                    f"suppression of {', '.join(rules)} requires a reason "
                    "('# graftlint: disable=RULE -- why this is safe')",
                )
            )
            return
        unknown = [r for r in rules if r not in RULES and r != "all"]
        if unknown:
            self.comment_findings.append(
                Finding(
                    "suppression", self.relpath, line, col,
                    f"suppression names unknown rule(s) {', '.join(unknown)} "
                    f"(known: {', '.join(sorted(RULES))})",
                )
            )
            return
        sup = Suppression(rules, reason, target)
        self.suppressions[target] = sup
        self._suppressions_by_anchor.setdefault(self.logical_anchor(target), []).append(sup)

    def suppression_for(self, rule: str, line: int) -> Optional[Suppression]:
        """A suppression covering ``line``: same physical line, or anchored to
        the same logical statement (multi-line calls, decorated defs)."""
        candidates = []
        direct = self.suppressions.get(line)
        if direct is not None:
            candidates.append(direct)
        candidates.extend(self._suppressions_by_anchor.get(self.logical_anchor(line), ()))
        for sup in candidates:
            if rule in sup.rules or "all" in sup.rules:
                return sup
        return None


class Rule:
    """A registered lint rule: ``check(project)`` yields raw findings."""

    def __init__(self, name: str, summary: str, check, family: str) -> None:
        self.name = name
        self.summary = summary
        self.check = check
        #: rule family = registering module minus the ``rules_`` prefix
        #: ("races", "resources", ...) — the unit of ``--only`` selection and
        #: of per-family wall-time attribution
        self.family = family


#: rule registry: name -> Rule (populated by the rule modules at import)
RULES: Dict[str, Rule] = {}


def register(name: str, summary: str):
    """Decorator registering ``check(project)`` under ``name``. The family is
    derived from the registering module, so a new rule module lands in the
    ``--only`` catalog, the SARIF catalog, and the timing report with no
    registration beyond its import in :func:`_load_rule_modules`."""

    def wrap(check):
        family = check.__module__.rsplit(".", 1)[-1]
        if family.startswith("rules_"):
            family = family[len("rules_"):]
        RULES[name] = Rule(name, summary, check, family)
        return check

    return wrap


def families() -> Dict[str, List[str]]:
    """family name -> sorted rule names (the ``--only FAMILY`` catalog)."""
    _load_rule_modules()
    out: Dict[str, List[str]] = {}
    for name, rule in RULES.items():
        out.setdefault(rule.family, []).append(name)
    return {fam: sorted(names) for fam, names in out.items()}


def _module_name(path: Path) -> str:
    """Dotted module name by walking up through ``__init__.py`` packages."""
    parts = [path.stem] if path.stem != "__init__" else []
    parent = path.parent
    while (parent / "__init__.py").exists():
        parts.insert(0, parent.name)
        parent = parent.parent
    return ".".join(parts) or path.stem


def collect_files(paths: Sequence[str]) -> List[Path]:
    files: List[Path] = []
    for p in paths:
        path = Path(p)
        if path.is_dir():
            files.extend(sorted(path.rglob("*.py")))
        elif path.suffix == ".py":
            files.append(path)
    # never lint generated/compiled droppings
    return [f for f in files if "__pycache__" not in f.parts]


class Project:
    """Every parsed module of one lint invocation plus the shared call graph."""

    def __init__(self, paths: Sequence[str]) -> None:
        # rule modules self-register on import; comment parsing validates
        # disable= names against the registry, so load them first
        _load_rule_modules()

        self.paths = list(paths)
        self.modules: List[SourceModule] = []
        self.errors: List[Finding] = []
        for f in collect_files(paths):
            try:
                text = f.read_text()
                self.modules.append(SourceModule(f, str(f), _module_name(f), text))
            except (SyntaxError, UnicodeDecodeError) as exc:
                self.errors.append(
                    Finding(
                        "parse", str(f), getattr(exc, "lineno", 1) or 1, 0,
                        f"file does not parse: {exc.msg if hasattr(exc, 'msg') else exc}",
                    )
                )
        from unionml_tpu.analysis.callgraph import CallGraph

        self.graph = CallGraph(self.modules)
        self._by_name = {m.name: m for m in self.modules}

    def module(self, name: str) -> Optional[SourceModule]:
        return self._by_name.get(name)


def _load_rule_modules() -> None:
    """Import every rule module for its registration side effect."""
    from unionml_tpu.analysis import (  # noqa: F401
        rules_async,
        rules_deadlock,
        rules_donation,
        rules_exceptions,
        rules_host_sync,
        rules_locks,
        rules_races,
        rules_resources,
        rules_retrace,
        rules_sharding,
    )


def load_baseline(path: str) -> Dict[str, Dict[str, object]]:
    """Read a ``--baseline`` file: fingerprint -> recorded finding summary."""
    with open(path) as fh:
        data = json.load(fh)
    if not isinstance(data, dict) or "findings" not in data:
        raise ValueError(f"{path} is not a graftlint baseline file")
    return dict(data["findings"])


def baseline_payload(findings: Sequence["Finding"]) -> Dict[str, object]:
    """The ``--write-baseline`` file body for the given active findings."""
    recorded: Dict[str, Dict[str, object]] = {}
    counts: Dict[Tuple, int] = {}
    for f in sorted(findings, key=lambda f: (f.path, f.line, f.col, f.rule)):
        key = (f.rule, f.path, f.symbol)
        occurrence = counts.get(key, 0)
        counts[key] = occurrence + 1
        recorded[f.fingerprint(occurrence)] = {
            "rule": f.rule, "path": f.path, "symbol": f.symbol, "message": f.message,
        }
    return {"graftlint_baseline": 1, "findings": recorded}


def _split_baselined(
    findings: List["Finding"], baseline: Dict[str, Dict[str, object]]
) -> Tuple[List["Finding"], List["Finding"]]:
    """Partition findings into (new, baselined) by line-independent
    fingerprint; occurrence counting keeps N recorded duplicates silencing at
    most N live ones."""
    new: List[Finding] = []
    old: List[Finding] = []
    counts: Dict[Tuple, int] = {}
    for f in findings:
        key = (f.rule, f.path, f.symbol)
        occurrence = counts.get(key, 0)
        counts[key] = occurrence + 1
        (old if f.fingerprint(occurrence) in baseline else new).append(f)
    return new, old


def run_lint(
    paths: Sequence[str],
    rules: Optional[Sequence[str]] = None,
    *,
    baseline: Optional[Dict[str, Dict[str, object]]] = None,
    restrict: Optional[Sequence[str]] = None,
) -> "LintResult":
    """Lint ``paths`` with the selected (default: all) rules.

    ``baseline`` (see :func:`load_baseline`) moves findings whose fingerprint
    is recorded into ``result.baselined`` — reported, but not failing — so a
    widened scope can land with its pre-existing findings inventoried and only
    NEW ones breaking the build.

    ``restrict`` keeps the full ``paths`` scan (the interprocedural passes
    need the whole call graph for context) but reports only findings located
    in the named files — the ``--paths`` incremental / pre-commit mode.
    """
    # rule modules self-register on import (Project also does this, but rule
    # selection below needs the registry before any Project exists)
    _load_rule_modules()

    selected = list(rules) if rules else sorted(RULES)
    unknown = [r for r in selected if r not in RULES]
    if unknown:
        raise ValueError(f"unknown rule(s): {', '.join(unknown)} (known: {', '.join(sorted(RULES))})")
    t0 = time.perf_counter()
    project = Project(paths)
    timings: Dict[str, float] = {"parse": time.perf_counter() - t0}
    active: List[Finding] = list(project.errors)
    suppressed: List[Finding] = []
    for mod in project.modules:
        active.extend(mod.comment_findings)  # suppression hygiene is not optional
    mods_by_path = {m.relpath: m for m in project.modules}
    for name in selected:
        t0 = time.perf_counter()
        for finding in RULES[name].check(project):
            mod = mods_by_path.get(finding.path)
            sup = mod.suppression_for(name, finding.line) if mod else None
            if sup is not None:
                suppressed.append(
                    dataclasses.replace(finding, suppressed=True, reason=sup.reason)
                )
            else:
                active.append(finding)
        fam = RULES[name].family
        timings[fam] = timings.get(fam, 0.0) + time.perf_counter() - t0
    if restrict is not None:
        wanted = {Path(p).resolve() for p in restrict}
        active = [f for f in active if Path(f.path).resolve() in wanted]
        suppressed = [f for f in suppressed if Path(f.path).resolve() in wanted]
    active.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    suppressed.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    baselined: List[Finding] = []
    if baseline:
        active, baselined = _split_baselined(active, baseline)
    return LintResult(paths=list(paths), rules=selected, files=len(project.modules),
                      findings=active, suppressed=suppressed, baselined=baselined,
                      timings=timings)


@dataclasses.dataclass
class LintResult:
    """One lint run's outcome; ``report()`` is the machine-readable surface."""

    paths: List[str]
    rules: List[str]
    files: int
    findings: List[Finding]
    suppressed: List[Finding]
    #: pre-existing findings recorded in a ``--baseline`` file: reported, not
    #: failing (``ok`` ignores them) — the widened-scope landing mechanism
    baselined: List[Finding] = dataclasses.field(default_factory=list)
    #: wall seconds per rule family, plus "parse" (project build): the budget
    #: attribution surface — a regression names a family, not "environmental"
    timings: Dict[str, float] = dataclasses.field(default_factory=dict)

    @property
    def ok(self) -> bool:
        return not self.findings

    def report(self) -> Dict[str, object]:
        return {
            "graftlint": REPORT_VERSION,
            "paths": self.paths,
            "rules": self.rules,
            "files": self.files,
            "counts": {
                "findings": len(self.findings),
                "suppressed": len(self.suppressed),
                "baselined": len(self.baselined),
            },
            "timings": {fam: round(s, 3) for fam, s in sorted(self.timings.items())},
            "findings": [f.as_dict() for f in self.findings],
            "suppressed": [f.as_dict() for f in self.suppressed],
            "baselined": [f.as_dict() for f in self.baselined],
        }

    def report_json(self) -> str:
        return json.dumps(self.report(), indent=2)

    def sarif(self) -> Dict[str, object]:
        """The SARIF 2.1.0 document (GitHub code scanning compatible)."""
        from unionml_tpu.analysis.sarif import to_sarif

        return to_sarif(self)

    def sarif_json(self) -> str:
        return json.dumps(self.sarif(), indent=2)
