"""Rule family ``resource-leak`` / ``double-release`` / ``unbalanced-transfer``:
path-sensitive paired-resource lifetime checking (graftlint v3).

The serving stack is full of linear resources — things acquired by one call
that MUST reach exactly one release: KV-block pins, prefix-cache refcounts,
engine slots, scheduler tickets, telemetry traces, file handles. v2's rules
could not see the paths between acquire and release; chaos tests found the
leaks, but only on the schedules they happened to exercise. This family walks
the :mod:`unionml_tpu.analysis.cfg` exception-edge CFG instead, so "an
exception between ``pin`` and ``requeue`` drops the pin on the floor" becomes
plain graph reachability.

**Resource spec table.** Each :class:`ResourceSpec` names a resource class and
its acquire/release signatures. Matching is *textual* (leaf method name plus a
receiver-hint substring), deliberately: the acquiring objects are usually
constructor parameters (``self._engine``, ``self.prefix_cache``), which the
call graph cannot type, and a lifetime checker that only fires on resolvable
receivers would be blind exactly where it matters.

========  ===========================================  =============================
class     acquires                                     releases
========  ===========================================  =============================
kv-pin    ``*prefix_cache*.pin(k)``,                   ``*prefix_cache*.unpin(k)``,
          ``k = *engine*.preempt(...)``                ``*engine*.release_preempted(k)``
kv-block  ``k = *allocator*.alloc_blocks(...)``,       ``*allocator*.free_blocks(k)``,
          ``k = self._alloc_slot_blocks(...)``         ``self._free_slot_blocks(k)``,
                                                       ``*prefix_cache*.adopt(k, ...)``
kv-ref    ``k, _ = *prefix_cache*.match(...)``,        ``*prefix_cache*.release(k)``
          ``k, _ = *prefix_cache*.extend(...)``
trace     ``k = *telemetry*.new_trace(...)``           ``*telemetry*.end_trace(k)``
slot      ``k = *engine*.admit(...)`` / ``admit_many`` ``*engine*.cancel(k)``
ticket    ``k = *scheduler*.make_ticket(...)``         ``*scheduler*.submit(k)``,
                                                       ``*scheduler*.requeue(k)``
handle    ``k = open(...)``                            ``k.close()``, ``os.close(k)``
========  ===========================================  =============================

**Finding shapes.**

- *leak-on-exception-path* (rule ``resource-leak``): from an acquire, a path
  along exception edges reaches the function's exceptional exit without a
  release, an ownership transfer, or the value escaping (returned, raised,
  stored into state, handed to another call). Implicit (may-throw) exception
  edges are followed only for ``strict`` resource classes, and only out of
  blocks that call back into project code; explicit ``raise`` edges always.
  The same walk reports *normal-exit* leaks (classes with ``exit_leak``) and
  *loop-carried* acquires (the back edge re-runs the acquire while the
  previous one is still held).
- *double-release* (rule ``double-release``): from a release, a path with no
  re-acquire, rebind, or escape of the key reaches a second release.
- *unbalanced-transfer* (rule ``unbalanced-transfer``): a function annotated
  ``# transfers: <class>`` releases the resource on a path that still returns
  it — both sides of the transfer would release.

**Ownership contracts.** Three comment annotations (parsed in
:mod:`unionml_tpu.analysis.core`, same family as ``# guarded-by:``):

- ``# transfers: <class>`` on a ``def``: the return value carries the
  resource; callers acquire it, this function must not also release what it
  returns.
- ``# owns: <class>`` on a ``def``: this function is the release point for
  resources handed to it. The contract is checked — a function annotated
  ``owns`` that no longer releases (directly or via a callee that
  releases/owns) is itself a finding, with its callers as the witness chain.
- ``# holds: <class>`` on a ``self.<attr> = ...`` line in ``__init__``: the
  attribute stores live resources; any other plain overwrite of it must sit
  in a function that releases the class (or is annotated ``owns``), and
  swap-reads (``a, self.x = self.x, []``) are exempt.

Summaries (which functions release/return which classes) propagate over v2's
resolved call graph to a fixpoint, so ``self.discard_salvage()`` counts as a
kv-pin release inside ``_capture_salvage`` without any annotation. Everything
unprovable errs toward silence: unresolvable keys, attribute-bound results,
and container round-trips drop out of tracking rather than guessing.
"""

import ast
import re
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

from unionml_tpu.analysis.callgraph import CallGraph, FunctionInfo, ModuleIndex
from unionml_tpu.analysis.cfg import (
    ALWAYS_KINDS,
    CFG,
    Block,
    build_cfg,
    path_to,
    reachable,
)
from unionml_tpu.analysis.core import Finding, Project, register
from unionml_tpu.analysis.dataflow import _call_map, own_nodes, resolved_edges

#: interprocedural summary chains stop growing past this depth (mirrors
#: dataflow.Summaries — deep chains stop being actionable witnesses)
_MAX_CHAIN = 6


class Sig:
    """One acquire/release signature: leaf method name, receiver-hint
    substring ('' = any receiver, including none), and where the key lives —
    ``arg`` (first positional), ``result`` (assigned name), ``recv``
    (the receiver itself, e.g. ``f.close()``)."""

    __slots__ = ("method", "hint", "keyed")

    def __init__(self, method: str, hint: str, keyed: str) -> None:
        self.method = method
        self.hint = hint
        self.keyed = keyed


class ResourceSpec:
    """One resource class in the spec table."""

    __slots__ = (
        "name", "noun", "acquires", "releases", "strict", "escape_call_arg",
        "raise_ok", "exit_leak",
    )

    def __init__(
        self,
        name: str,
        noun: str,
        acquires: Tuple[Sig, ...],
        releases: Tuple[Sig, ...],
        *,
        strict: bool = False,
        escape_call_arg: bool = False,
        raise_ok: bool = False,
        exit_leak: bool = True,
    ) -> None:
        self.name = name
        self.noun = noun
        self.acquires = acquires
        self.releases = releases
        #: follow implicit (may-throw) exception edges out of blocks that call
        #: project code — device-memory pins justify the extra paths
        self.strict = strict
        #: any call taking the key escapes it (loose handoff protocols)
        self.escape_call_arg = escape_call_arg
        #: exceptions are an accepted exit (the surrounding failure machinery
        #: reclaims the resource) — no except edges at all
        self.raise_ok = raise_ok
        #: falling off the end without a release is a leak too
        self.exit_leak = exit_leak


SPECS: Tuple[ResourceSpec, ...] = (
    ResourceSpec(
        "kv-pin",
        "KV-block pin",
        acquires=(Sig("pin", "prefix_cache", "arg"), Sig("preempt", "engine", "result")),
        releases=(
            Sig("unpin", "prefix_cache", "arg"),
            Sig("release_preempted", "engine", "arg"),
        ),
        strict=True,
        exit_leak=False,
    ),
    ResourceSpec(
        # paged serving: a block-table grant out of the shared KV pool. The
        # engine acquires on admit/splice (_alloc_slot_blocks, which records
        # the grant in _slot_block_map and returns the ids — '# transfers:'),
        # and releases on finish/cancel/preempt/rebuild (_free_slot_blocks,
        # the '# owns:' release point) or by adoption into the radix index.
        # kv_quantize="int8" adds no paths here: the scale arrays are pool
        # device leaves indexed by the SAME block ids this grant tracks, so
        # the existing acquire/release sites cover their lifetime too. The
        # speculative draft pool (SpeculativeEngine._draft_pool) is the same
        # story one level up: draft K/V leaves are a SECOND set of pool
        # arrays indexed by the one shared block table — there is no draft
        # allocator and no draft grant, so freeing the target grant IS
        # freeing the draft blocks, and any new draft-side alloc/free
        # entry point must route through _alloc_slot_blocks /
        # _free_slot_blocks to stay inside this spec.
        "kv-block",
        "slot-owned KV block grant",
        acquires=(
            Sig("alloc_blocks", "allocator", "result"),
            # empty hint, NOT "self": a "self" hint would enter _ALL_HINTS and
            # exempt every self.<method>(key) call from escape analysis
            Sig("_alloc_slot_blocks", "", "result"),
        ),
        releases=(
            Sig("free_blocks", "allocator", "arg"),
            Sig("_free_slot_blocks", "", "arg"),
            Sig("adopt", "prefix_cache", "arg"),
        ),
        strict=True,
        exit_leak=False,
    ),
    ResourceSpec(
        "kv-ref",
        "prefix-cache block reference",
        acquires=(
            Sig("match", "prefix_cache", "result"),
            Sig("extend", "prefix_cache", "result"),
        ),
        releases=(Sig("release", "prefix_cache", "arg"),),
    ),
    ResourceSpec(
        "trace",
        "telemetry trace",
        acquires=(Sig("new_trace", "telemetry", "result"),),
        releases=(Sig("end_trace", "telemetry", "arg"),),
    ),
    ResourceSpec(
        "slot",
        "engine slot",
        acquires=(Sig("admit", "engine", "result"), Sig("admit_many", "engine", "result")),
        releases=(Sig("cancel", "engine", "arg"),),
        escape_call_arg=True,
        raise_ok=True,
        exit_leak=False,
    ),
    ResourceSpec(
        "ticket",
        "scheduler ticket",
        acquires=(Sig("make_ticket", "scheduler", "result"),),
        releases=(Sig("submit", "scheduler", "arg"), Sig("requeue", "scheduler", "arg")),
        escape_call_arg=True,
        raise_ok=True,
        exit_leak=False,
    ),
    ResourceSpec(
        "handle",
        "file handle",
        acquires=(Sig("open", "", "result"),),
        releases=(Sig("close", "", "recv"), Sig("close", "os", "arg")),
    ),
)

SPEC_BY_NAME: Dict[str, ResourceSpec] = {s.name: s for s in SPECS}
#: leaf method names the family cares about at all (cheap per-function filter)
_METHOD_NAMES = frozenset(
    sig.method for spec in SPECS for sig in spec.acquires + spec.releases
)
#: non-empty receiver hints: calls on these receivers are part of the resource
#: protocol, so they never count as a generic escape of somebody's key
_ALL_HINTS = frozenset(
    sig.hint for spec in SPECS for sig in spec.acquires + spec.releases if sig.hint
)
#: container/sink methods that take ownership of their argument
_SINK_METHODS = frozenset(
    {"append", "add", "appendleft", "put", "put_nowait", "extend", "insert",
     "push", "setdefault", "send"}
)

# ------------------------------------------------------------- text utilities

#: keyed by id(node) and pinning the node itself — the reference keeps the
#: address from being reused by a later Project's AST (same-process reruns)
_UNPARSE_CACHE: Dict[int, Tuple[ast.AST, str]] = {}


def _unp(node: ast.AST) -> str:
    got = _UNPARSE_CACHE.get(id(node))
    if got is not None and got[0] is node:
        return got[1]
    try:
        text = ast.unparse(node)
    except (ValueError, AttributeError, RecursionError):  # pragma: no cover
        text = ""
    _UNPARSE_CACHE[id(node)] = (node, text)
    return text


_MENTION_CACHE: Dict[str, "re.Pattern[str]"] = {}


def _mention_re(key: str) -> "re.Pattern[str]":
    got = _MENTION_CACHE.get(key)
    if got is None:
        got = re.compile(
            r"(?<![A-Za-z0-9_.])" + re.escape(key) + r"(?![A-Za-z0-9_])"
        )
        _MENTION_CACHE[key] = got
    return got


def _mentions(node: ast.AST, key: str) -> bool:
    text = _unp(node)
    if key not in text:
        return False
    return _mention_re(key).search(text) is not None


def _base(key: str) -> str:
    """``ticket.resume`` -> ``ticket``; rebinding the base kills the key."""
    return key.split(".", 1)[0].split("[", 1)[0]


def _leaf_and_recv(call: ast.Call) -> Tuple[Optional[str], str]:
    f = call.func
    if isinstance(f, ast.Attribute):
        return f.attr, _unp(f.value)
    if isinstance(f, ast.Name):
        return f.id, ""
    return None, ""


def _sig_matches(sig: Sig, leaf: Optional[str], recv: str) -> bool:
    return leaf == sig.method and (not sig.hint or sig.hint in recv)


def _any_sig_matches(leaf: Optional[str], recv: str) -> bool:
    if leaf not in _METHOD_NAMES:
        return False
    for spec in SPECS:
        for sig in spec.acquires + spec.releases:
            if _sig_matches(sig, leaf, recv):
                return True
    return False


def _arg_exprs(call: ast.Call) -> Iterator[ast.AST]:
    for a in call.args:
        yield a.value if isinstance(a, ast.Starred) else a
    for kw in call.keywords:
        yield kw.value


def _result_key(stmt: ast.AST) -> Optional[str]:
    """The tracked name a result-keyed acquire binds: a plain ``Name`` target
    (first element for tuple unpacking). Attribute/subscript targets escape
    into state immediately — untracked, deliberately."""
    if isinstance(stmt, ast.Assign) and stmt.targets:
        target = stmt.targets[0]
    elif isinstance(stmt, ast.AnnAssign):
        target = stmt.target
    else:
        return None
    if isinstance(target, (ast.Tuple, ast.List)) and target.elts:
        target = target.elts[0]
    return target.id if isinstance(target, ast.Name) else None


def _collect_targets(t: ast.AST, out: Set[str]) -> None:
    if isinstance(t, (ast.Tuple, ast.List)):
        for e in t.elts:
            _collect_targets(e, out)
    elif isinstance(t, ast.Starred):
        _collect_targets(t.value, out)
    else:
        out.add(_unp(t))


# -------------------------------------------------------------- per-block view

_OPAQUE = (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)


def _block_exprs(block: Block) -> Iterator[ast.AST]:
    """Nodes evaluated as part of this block (nested defs/lambdas excluded;
    ``with`` headers contribute only their ``as`` bindings — the context
    manager releases its own resource)."""
    for node, role in block.items:
        if role == "stmt":
            if isinstance(node, _OPAQUE):
                continue
            yield from own_nodes(node)
        elif role == "test":
            yield from own_nodes(node)
        elif role == "for":
            yield from own_nodes(node.iter)


class _Facts:
    """Per-block facts the reachability walks consult."""

    __slots__ = ("calls", "bindings", "resolved_call", "releases", "acquires")

    def __init__(self) -> None:
        #: (leaf, recv, call) for every call evaluated in the block
        self.calls: List[Tuple[Optional[str], str, ast.Call]] = []
        #: unparsed assignment/for/with/handler/del target texts
        self.bindings: Set[str] = set()
        #: the block calls back into scanned project code
        self.resolved_call = False
        #: (class name, key, call) direct textual releases
        self.releases: List[Tuple[str, str, ast.Call]] = []
        #: (spec, key, call) acquires that bind a trackable key
        self.acquires: List[Tuple[ResourceSpec, str, ast.Call]] = []


def _build_facts(fn: FunctionInfo, cfg: CFG, graph: CallGraph,
                 acquires_ret: Dict[Tuple[str, str], Set[str]]) -> Dict[int, _Facts]:
    callmap = _call_map(fn)
    facts: Dict[int, _Facts] = {}
    for bid, block in cfg.blocks.items():
        f = _Facts()
        facts[bid] = f
        for node in _block_exprs(block):
            if not isinstance(node, ast.Call):
                continue
            leaf, recv = _leaf_and_recv(node)
            f.calls.append((leaf, recv, node))
            cands = callmap.get(id(node))
            callee = graph._resolve(cands) if cands else None
            if callee is not None and callee is not fn:
                f.resolved_call = True
            for spec in SPECS:
                for sig in spec.releases:
                    if not _sig_matches(sig, leaf, recv):
                        continue
                    if sig.keyed == "recv":
                        key = recv
                    else:
                        key = _unp(node.args[0]) if node.args else ""
                    if key:
                        f.releases.append((spec.name, key, node))
                for sig in spec.acquires:
                    if not _sig_matches(sig, leaf, recv):
                        continue
                    if sig.keyed == "arg":
                        key = _unp(node.args[0]) if node.args else None
                    else:  # result-keyed: only a plain assignment binds it
                        key = _stmt_result_key(block, node)
                    if key:
                        f.acquires.append((spec, key, node))
            if callee is not None:
                classes = acquires_ret.get(callee.key)
                if classes:
                    key = _stmt_result_key(block, node)
                    if key:
                        for cls in classes:
                            spec = SPEC_BY_NAME.get(cls)
                            if spec is not None:
                                f.acquires.append((spec, key, node))
        # de-duplicate acquires (textual sig + summary may both fire)
        seen: Set[Tuple[str, str]] = set()
        uniq = []
        for spec, key, call in f.acquires:
            if (spec.name, key) not in seen:
                seen.add((spec.name, key))
                uniq.append((spec, key, call))
        f.acquires = uniq
        for node, role in block.items:
            if role == "stmt":
                if isinstance(node, ast.Assign):
                    for t in node.targets:
                        _collect_targets(t, f.bindings)
                elif isinstance(node, ast.AnnAssign) and node.value is not None:
                    _collect_targets(node.target, f.bindings)
                elif isinstance(node, ast.Delete):
                    for t in node.targets:
                        f.bindings.add(_unp(t))
                elif isinstance(node, _OPAQUE):
                    f.bindings.add(node.name)
            elif role == "for":
                _collect_targets(node.target, f.bindings)
            elif role == "with":
                for item in node.items:
                    if item.optional_vars is not None:
                        _collect_targets(item.optional_vars, f.bindings)
            elif role == "handler" and node.name:
                f.bindings.add(node.name)
    return facts


def _stmt_result_key(block: Block, call: ast.Call) -> Optional[str]:
    """For a result-keyed match: the call must be the whole RHS of the
    block's (single) assignment statement."""
    for node, role in block.items:
        if role == "stmt" and isinstance(node, (ast.Assign, ast.AnnAssign)):
            if getattr(node, "value", None) is call:
                return _result_key(node)
    return None


def _rebinds(f: _Facts, key: str) -> bool:
    return key in f.bindings or _base(key) in f.bindings


def _escapes(block: Block, f: _Facts, key: str, spec: ResourceSpec) -> bool:
    """The key's resource is handed to something that may own it now: returned,
    raised, yielded, stored into state, put in a container, passed to a
    constructor — or passed to any call at all for ``escape_call_arg``
    classes. Calls that are part of a resource protocol (matching any spec
    signature, or on a hinted receiver) never count: ``release(path)`` on the
    prefix cache must not hide ``path``'s pin from the walk."""
    for node, role in block.items:
        if role != "stmt":
            continue
        if isinstance(node, ast.Return):
            if node.value is not None and _mentions(node.value, key):
                return True
        elif isinstance(node, ast.Raise):
            if node.exc is not None and _mentions(node.exc, key):
                return True
        elif isinstance(node, ast.Assign):
            # storing the key into state escapes it; so does registering
            # state UNDER the key (``bookkeeping[slot] = ...``)
            if _mentions_any_store_target(node) and (
                _mentions(node.value, key)
                or any(_mentions(t, key) for t in node.targets)
            ):
                return True
    for node in _block_exprs(block):
        if isinstance(node, (ast.Yield, ast.YieldFrom, ast.Await)):
            if _mentions(node, key):
                return True
        if isinstance(node, ast.Call):
            leaf, recv = _leaf_and_recv(node)
            arg_hit = any(_mentions(a, key) for a in _arg_exprs(node))
            if not arg_hit:
                continue
            if spec.escape_call_arg:
                return True
            if leaf in _SINK_METHODS:
                return True
            if leaf and leaf[:1].isupper():  # constructor-like: Foo(key)
                return True
            if _any_sig_matches(leaf, recv):
                continue
            if any(h in recv for h in _ALL_HINTS):
                continue
            return True
    return False


def _mentions_any_store_target(node: ast.Assign) -> bool:
    for t in node.targets:
        elts = t.elts if isinstance(t, (ast.Tuple, ast.List)) else [t]
        for e in elts:
            if isinstance(e, (ast.Attribute, ast.Subscript)):
                return True
    return False


def _pruned_kind(block: Block, key: str) -> Optional[str]:
    """None-guard path sensitivity: on ``if key is None: ...`` the true branch
    cannot hold the resource. Returns the edge kind to skip, if any."""
    if block.kind != "branch":
        return None
    test = next((n for n, r in block.items if r == "test"), None)
    if test is None:
        return None
    text = _unp(test)
    if text == f"{key} is None" or text == f"not {key}":
        return "true"
    if text == f"{key} is not None" or text == key:
        return "false"
    return None


def _witness(cfg: CFG, parents: Dict[int, Optional[int]], target: int,
             extra: Sequence[int] = ()) -> str:
    lines: List[int] = []
    for bid in list(path_to(parents, target)) + list(extra):
        ln = cfg.blocks[bid].line
        if ln and (not lines or lines[-1] != ln):
            lines.append(ln)
    shown = lines[:_MAX_CHAIN]
    tail = "..." if len(lines) > _MAX_CHAIN else ""
    return "->".join(str(ln) for ln in shown) + tail


def _verbs(spec: ResourceSpec) -> str:
    return "/".join(
        sorted({f"{sig.method}()" for sig in spec.releases})
    )


# ------------------------------------------------------- summaries + contracts


class ResourceSummaries:
    """Per-function resource facts propagated over the resolved call graph:
    which classes a function releases (directly, via a releasing callee, or by
    ``# owns:`` contract) and which classes its return value carries
    (``# transfers:`` or an acquire that flows into a ``return``)."""

    def __init__(self, graph: CallGraph) -> None:
        self.graph = graph
        self.owns_annot: Dict[Tuple[str, str], Tuple[str, ...]] = {}
        self.transfers_annot: Dict[Tuple[str, str], Tuple[str, ...]] = {}
        #: fn key -> class -> qualname witness chain
        self.releases: Dict[Tuple[str, str], Dict[str, Tuple[str, ...]]] = {}
        #: classes a direct textual release touches in the fn's own body
        self.direct_releases: Dict[Tuple[str, str], Set[str]] = {}
        #: fn key -> classes its return value carries
        self.acquires_ret: Dict[Tuple[str, str], Set[str]] = {}
        #: leaf method name -> qualnames of functions calling it (textual —
        #: the witness chain for broken ``# owns:`` contracts)
        self.callers_by_leaf: Dict[str, Set[str]] = {}
        #: (relpath, line, message) annotation hygiene problems
        self.hygiene: List[Tuple[str, int, str]] = []
        #: fn key -> resolved callees of ``return f(...)`` statements — walked
        #: once here so the fixpoint never re-walks function bodies
        self._ret_call_callees: Dict[Tuple[str, str], List[Tuple[str, str]]] = {}
        self._collect_annotations()
        self._collect_direct()
        self._fixpoint()

    # -- annotations ------------------------------------------------------

    def _collect_annotations(self) -> None:
        known = set(SPEC_BY_NAME)
        for idx in self.graph.indexes:
            mod = idx.source
            for table, label in ((mod.owns, "owns"), (mod.transfers, "transfers")):
                for line, classes in table.items():
                    fn = self._fn_at_line(idx, line)
                    if fn is None:
                        self.hygiene.append((
                            mod.relpath, line,
                            f"'# {label}:' annotation is not attached to a "
                            f"function definition",
                        ))
                        continue
                    good = tuple(c for c in classes if c in known)
                    for c in classes:
                        if c not in known:
                            self.hygiene.append((
                                mod.relpath, line,
                                f"'# {label}:' names unknown resource class "
                                f"'{c}' (known: {', '.join(sorted(known))})",
                            ))
                    if not good:
                        continue
                    table_out = (
                        self.owns_annot if label == "owns" else self.transfers_annot
                    )
                    prev = table_out.get(fn.key, ())
                    table_out[fn.key] = prev + tuple(
                        c for c in good if c not in prev
                    )
            for line, classes in mod.holds.items():
                for c in classes:
                    if c not in known:
                        self.hygiene.append((
                            mod.relpath, line,
                            f"'# holds:' names unknown resource class '{c}' "
                            f"(known: {', '.join(sorted(known))})",
                        ))

    @staticmethod
    def _fn_at_line(idx: ModuleIndex, line: int) -> Optional[FunctionInfo]:
        """The function whose def statement (decorators through signature)
        covers ``line`` — innermost when nested."""
        best: Optional[FunctionInfo] = None
        best_start = -1
        for fn in idx.functions.values():
            node = fn.node
            start = min(
                [node.lineno] + [d.lineno for d in getattr(node, "decorator_list", [])]
            )
            body = getattr(node, "body", None)
            end = body[0].lineno - 1 if body else node.lineno
            if start <= line <= max(end, node.lineno) and start > best_start:
                best, best_start = fn, start
        return best

    # -- direct facts -----------------------------------------------------

    def _collect_direct(self) -> None:
        for fn in self.graph.by_key.values():
            rel: Set[str] = set()
            acq: List[Tuple[str, str]] = []  # (class, key)
            returns: List[ast.AST] = []
            ret_callees: List[Tuple[str, str]] = []
            for node in own_nodes(fn.node):
                if isinstance(node, ast.Return) and node.value is not None:
                    returns.append(node.value)
                    if isinstance(node.value, ast.Call):
                        cands = _call_map(fn).get(id(node.value))
                        callee = self.graph._resolve(cands) if cands else None
                        if callee is not None and callee is not fn:
                            ret_callees.append(callee.key)
                if not isinstance(node, ast.Call):
                    continue
                leaf, recv = _leaf_and_recv(node)
                if leaf is not None:
                    self.callers_by_leaf.setdefault(leaf, set()).add(fn.qualname)
                if leaf not in _METHOD_NAMES:
                    continue
                for spec in SPECS:
                    for sig in spec.releases:
                        if _sig_matches(sig, leaf, recv):
                            rel.add(spec.name)
                    for sig in spec.acquires:
                        if _sig_matches(sig, leaf, recv):
                            if sig.keyed == "arg" and node.args:
                                acq.append((spec.name, _unp(node.args[0])))
                            elif sig.keyed == "result":
                                # resolved precisely in the CFG pass; here the
                                # summary only needs "this fn pulls one out"
                                acq.append((spec.name, ""))
            if rel:
                self.direct_releases[fn.key] = rel
                self.releases[fn.key] = {c: (fn.qualname,) for c in rel}
            ret_classes: Set[str] = set(self.transfers_annot.get(fn.key, ()))
            for cls, key in acq:
                if key and any(_mentions(r, key) for r in returns):
                    ret_classes.add(cls)
            if ret_classes:
                self.acquires_ret[fn.key] = ret_classes
            for cls in self.owns_annot.get(fn.key, ()):
                self.releases.setdefault(fn.key, {}).setdefault(
                    cls, (fn.qualname + " (# owns contract)",)
                )
            if ret_callees:
                self._ret_call_callees[fn.key] = ret_callees

    # -- propagation ------------------------------------------------------

    def _fixpoint(self) -> None:
        changed = True
        while changed:
            changed = False
            for fn in self.graph.by_key.values():
                for callee, call in resolved_edges(self.graph, fn):
                    if callee is fn:
                        continue
                    for cls, chain in self.releases.get(callee.key, {}).items():
                        mine = self.releases.setdefault(fn.key, {})
                        if cls not in mine and len(chain) < _MAX_CHAIN:
                            mine[cls] = (fn.qualname,) + chain
                            changed = True
                for callee_key in self._ret_call_callees.get(fn.key, ()):
                    inherited = self.acquires_ret.get(callee_key)
                    if inherited:
                        mine = self.acquires_ret.setdefault(fn.key, set())
                        if not inherited <= mine:
                            mine |= inherited
                            changed = True

    # -- contract queries -------------------------------------------------

    def fn_releases_cls(self, fn: FunctionInfo, cls: str) -> bool:
        """Does ``fn`` provably release ``cls`` — a direct textual release or
        a resolved call into a releasing/owning callee? (``fn``'s own
        ``# owns:`` annotation deliberately does NOT satisfy this: it is the
        claim under test.)"""
        if cls in self.direct_releases.get(fn.key, ()):
            return True
        for callee, _call in resolved_edges(self.graph, fn):
            if callee is fn:
                continue
            if cls in self.releases.get(callee.key, {}):
                return True
        return False


# ------------------------------------------------------------------ analysis


class _Analysis:
    """Shared engine behind the three registered rules (built once per lint
    run, cached on the project's call graph)."""

    def __init__(self, project: Project) -> None:
        self.project = project
        self.graph = project.graph
        self.sums = ResourceSummaries(self.graph)
        self.leaks: List[Finding] = []
        self.doubles: List[Finding] = []
        self.transfers: List[Finding] = []
        for relpath, line, msg in self.sums.hygiene:
            self.leaks.append(Finding("resource-leak", relpath, line, 0, msg))
        for idx in self.graph.indexes:
            self._check_holds(idx)
            for fn in idx.functions.values():
                self._check_fn(fn, idx)
        self.leaks.sort(key=lambda f: (f.path, f.line, f.col))
        self.doubles.sort(key=lambda f: (f.path, f.line, f.col))
        self.transfers.sort(key=lambda f: (f.path, f.line, f.col))

    # -- per-function -----------------------------------------------------

    def _check_fn(self, fn: FunctionInfo, idx: ModuleIndex) -> None:
        relevant = False
        for _cands, call in fn.calls:
            leaf, _recv = _leaf_and_recv(call)
            if leaf in _METHOD_NAMES:
                relevant = True
                break
        self._check_owns(fn, idx)
        if not relevant:
            return
        cfg = build_cfg(fn.node)
        facts = _build_facts(fn, cfg, self.graph, self.sums.acquires_ret)
        for bid, f in facts.items():
            for spec, key, call in f.acquires:
                self._check_leak(fn, idx, cfg, facts, spec, key, bid, call)
        self._check_doubles(fn, idx, cfg, facts)
        self._check_transfers(fn, idx, cfg, facts)

    # -- leak-on-path -----------------------------------------------------

    def _leak_stop(self, facts: Dict[int, _Facts], fn: FunctionInfo,
                   spec: ResourceSpec, key: str):
        callmap = _call_map(fn)

        def stop(block: Block) -> bool:
            f = facts[block.id]
            for cls, k, _call in f.releases:
                if cls == spec.name and k == key:
                    return True
            for leaf, recv, call in f.calls:
                cands = callmap.get(id(call))
                callee = self.graph._resolve(cands) if cands else None
                if callee is None or callee is fn:
                    continue
                if spec.name in self.sums.releases.get(callee.key, {}):
                    if any(_mentions(a, key) for a in _arg_exprs(call)):
                        return True
            if _escapes(block, f, key, spec):
                return True
            return _rebinds(f, key)

        return stop

    def _check_leak(self, fn: FunctionInfo, idx: ModuleIndex, cfg: CFG,
                    facts: Dict[int, _Facts], spec: ResourceSpec, key: str,
                    b0: int, call: ast.Call) -> None:
        def follow(block: Block, edge) -> bool:
            if edge.kind == "except":
                if spec.raise_ok or block.id == b0:
                    return False
                if edge.explicit:
                    return True
                return spec.strict and facts[block.id].resolved_call
            if edge.kind not in ALWAYS_KINDS:
                return False
            pruned = _pruned_kind(block, key)
            return pruned is None or edge.kind != pruned

        stop = self._leak_stop(facts, fn, spec, key)
        parents = reachable(cfg, b0, follow=follow, stop=stop)
        verbs = _verbs(spec)
        line, col = call.lineno, call.col_offset

        loop_src: Optional[int] = None
        for bid in parents:
            if bid != b0 and stop(cfg.blocks[bid]):
                continue
            for e in cfg.blocks[bid].edges:
                if e.dst == b0 and follow(cfg.blocks[bid], e):
                    loop_src = bid
                    break
            if loop_src is not None:
                break
        if loop_src is not None:
            self.leaks.append(Finding(
                "resource-leak", idx.source.relpath, line, col,
                f"{spec.noun} '{key}' is re-acquired on a loop back-edge "
                f"(lines {_witness(cfg, parents, loop_src, (b0,))}) while the "
                f"previous acquisition is still held — release with {verbs} "
                f"before the next iteration",
                symbol=fn.qualname,
            ))
            return
        if cfg.rexit in parents:
            self.leaks.append(Finding(
                "resource-leak", idx.source.relpath, line, col,
                f"{spec.noun} '{key}' can leak on an exception path (lines "
                f"{_witness(cfg, parents, cfg.rexit)}): the error escapes "
                f"before any {verbs} — release in a handler/finally or "
                f"annotate the receiving function with "
                f"'# owns: {spec.name}'",
                symbol=fn.qualname,
            ))
            return
        if spec.exit_leak and cfg.exit in parents:
            self.leaks.append(Finding(
                "resource-leak", idx.source.relpath, line, col,
                f"{spec.noun} '{key}' leaks on a normal exit path (lines "
                f"{_witness(cfg, parents, cfg.exit)}): no {verbs} before the "
                f"function returns — release it, or annotate the transfer "
                f"with '# transfers: {spec.name}'",
                symbol=fn.qualname,
            ))

    # -- double-release ---------------------------------------------------

    def _check_doubles(self, fn: FunctionInfo, idx: ModuleIndex, cfg: CFG,
                       facts: Dict[int, _Facts]) -> None:
        reported: Set[Tuple[int, int, str, str]] = set()
        for b0, f0 in facts.items():
            for cls, key, call0 in f0.releases:
                spec = SPEC_BY_NAME[cls]

                def follow(block: Block, edge) -> bool:
                    if edge.kind not in ALWAYS_KINDS:
                        return False
                    pruned = _pruned_kind(block, key)
                    return pruned is None or edge.kind != pruned

                def stop(block: Block) -> bool:
                    fb = facts[block.id]
                    for sp2, k2, _c in fb.acquires:
                        if sp2.name == cls and k2 == key:
                            return True
                    for c2, k2, _c in fb.releases:
                        if c2 == cls and k2 == key:
                            return True
                    if _escapes(block, fb, key, spec):
                        return True
                    return _rebinds(fb, key)

                parents = reachable(cfg, b0, follow=follow, stop=stop)
                hits = []
                for bid in parents:
                    if bid == b0:
                        continue
                    fb = facts[bid]
                    if _mentions_release(fb, cls, key) and not _reacquires(fb, cls, key):
                        hits.append(bid)
                for bid in sorted(hits, key=lambda b: cfg.blocks[b].line):
                    pair = (min(b0, bid), max(b0, bid), cls, key)
                    if pair in reported:
                        continue
                    reported.add(pair)
                    rel = next(
                        c for c2, k2, c in facts[bid].releases
                        if c2 == cls and k2 == key
                    )
                    self.doubles.append(Finding(
                        "double-release", idx.source.relpath,
                        rel.lineno, rel.col_offset,
                        f"{spec.noun} '{key}' released twice: already "
                        f"released at line {call0.lineno}, and no path in "
                        f"between re-acquires or rebinds it (path: lines "
                        f"{_witness(cfg, parents, bid)})",
                        symbol=fn.qualname,
                    ))
                    break  # one finding per source release

    # -- unbalanced-transfer ----------------------------------------------

    def _check_transfers(self, fn: FunctionInfo, idx: ModuleIndex, cfg: CFG,
                         facts: Dict[int, _Facts]) -> None:
        transfer_classes = self.sums.transfers_annot.get(fn.key, ())
        if not transfer_classes:
            return
        for b0, f0 in facts.items():
            for cls, key, call0 in f0.releases:
                if cls not in transfer_classes:
                    continue
                spec = SPEC_BY_NAME[cls]

                def follow(block: Block, edge) -> bool:
                    if edge.kind not in ALWAYS_KINDS:
                        return False
                    pruned = _pruned_kind(block, key)
                    return pruned is None or edge.kind != pruned

                def stop(block: Block) -> bool:
                    fb = facts[block.id]
                    for sp2, k2, _c in fb.acquires:
                        if sp2.name == cls and k2 == key:
                            return True
                    return _rebinds(fb, key)

                parents = reachable(cfg, b0, follow=follow, stop=stop)
                for bid in sorted(parents, key=lambda b: cfg.blocks[b].line):
                    if bid == b0:
                        continue
                    ret = _returns_key(cfg.blocks[bid], key)
                    if ret is None:
                        continue
                    self.transfers.append(Finding(
                        "unbalanced-transfer", idx.source.relpath,
                        call0.lineno, call0.col_offset,
                        f"function transfers {spec.noun} ownership to its "
                        f"caller ('# transfers: {cls}') but releases '{key}' "
                        f"here while a path (lines "
                        f"{_witness(cfg, parents, bid)}) still returns it — "
                        f"both sides of the transfer would release",
                        symbol=fn.qualname,
                    ))
                    break

    # -- ownership contracts ----------------------------------------------

    def _check_owns(self, fn: FunctionInfo, idx: ModuleIndex) -> None:
        for cls in self.sums.owns_annot.get(fn.key, ()):
            if self.sums.fn_releases_cls(fn, cls):
                continue
            spec = SPEC_BY_NAME[cls]
            leaf = fn.qualname.rsplit(".", 1)[-1]
            callers = sorted(
                q for q in self.sums.callers_by_leaf.get(leaf, ())
                if q != fn.qualname
            )[:3]
            relied = (
                f"; relied on by {', '.join(callers)}" if callers else ""
            )
            self.leaks.append(Finding(
                "resource-leak", idx.source.relpath,
                fn.node.lineno, fn.node.col_offset,
                f"function is annotated '# owns: {cls}' but no path releases "
                f"a {spec.noun} ({_verbs(spec)} or a releasing callee)"
                f"{relied} — the contract callers rely on is broken",
                symbol=fn.qualname,
            ))

    def _check_holds(self, idx: ModuleIndex) -> None:
        mod = idx.source
        if not mod.holds:
            return
        #: attr text -> (classes, annotation line), per enclosing class
        held: Dict[Tuple[str, str], Tuple[Tuple[str, ...], int]] = {}
        consumed: Set[int] = set()
        for fn in idx.functions.values():
            if not fn.qualname.endswith("__init__") or fn.class_name is None:
                continue
            for node in own_nodes(fn.node):
                if not isinstance(node, (ast.Assign, ast.AnnAssign)):
                    continue
                end = getattr(node, "end_lineno", node.lineno) or node.lineno
                classes = None
                for line in range(node.lineno, end + 1):
                    if line in mod.holds:
                        classes = tuple(
                            c for c in mod.holds[line] if c in SPEC_BY_NAME
                        )
                        consumed.add(line)
                        break
                if not classes:
                    continue
                targets: Set[str] = set()
                if isinstance(node, ast.Assign):
                    for t in node.targets:
                        _collect_targets(t, targets)
                else:
                    _collect_targets(node.target, targets)
                for attr in targets:
                    if attr.startswith("self."):
                        held[(fn.class_name, attr)] = (classes, node.lineno)
        for line in mod.holds:
            if line not in consumed:
                self.leaks.append(Finding(
                    "resource-leak", mod.relpath, line, 0,
                    "'# holds:' annotation is not attached to a "
                    "'self.<attr> = ...' assignment in __init__",
                ))
        if not held:
            return
        for fn in idx.functions.values():
            if fn.class_name is None or fn.qualname.endswith("__init__"):
                continue
            owned = self.sums.owns_annot.get(fn.key, ())
            for node in own_nodes(fn.node):
                if not isinstance(node, ast.Assign):
                    continue
                for t in node.targets:
                    elts = t.elts if isinstance(t, (ast.Tuple, ast.List)) else [t]
                    for e in elts:
                        attr = _unp(e)
                        entry = held.get((fn.class_name, attr))
                        if entry is None:
                            continue
                        classes, _decl = entry
                        if _mentions(node.value, attr):
                            continue  # swap-read: old value was taken out
                        for cls in classes:
                            if cls in owned:
                                continue  # the owner reassigns by contract
                            if self.sums.fn_releases_cls(fn, cls):
                                continue
                            spec = SPEC_BY_NAME[cls]
                            self.leaks.append(Finding(
                                "resource-leak", mod.relpath,
                                node.lineno, node.col_offset,
                                f"'{attr}' holds live {spec.noun}s "
                                f"('# holds: {cls}') but is overwritten "
                                f"without releasing the previous contents "
                                f"({_verbs(spec)} or a releasing callee "
                                f"first)",
                                symbol=fn.qualname,
                            ))


def _mentions_release(fb: _Facts, cls: str, key: str) -> bool:
    return any(c2 == cls and k2 == key for c2, k2, _c in fb.releases)


def _reacquires(fb: _Facts, cls: str, key: str) -> bool:
    return any(sp.name == cls and k2 == key for sp, k2, _c in fb.acquires)


def _returns_key(block: Block, key: str) -> Optional[ast.Return]:
    for node, role in block.items:
        if role == "stmt" and isinstance(node, ast.Return):
            if node.value is not None and _mentions(node.value, key):
                return node
    return None


def _analysis(project: Project) -> _Analysis:
    cached = getattr(project.graph, "_graftlint_resources", None)
    if cached is None:
        cached = _Analysis(project)
        project.graph._graftlint_resources = cached
    return cached


@register(
    "resource-leak",
    "paired resources (pins/refs/traces/slots/tickets/handles) with a path "
    "that escapes without release or ownership transfer",
)
def check_leaks(project: Project) -> Iterator[Finding]:
    yield from _analysis(project).leaks


@register(
    "double-release",
    "a resource released twice along one path with no re-acquire or rebind "
    "in between",
)
def check_doubles(project: Project) -> Iterator[Finding]:
    yield from _analysis(project).doubles


@register(
    "unbalanced-transfer",
    "ownership annotated as transferred ('# transfers:') but a path releases "
    "on the transferring side too",
)
def check_transfers(project: Project) -> Iterator[Finding]:
    yield from _analysis(project).transfers
