"""Rule ``async-blocking``: blocking calls inside ``async def`` bodies.

The serving surface is a single asyncio event loop (`serving/app.py`,
`serving/batcher.py`, the FastAPI adapter): one handler that blocks — a
compiled predictor call, a device fetch, an unbounded ``.result()`` — stalls
EVERY in-flight request, not just its own. The engine code routes such work
through ``run_in_executor``; this rule mechanically holds that line:

- direct blocking primitives in an async body (``time.sleep``, unbounded
  ``.wait()`` / ``.join()`` / ``.result()`` / ``.acquire()``,
  ``subprocess.run``, ``jax.device_get``, ``.block_until_ready()``);
- calls that resolve — through the call graph, including instance types
  (``predictor = ResidentPredictor(...)`` then ``predictor.predict(...)``) —
  to a scanned function that TRANSITIVELY blocks; the finding carries the
  chain down to the primitive.

Awaited calls are exempt (``await queue.get()`` parks the coroutine, not the
loop), and nested ``def`` / ``lambda`` bodies are skipped — they execute under
whatever frame actually calls them (usually an executor thread, which is the
fix this rule suggests).
"""

import ast
from typing import Iterator, Set

from unionml_tpu.analysis.callgraph import FunctionInfo, ModuleIndex
from unionml_tpu.analysis.core import Finding, Project, register
from unionml_tpu.analysis.dataflow import (
    Summaries,
    blocking_reason,
    own_nodes,
    shared_analyses,
)


def _awaited_calls(fn_node: ast.AST) -> Set[int]:
    """ids of Call nodes directly under an Await (parked, not blocking)."""
    out: Set[int] = set()
    for node in own_nodes(fn_node):
        if isinstance(node, ast.Await) and isinstance(node.value, ast.Call):
            out.add(id(node.value))
    return out


def _check_async_fn(
    fn: FunctionInfo, idx: ModuleIndex, summaries: Summaries
) -> Iterator[Finding]:
    awaited = _awaited_calls(fn.node)
    for node in own_nodes(fn.node):
        if not isinstance(node, ast.Call) or id(node) in awaited:
            continue
        reason = blocking_reason(node, idx)
        if reason is not None:
            yield Finding(
                "async-blocking",
                idx.source.relpath,
                node.lineno,
                node.col_offset,
                f"blocking call in async handler: {reason} — the event loop "
                f"stalls for every in-flight request; await an async "
                f"equivalent or run it in an executor",
                symbol=fn.qualname,
            )
            continue
        callee = summaries.resolve_call(fn, node)
        if callee is None:
            continue
        info = summaries.blocking.get(callee.key)
        if info is not None:
            chain = " -> ".join(info.chain)
            yield Finding(
                "async-blocking",
                idx.source.relpath,
                node.lineno,
                node.col_offset,
                f"call in async handler blocks the event loop: {chain} reaches "
                f"'{info.reason}'; run it in an executor "
                f"(loop.run_in_executor) or make the handler sync so the "
                f"framework threadpools it",
                symbol=fn.qualname,
            )


@register(
    "async-blocking",
    "blocking calls inside async def handlers (event-loop stalls; dataflow chains)",
)
def check(project: Project):
    graph = project.graph
    _locks, summaries = shared_analyses(graph)
    for idx in graph.indexes:
        for fn in idx.functions.values():
            if fn.is_async:
                yield from _check_async_fn(fn, idx, summaries)
