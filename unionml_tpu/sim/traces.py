"""Seeded synthetic workload generators for the fleet simulator.

One :func:`generate_requests` call turns a :class:`SyntheticConfig` into a
deterministic arrival-ordered request list exhibiting the phenomena that
actually stress a serving fleet:

- **Diurnal rate curve**: arrivals are a thinned Poisson process whose
  instantaneous rate follows ``1 + diurnal_amplitude · sin(...)`` over the
  run, so the autoscaler sees a morning ramp, a peak, and a trough.
- **Bursts**: every ``burst_every_s`` the rate multiplies by
  ``burst_factor`` for ``burst_len_s`` — the flash-crowd that tests
  shedding and scale-up latency.
- **Heavy-tail lengths**: prompt and budget are lognormal (the right tail
  is what fills block pools and starves slots).
- **Hot-prefix skew**: each request prepends one of ``hot_prefixes``
  shared system-prompt blocks chosen Zipf-style, so router prefix
  affinity has something real to exploit; prefix token tuples are shared
  objects (memory stays flat at a million users).
- **Session churn**: users hold multi-turn sessions (geometric turn
  count); each turn reuses the session id so router stickiness and
  session-expiry sweeps are exercised.
- **Replica deaths**: an explicit :class:`ReplicaDeath` schedule for
  failover drills.

Everything derives from one ``random.Random(seed)`` — same config, same
requests, bit for bit.
"""

from __future__ import annotations

import math
import random
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

__all__ = ["ReplicaDeath", "SimRequest", "SyntheticConfig", "generate_requests"]


@dataclass(frozen=True)
class ReplicaDeath:
    """Kill ``replica`` at virtual time ``at_s`` (permanent — the drill is
    failover adoption + re-routing, not supervisor rebuild timing)."""

    at_s: float
    replica: int


@dataclass(frozen=True)
class SimRequest:
    """One synthetic arrival (prompt tokens are ints < 256 so CPython
    interns them — a million-user trace stays in small memory)."""

    arrival_s: float
    session_id: str
    prompt: Tuple[int, ...]
    budget: int
    cls: str
    deadline_ms: Optional[float]
    speculative: bool = False


def _default_class_mix() -> Dict[str, float]:
    return {"interactive": 0.5, "standard": 0.35, "batch": 0.15}


def _default_deadlines() -> Dict[str, Optional[float]]:
    # wall budgets by class; batch runs open-ended
    return {"interactive": 2_000.0, "standard": 10_000.0, "batch": None}


@dataclass(frozen=True)
class SyntheticConfig:
    """Workload shape knobs (see module docstring for what each models).

    ``users`` is the session population; each user opens sessions whose
    turn counts are geometric with mean ``mean_turns``, so total requests
    ≈ ``users · mean_turns``. ``arrival_rate_per_s`` of ``None`` spreads
    that total uniformly-by-curve over ``duration_s``.
    """

    users: int = 1000
    duration_s: float = 600.0
    mean_turns: float = 1.5
    arrival_rate_per_s: Optional[float] = None
    diurnal_amplitude: float = 0.5
    burst_every_s: float = 0.0  # 0 disables bursts
    burst_len_s: float = 5.0
    burst_factor: float = 3.0
    prompt_len_median: float = 24.0
    prompt_len_sigma: float = 0.6  # lognormal sigma (heavy right tail)
    max_prompt_len: int = 512
    budget_median: float = 16.0
    budget_sigma: float = 0.7
    max_budget: int = 512
    hot_prefixes: int = 8
    hot_prefix_blocks: int = 4  # shared system-prompt length, in blocks
    zipf_a: float = 1.2  # hot-prefix popularity skew (>1; higher = hotter head)
    block_size: int = 4
    class_mix: Dict[str, float] = field(default_factory=_default_class_mix)
    deadline_ms_by_class: Dict[str, Optional[float]] = field(
        default_factory=_default_deadlines
    )
    # classes served speculatively (mirrors SchedulerConfig.speculative_classes:
    # the ITL play is for latency-sensitive traffic; the flag only has an
    # effect when the CostModel's spec_alpha term is enabled)
    speculative_classes: Tuple[str, ...] = ("interactive",)
    deaths: Tuple[ReplicaDeath, ...] = ()
    seed: int = 0

    def __post_init__(self) -> None:
        if self.users < 1:
            raise ValueError(f"users must be >= 1, got {self.users}")
        if self.duration_s <= 0:
            raise ValueError(f"duration_s must be > 0, got {self.duration_s}")
        if self.mean_turns < 1.0:
            raise ValueError(f"mean_turns must be >= 1, got {self.mean_turns}")
        if not self.class_mix or any(w < 0 for w in self.class_mix.values()):
            raise ValueError("class_mix must be non-empty with non-negative weights")


def _rate_multiplier(config: SyntheticConfig, t: float) -> float:
    """Instantaneous arrival-rate multiplier at virtual time ``t`` (peaks
    mid-run; floored at 0.05 so the trough never fully silences traffic)."""
    phase = t / config.duration_s  # one diurnal cycle per run
    rate = 1.0 + config.diurnal_amplitude * math.sin(2.0 * math.pi * (phase - 0.25))
    if config.burst_every_s > 0 and (t % config.burst_every_s) < config.burst_len_s:
        rate *= config.burst_factor
    return max(0.05, rate)


def _zipf_weights(n: int, a: float) -> List[float]:
    weights = [1.0 / (rank ** a) for rank in range(1, n + 1)]
    total = sum(weights)
    return [w / total for w in weights]


def generate_requests(config: SyntheticConfig) -> List[SimRequest]:
    """The deterministic arrival-ordered request list for ``config``."""
    rng = random.Random(config.seed)
    # shared hot-prefix token tuples (one per popularity rank); ids < 256
    # (CPython interns small ints — a million-user trace stays in small
    # memory) drawn via randbytes, which is ~10x randrange per token
    prefix_len = config.hot_prefix_blocks * config.block_size
    prefixes = [
        tuple(rng.randbytes(prefix_len)) for _ in range(max(1, config.hot_prefixes))
    ]
    prefix_weights = _zipf_weights(len(prefixes), config.zipf_a)
    classes = list(config.class_mix)
    class_weights = [config.class_mix[c] for c in classes]

    # --- arrival times: thinned homogeneous Poisson over the rate curve ---
    turns_per_user = [
        1 + _geometric_extra_turns(rng, config.mean_turns) for _ in range(config.users)
    ]
    total = sum(turns_per_user)
    if config.arrival_rate_per_s is not None:
        base_rate = config.arrival_rate_per_s
    else:
        base_rate = total / config.duration_s
    peak = base_rate * (1.0 + config.diurnal_amplitude) * max(1.0, config.burst_factor)
    arrivals: List[float] = []
    t = 0.0
    while len(arrivals) < total:
        t += rng.expovariate(peak)
        if t >= config.duration_s:
            # wrap: the curve is periodic over the run, so restarting keeps
            # the target count without biasing toward the run's tail
            t = t % config.duration_s
        if rng.random() < _rate_multiplier(config, t) / (
            (1.0 + config.diurnal_amplitude) * max(1.0, config.burst_factor)
        ):
            arrivals.append(t)
    arrivals.sort()

    # --- sessions: assign consecutive arrivals of a user's session ---
    requests: List[SimRequest] = []
    arrival_iter = iter(arrivals)
    for user in range(config.users):
        turns = turns_per_user[user]
        session_id = f"u{user}"
        prefix = prefixes[_weighted_index(rng, prefix_weights)]
        cls = classes[_weighted_index(rng, class_weights)]
        for turn in range(turns):
            try:
                arrival = next(arrival_iter)
            except StopIteration:
                break
            suffix_len = min(
                config.max_prompt_len - len(prefix),
                max(1, int(rng.lognormvariate(
                    math.log(config.prompt_len_median), config.prompt_len_sigma
                ))),
            )
            # per-turn unique tail (ids < 256, same interning note as above)
            suffix = tuple(rng.randbytes(max(1, suffix_len)))
            budget = min(
                config.max_budget,
                max(1, int(rng.lognormvariate(
                    math.log(config.budget_median), config.budget_sigma
                ))),
            )
            requests.append(
                SimRequest(
                    arrival_s=arrival,
                    session_id=session_id,
                    prompt=prefix + suffix,
                    budget=budget,
                    cls=cls,
                    deadline_ms=config.deadline_ms_by_class.get(cls),
                    speculative=cls in config.speculative_classes,
                )
            )
    requests.sort(key=lambda r: r.arrival_s)
    return requests


def _geometric_extra_turns(rng: random.Random, mean_turns: float) -> int:
    """Extra turns beyond the first, geometric with mean ``mean_turns - 1``."""
    extra_mean = mean_turns - 1.0
    if extra_mean <= 0:
        return 0
    p = 1.0 / (1.0 + extra_mean)
    count = 0
    while rng.random() > p and count < 64:
        count += 1
    return count


def _weighted_index(rng: random.Random, weights: List[float]) -> int:
    pick = rng.random() * sum(weights)
    acc = 0.0
    for index, weight in enumerate(weights):
        acc += weight
        if pick <= acc:
            return index
    return len(weights) - 1
