"""Latency cost model for the fleet simulator, fittable from a real journal.

The simulator advances a virtual clock; this module decides by how much.
A :class:`CostModel` prices the three timed phases the journal decomposes
a request into:

- **prefill**: affine in prompt tokens (``prefill_base_ms`` +
  ``prefill_ms_per_token`` · tokens_in) — the base term absorbs dispatch
  and bucket-padding overheads that do not scale with length.
- **decode**: one inter-token latency per generated token, optionally
  per class (``itl_ms_by_class``) — batch traffic often decodes alongside
  fuller batches and measures slower than interactive.
- **dispatch**: fixed per-admission overhead (slot bind + first dispatch).

Speculative decoding enters as a multiplicative ITL scale, α-parameterized
after the standard speculative-sampling analysis: a round of γ draft
proposals plus one verify emits ``E[tokens] = (1 − α^(γ+1)) / (1 − α)``
tokens per target step (α the per-token acceptance rate) at relative cost
``γ·ρ + 1`` (ρ the draft/target step-cost ratio), so a speculative
request's ITL scales by ``(γ·ρ + 1) / E[tokens]``. The scale is clamped
at 1.0 because the production engine's γ is *adaptive* — acceptance EMAs
below threshold decay γ to 0 (vanilla decode), so speculation never runs
slower than the baseline; a static-γ model would not earn that clamp.

:func:`fit_cost_model` estimates all of it from journaled ``ok`` records
using medians (robust to the heavy right tail every serving latency
distribution has): prefill compute per request is recovered as
``ttft_ms − queue_wait`` — both journaled per record — then the affine
fit splits records at the median prompt length and solves the two-point
slope between group medians. Too few records (< ``_MIN_FIT_RECORDS``
usable) falls back to the conservative defaults rather than fitting
noise.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from unionml_tpu.sim.journal import JournalRecord

__all__ = ["CostModel", "fit_cost_model"]

_MIN_FIT_RECORDS = 8


def _median(values: Sequence[float]) -> float:
    ordered = sorted(values)
    n = len(ordered)
    mid = n // 2
    if n % 2:
        return float(ordered[mid])
    return (ordered[mid - 1] + ordered[mid]) / 2.0


@dataclass(frozen=True)
class CostModel:
    """Virtual-clock phase costs (milliseconds). See the module docstring
    for what each term prices; defaults approximate a small paged CPU
    engine and are replaced wholesale by :func:`fit_cost_model` when a
    journal is available."""

    prefill_base_ms: float = 5.0
    prefill_ms_per_token: float = 0.15
    itl_ms: float = 8.0
    itl_ms_by_class: Dict[str, float] = field(default_factory=dict)
    dispatch_ms: float = 1.0
    spec_alpha: float = 0.0  # 0 disables the speculative term entirely
    spec_alpha_by_class: Dict[str, float] = field(default_factory=dict)
    spec_gamma: int = 4
    spec_draft_cost_ratio: float = 0.15  # ρ: draft step cost / target step cost

    def __post_init__(self) -> None:
        for name, value in (
            ("prefill_base_ms", self.prefill_base_ms),
            ("prefill_ms_per_token", self.prefill_ms_per_token),
            ("itl_ms", self.itl_ms),
            ("dispatch_ms", self.dispatch_ms),
            ("spec_draft_cost_ratio", self.spec_draft_cost_ratio),
        ):
            if value < 0:
                raise ValueError(f"{name} must be >= 0, got {value}")
        for cls, alpha in [(None, self.spec_alpha)] + list(
            self.spec_alpha_by_class.items()
        ):
            if not 0.0 <= alpha < 1.0:
                where = f"spec_alpha_by_class[{cls!r}]" if cls else "spec_alpha"
                raise ValueError(f"{where} must be in [0, 1), got {alpha}")
        if self.spec_gamma < 0:
            raise ValueError(f"spec_gamma must be >= 0, got {self.spec_gamma}")

    def prefill_ms(self, tokens_in: int) -> float:
        return self.prefill_base_ms + self.prefill_ms_per_token * max(0, int(tokens_in))

    def ttft_compute_ms(self, tokens_in: int) -> float:
        """Admission-to-first-token compute (excludes queue wait, which the
        real scheduler measures for itself inside the simulator)."""
        return self.dispatch_ms + self.prefill_ms(tokens_in)

    def spec_itl_scale(self, cls: str = "standard") -> float:
        """ITL multiplier for a speculative request of class ``cls``:
        ``min(1, (γ·ρ + 1) / E[tokens])`` with
        ``E[tokens] = (1 − α^(γ+1)) / (1 − α)`` — the clamp models the
        engine's adaptive γ decaying to vanilla on hostile traffic."""
        alpha = self.spec_alpha_by_class.get(cls, self.spec_alpha)
        if alpha <= 0.0 or self.spec_gamma == 0:
            return 1.0
        expected_tokens = (1.0 - alpha ** (self.spec_gamma + 1)) / (1.0 - alpha)
        round_cost = self.spec_gamma * self.spec_draft_cost_ratio + 1.0
        return min(1.0, round_cost / expected_tokens)

    def decode_ms(
        self, tokens_out: int, cls: str = "standard", speculative: bool = False
    ) -> float:
        itl = self.itl_ms_by_class.get(cls, self.itl_ms)
        if speculative:
            itl *= self.spec_itl_scale(cls)
        # first token is priced by prefill; each FURTHER token costs one ITL
        return itl * max(0, int(tokens_out) - 1)

    def service_ms(
        self,
        tokens_in: int,
        tokens_out: int,
        cls: str = "standard",
        speculative: bool = False,
    ) -> float:
        """Slot-occupancy time for one admitted request (no queue wait)."""
        return self.ttft_compute_ms(tokens_in) + self.decode_ms(
            tokens_out, cls, speculative
        )


def fit_cost_model(
    records: Sequence[JournalRecord], default: Optional[CostModel] = None
) -> CostModel:
    """Fit a :class:`CostModel` from journaled completions (see module
    docstring for the estimators). ``default`` supplies every term the
    journal cannot support (too few records, no ITL data for a class)."""
    default = default or CostModel()
    # (tokens_in, compute_ms): ttft minus measured queue wait, floored at 0
    points: List[Tuple[int, float]] = []
    itl_by_class: Dict[str, List[float]] = {}
    for rec in records:
        if rec.status != "ok":
            continue
        if rec.itl_ms is not None:
            itl_by_class.setdefault(rec.cls, []).append(rec.itl_ms)
        if rec.ttft_ms is None:
            continue
        wait = rec.queue_wait_ms or 0.0
        points.append((rec.tokens_in, max(0.0, rec.ttft_ms - wait)))
    if len(points) < _MIN_FIT_RECORDS:
        return default
    split = _median([float(n) for n, _ in points])
    short = [(n, ms) for n, ms in points if n <= split]
    long = [(n, ms) for n, ms in points if n > split]
    if short and long:
        n_short = _median([float(n) for n, _ in short])
        n_long = _median([float(n) for n, _ in long])
        ms_short = _median([ms for _, ms in short])
        ms_long = _median([ms for _, ms in long])
        if n_long > n_short:
            slope = max(0.0, (ms_long - ms_short) / (n_long - n_short))
        else:
            slope = default.prefill_ms_per_token
        base = max(0.0, ms_short - slope * n_short)
    else:
        # all prompts the same length: the slope is unobservable — keep the
        # default slope and absorb the rest into the base
        slope = default.prefill_ms_per_token
        base = max(0.0, _median([ms for _, ms in points]) - slope * points[0][0])
    itl_fit = {cls: round(_median(vals), 4) for cls, vals in itl_by_class.items() if vals}
    all_itl = [v for vals in itl_by_class.values() for v in vals]
    return CostModel(
        prefill_base_ms=round(max(0.0, base - default.dispatch_ms), 4),
        prefill_ms_per_token=round(slope, 6),
        itl_ms=round(_median(all_itl), 4) if all_itl else default.itl_ms,
        itl_ms_by_class=itl_fit,
        dispatch_ms=default.dispatch_ms,
        # journals do not record acceptance; the speculative term rides the
        # defaults through so a CLI-chosen alpha survives the fit
        spec_alpha=default.spec_alpha,
        spec_alpha_by_class=default.spec_alpha_by_class,
        spec_gamma=default.spec_gamma,
        spec_draft_cost_ratio=default.spec_draft_cost_ratio,
    )
