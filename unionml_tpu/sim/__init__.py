"""Trace-driven fleet observatory: journal replay + discrete-event simulation.

The serving stack journals every completed request (``telemetry.py``,
schema v2) and exposes its scheduling policy as engine-free pure host
objects (:class:`~unionml_tpu.serving.scheduler.SLOScheduler`,
:class:`~unionml_tpu.serving.fleet.Router`, the paged-KV block-demand
arithmetic in ``continuous.block_demand``). This package closes the loop:
a deterministic discrete-event simulator drives those SAME policy objects
with a virtual clock, so capacity questions ("how many replicas for a
million users at this SLO?", "does the autoscaler beat static
provisioning?") are answered by the production code paths, not a
re-implementation that would drift.

Two input modes:

- **Journal replay** (:func:`replay_journal`): re-derive every policy
  counter (sheds by reason, preemptions, deadline misses, failover
  adoptions) and the SLO good/total ledger from a recorded journal alone,
  for bit-for-bit validation against the live process that wrote it.
- **Synthetic traces** (:func:`generate_requests`): seeded million-user
  workloads — diurnal rate curves, bursts, heavy-tail lengths, hot-prefix
  skew, session churn, replica-death schedules — fed through
  :class:`FleetSimulator`.

Costs (prefill / inter-token / dispatch latency) come from a
:class:`CostModel`, fit from a real journal with :func:`fit_cost_model`
so the simulator's clock advances at measured speeds.
"""

from unionml_tpu.sim.autoscaler import Autoscaler, AutoscalerConfig
from unionml_tpu.sim.cost_model import CostModel, fit_cost_model
from unionml_tpu.sim.core import FleetSimulator, SimConfig, replay_journal
from unionml_tpu.sim.journal import JournalRecord, load_journal, parse_journal_record
from unionml_tpu.sim.traces import (
    ReplicaDeath,
    SimRequest,
    SyntheticConfig,
    generate_requests,
)

__all__ = [
    "Autoscaler",
    "AutoscalerConfig",
    "CostModel",
    "FleetSimulator",
    "JournalRecord",
    "ReplicaDeath",
    "SimConfig",
    "SimRequest",
    "SyntheticConfig",
    "fit_cost_model",
    "generate_requests",
    "load_journal",
    "parse_journal_record",
    "replay_journal",
]
