"""Deterministic discrete-event fleet simulator driving the REAL policies.

The point of this simulator is that it contains almost no policy code of
its own. Routing is the production :class:`~unionml_tpu.serving.fleet.
Router` (prefix-affinity scoring, session stickiness, LRU digest index);
queueing, aging, shedding, displacement, and deadline enforcement are the
production :class:`~unionml_tpu.serving.scheduler.SLOScheduler` (every
method takes ``now=``, so the virtual clock threads straight through);
paged-KV admission is the production ``block_demand`` arithmetic from
``continuous.py``; SLO scoring is the production
:class:`~unionml_tpu.serving.slo.SLOTracker`. What the simulator adds is
only what hardware would: a virtual clock, slot occupancy, a block-pool
ledger per replica (live/cached/pinned counters shaped exactly like
``DecodeEngine.pool_signal``), and a :class:`~unionml_tpu.sim.cost_model.
CostModel` that prices prefill/decode time. Capacity answers therefore
come from the code that will serve the traffic, at ~10⁵–10⁶ requests per
CPU-minute, with bit-for-bit determinism (no wall clock, no unseeded
randomness anywhere).

Two entry points:

- :class:`FleetSimulator` — synthetic workloads (``sim.traces``),
  optional replica-death schedules, optional in-loop
  :class:`~unionml_tpu.sim.autoscaler.Autoscaler` (scale-up warms the new
  replica's router index from ``Router.hot_digests``).
- :func:`replay_journal` — derive every policy counter and the SLO
  good/total ledger from a recorded journal ALONE, for bit-for-bit
  validation against the live process that wrote it (the tier-1 golden
  replay test).
"""

from __future__ import annotations

import heapq
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

from unionml_tpu.serving.fleet import FleetConfig, Router
from unionml_tpu.serving.scheduler import (
    PRIORITY_CLASSES,
    SchedulerConfig,
    SchedulingError,
    SLOScheduler,
    Ticket,
    class_name,
)
from unionml_tpu.serving.slo import SLOConfig, SLOTracker
from unionml_tpu.sim.autoscaler import Autoscaler, AutoscalerConfig
from unionml_tpu.sim.cost_model import CostModel
from unionml_tpu.sim.journal import JournalRecord
from unionml_tpu.sim.traces import ReplicaDeath, SimRequest

__all__ = ["FleetSimulator", "SimConfig", "replay_journal"]


@dataclass(frozen=True)
class SimConfig:
    """Fleet shape + policies for one :class:`FleetSimulator` run.

    ``num_replicas`` is the STARTING active count; the router (and the
    autoscaler's headroom) is sized to ``max_replicas``. Per-replica
    capacity mirrors a paged :class:`~unionml_tpu.serving.continuous.
    DecodeEngine`: ``num_slots`` decode slots over a pool of
    ``num_blocks`` KV blocks of ``block_size`` tokens.
    """

    num_replicas: int = 2
    max_replicas: Optional[int] = None  # default: num_replicas (no headroom)
    num_slots: int = 4
    num_blocks: int = 512
    block_size: int = 4
    max_len: int = 512
    scheduler: SchedulerConfig = field(default_factory=SchedulerConfig)
    fleet: FleetConfig = field(default_factory=FleetConfig)
    cost: CostModel = field(default_factory=CostModel)
    slo: SLOConfig = field(default_factory=SLOConfig)
    autoscaler: Optional[AutoscalerConfig] = None
    autoscale_interval_s: float = 5.0
    deaths: Tuple[ReplicaDeath, ...] = ()

    def __post_init__(self) -> None:
        if self.num_replicas < 1:
            raise ValueError(f"num_replicas must be >= 1, got {self.num_replicas}")
        ceiling = self.num_replicas if self.max_replicas is None else self.max_replicas
        if ceiling < self.num_replicas:
            raise ValueError(
                f"max_replicas ({ceiling}) must be >= num_replicas ({self.num_replicas})"
            )
        if self.num_slots < 1 or self.num_blocks < 1:
            raise ValueError("num_slots and num_blocks must be >= 1")

    @property
    def replica_ceiling(self) -> int:
        return self.num_replicas if self.max_replicas is None else self.max_replicas


class _Entry:
    """One in-flight request's simulator-side state (the ticket's sink)."""

    __slots__ = (
        "request", "ticket", "replica", "demand", "admit_t", "first_token_t",
        "finish_t", "epoch", "remaining_ms", "done",
    )

    def __init__(self, request: SimRequest, ticket: Ticket) -> None:
        self.request = request
        self.ticket = ticket
        self.replica: Optional[int] = None
        self.demand = 0
        self.admit_t = 0.0
        self.first_token_t: Optional[float] = None
        self.finish_t = 0.0
        self.epoch = 0  # bumped on preempt/cancel to invalidate heap events
        self.remaining_ms: Optional[float] = None  # set when preempted
        self.done = False


class _SimReplica:
    """One replica: a REAL scheduler plus the hardware-shaped ledgers the
    policies read (slots, block pool split live/cached/pinned)."""

    __slots__ = (
        "index", "scheduler", "running", "resume_queue", "num_slots",
        "num_blocks", "live_blocks", "cached_blocks", "pinned_blocks",
        "active", "draining", "_pool_key", "_pool_cache",
    )

    def __init__(self, index: int, config: SimConfig) -> None:
        self.index = index
        self.scheduler = SLOScheduler(config.scheduler)
        self.scheduler.pool_signal = self.pool_signal
        self.running: List[_Entry] = []
        # queued preempted tickets, admission order (their checkpoints pin
        # blocks on THIS replica — tracked for the idle-pool deadlock break)
        self.resume_queue: List[Any] = []
        self.num_slots = config.num_slots
        self.num_blocks = config.num_blocks
        self.live_blocks = 0
        self.cached_blocks = 0
        self.pinned_blocks = 0
        self.active = False
        self.draining = False
        self._pool_key: Optional[Tuple[int, int, int]] = None
        self._pool_cache: Dict[str, Any] = {}

    # ---- the SAME shape DecodeEngine.pool_signal exports (continuous.py),
    # so the scheduler's load_signal()["pool"] block — and anything scoring
    # it, router or autoscaler — cannot tell sim from live. Memoized on the
    # counter triple: every arrival reads all replicas' signals but mutates
    # at most one, so the cache absorbs most of the route-time cost.
    def pool_signal(self) -> Dict[str, Any]:
        key = (self.live_blocks, self.cached_blocks, self.pinned_blocks)
        if key == self._pool_key:
            return self._pool_cache
        total = self.num_blocks
        free = total - key[0] - key[1] - key[2]
        available = max(0, min(total, free + self.cached_blocks - self.pinned_blocks))
        self._pool_key = key
        self._pool_cache = {
            "num_blocks": total,
            "free_frac": round(free / total, 4),
            "live_frac": round(self.live_blocks / total, 4),
            "cached_frac": round(self.cached_blocks / total, 4),
            "pinned_frac": round(self.pinned_blocks / total, 4),
            "available_blocks": available,
            "pressure": round(1.0 - available / total, 4),
        }
        return self._pool_cache

    def available_blocks(self) -> int:
        free = (
            self.num_blocks - self.live_blocks - self.cached_blocks - self.pinned_blocks
        )
        return max(
            0, min(self.num_blocks, free + self.cached_blocks - self.pinned_blocks)
        )

    def allocate(self, demand: int) -> None:
        free = (
            self.num_blocks - self.live_blocks - self.cached_blocks - self.pinned_blocks
        )
        evict = max(0, demand - free)
        self.cached_blocks = max(0, self.cached_blocks - evict)
        self.live_blocks += demand

    def release(self, demand: int) -> None:
        # finished/cancelled KV re-enters the radix cache (reclaimable),
        # clamped to pool capacity like the real LRU would enforce
        self.live_blocks = max(0, self.live_blocks - demand)
        self.cached_blocks = min(
            self.cached_blocks + demand,
            self.num_blocks - self.live_blocks - self.pinned_blocks,
        )

    def load(self) -> float:
        """The fleet ``_candidates()`` load formula, verbatim."""
        signal = self.scheduler.load_signal()
        ema_ms = signal.get("queue_wait_ema_ms") or 0.0
        load = (signal["depth"] + len(self.running)) / max(1, self.num_slots)
        load += ema_ms / 1e3
        pool = signal.get("pool")
        if pool:
            load += float(pool.get("pressure", 0.0))
        return load


class FleetSimulator:
    """Run a synthetic workload through the real serving policies.

    Construct, then :meth:`run` once; the report dict is also kept on
    ``self.report_``. Deterministic: same requests + config → same report.
    """

    def __init__(self, config: SimConfig, requests: Sequence[SimRequest]) -> None:
        from unionml_tpu.serving.continuous import block_demand  # real arithmetic

        self._block_demand = block_demand
        self.config = config
        self.requests = list(requests)
        ceiling = config.replica_ceiling
        self.router = Router(
            ceiling, block_size=config.block_size, config=config.fleet
        )
        self.replicas = [_SimReplica(i, config) for i in range(ceiling)]
        for rep in self.replicas[: config.num_replicas]:
            rep.active = True
        self.slo = SLOTracker(config.slo)
        self.autoscaler = (
            None if config.autoscaler is None else Autoscaler(config.autoscaler)
        )
        # events: (t, seq, kind, payload); seq keeps ordering deterministic
        self._events: List[Tuple[float, int, str, Any]] = []
        self._event_seq = 0
        # counters
        self.completed = 0
        self.sheds: Dict[str, int] = {}
        self.failover_adoptions = 0
        self.rebalanced = 0
        self.dead_replicas: List[int] = []
        # replica-seconds integration
        self._occupancy_t = 0.0
        self._replica_seconds = 0.0
        self._min_active = config.num_replicas
        self._max_active = config.num_replicas
        self._shed_total_last_tick = 0
        self.report_: Optional[Dict[str, Any]] = None

    # ------------------------------------------------------------- event plumbing

    def _push(self, t: float, kind: str, payload: Any) -> None:
        heapq.heappush(self._events, (t, self._event_seq, kind, payload))
        self._event_seq += 1

    def _active_replicas(self) -> List[_SimReplica]:
        return [r for r in self.replicas if r.active and not r.draining]

    def _occupied_count(self) -> int:
        # draining replicas still consume a machine until empty
        return sum(1 for r in self.replicas if r.active)

    def _note_occupancy(self, now: float) -> None:
        self._replica_seconds += self._occupied_count() * (now - self._occupancy_t)
        self._occupancy_t = now

    # ------------------------------------------------------------------ intake

    def _shed(self, entry: _Entry, reason: str, now: float) -> None:
        ticket = entry.ticket
        if ticket is not None and ticket.resume is not None and entry.replica is not None:
            # a preempted request shed while waiting to resume abandons its
            # pinned checkpoint — un-pin it back to reclaimable cache, or the
            # leak wedges the pool (available shrinks monotonically)
            rep = self.replicas[entry.replica]
            rep.pinned_blocks = max(0, rep.pinned_blocks - entry.demand)
            rep.cached_blocks = min(
                rep.cached_blocks + entry.demand,
                rep.num_blocks - rep.live_blocks - rep.pinned_blocks,
            )
            ticket.resume = None
            if ticket in rep.resume_queue:
                rep.resume_queue.remove(ticket)
        self.sheds[reason] = self.sheds.get(reason, 0) + 1
        # mirrors Telemetry.end_trace: a shed is a bad SLO event, no TTFT
        self.slo.record(entry.request.cls, "shed", None, now=now)
        entry.done = True

    def _arrive(self, request: SimRequest, now: float) -> None:
        active = self._active_replicas()
        if not active:
            entry = _Entry(request, None)  # type: ignore[arg-type]
            self._shed(entry, "no_replicas", now)
            return
        candidates = [(rep.index, 1.0, rep.load()) for rep in active]
        index, _decision = self.router.route(
            request.prompt, candidates, session_id=request.session_id
        )
        rep = self.replicas[index]
        ticket = rep.scheduler.make_ticket(
            request.prompt, request.budget, None, None,
            priority=request.cls, deadline_ms=request.deadline_ms, now=now,
        )
        entry = _Entry(request, ticket)
        ticket.sink = entry
        try:
            displaced = rep.scheduler.submit(ticket, now=now)
        except SchedulingError as exc:
            self._shed(entry, exc.reason, now)
            return
        if displaced is not None:
            self._shed(displaced.sink, displaced.shed_exc.reason, now)
        self._admit_loop(rep, now)

    # --------------------------------------------------------------- admission

    def _entry_demand(self, entry: _Entry) -> int:
        return self._block_demand(
            len(entry.request.prompt), entry.request.budget,
            max_len=self.config.max_len, block_size=self.config.block_size,
        )

    def _admit_loop(self, rep: _SimReplica, now: float) -> None:
        """The batcher admission loop: expire, then admit in scheduler order
        while slots AND blocks allow; head-of-line blocks on block demand
        exactly like the paged engine (no skip-ahead — that would invert
        the priority order the scheduler just computed)."""
        for expired in rep.scheduler.take_expired(now):
            self._shed(expired.sink, "deadline_exceeded", now)
        while True:
            if len(rep.running) >= rep.num_slots:
                if not self._try_preempt(rep, now):
                    return
            ticket = rep.scheduler.peek(now)
            if ticket is None:
                return
            entry: _Entry = ticket.sink
            resume = ticket.resume is not None
            demand = entry.demand if resume else self._entry_demand(entry)
            if not resume and demand > rep.available_blocks():
                # HOL block: pool pressure gates admission — with one escape.
                # When NOTHING is running, no finish can ever grow
                # availability, and a head whose demand exceeds a pool pinned
                # down by queued checkpoints would wedge this replica for the
                # rest of the run (the live batcher reaches the same state
                # but deadline enforcement clears it; a deadline-less head
                # has no such clock). A queued resume admits out of order:
                # resumption adopts its OWN pinned blocks — it allocates
                # nothing — and its eventual finish is the only transition
                # that can un-pin the pool from here.
                if rep.running or not rep.resume_queue:
                    return
                ticket = rep.resume_queue[0]
                entry = ticket.sink
                resume = True
                demand = entry.demand
            if not rep.scheduler.pop_ticket(ticket, now):
                return
            if resume:
                if ticket in rep.resume_queue:
                    rep.resume_queue.remove(ticket)
                # checkpoint blocks un-pin and go live again (transfer)
                rep.pinned_blocks = max(0, rep.pinned_blocks - demand)
                rep.live_blocks += demand
                ticket.resume = None
                service_ms = self.config.cost.dispatch_ms + (entry.remaining_ms or 0.0)
                entry.remaining_ms = None
            else:
                rep.allocate(demand)
                entry.demand = demand
                service_ms = self.config.cost.service_ms(
                    len(entry.request.prompt), entry.request.budget, entry.request.cls,
                    speculative=getattr(entry.request, "speculative", False),
                )
            entry.replica = rep.index
            entry.admit_t = now
            if entry.first_token_t is None:
                entry.first_token_t = now + (
                    self.config.cost.ttft_compute_ms(len(entry.request.prompt)) / 1e3
                )
            entry.finish_t = now + service_ms / 1e3
            entry.epoch += 1
            rep.running.append(entry)
            deadline = ticket.deadline
            if deadline is not None and entry.finish_t > deadline:
                self._push(deadline, "deadline", (rep.index, entry, entry.epoch))
            else:
                self._push(entry.finish_t, "finish", (rep.index, entry, entry.epoch))

    def _try_preempt(self, rep: _SimReplica, now: float) -> bool:
        """Preempt-to-prefix-cache: when a strictly-more-urgent class waits
        with no free slot, checkpoint the worst runner (lowest class, most
        time remaining) and requeue it — the scheduler's counters and the
        resume bookkeeping are the production objects' own."""
        if not self.config.scheduler.preempt:
            return False
        best = rep.scheduler.best_waiting_priority()
        if best is None or not rep.running:
            return False
        victim = max(rep.running, key=lambda e: (e.ticket.priority, e.finish_t))
        if best >= victim.ticket.priority:
            return False
        if victim.admit_t >= now:
            # never preempt work admitted at this same instant — the live
            # batcher interleaves admissions with engine steps, so a runner
            # always holds its slot for at least one step; without this the
            # zero-time admit loop could ping-pong preemptions forever
            return False
        rep.running.remove(victim)
        victim.epoch += 1  # invalidates its finish/deadline heap event
        victim.remaining_ms = max(0.0, (victim.finish_t - now) * 1e3)
        # live blocks become a pinned checkpoint (LRU-eviction-proof)
        rep.live_blocks = max(0, rep.live_blocks - victim.demand)
        rep.pinned_blocks += victim.demand
        victim.ticket.resume = victim  # resume tickets bypass queue bounds
        rep.scheduler.requeue(victim.ticket, preemption=True)
        rep.resume_queue.append(victim.ticket)
        return True

    # ------------------------------------------------------------- completions

    def _finish(self, rep: _SimReplica, entry: _Entry, now: float) -> None:
        rep.running.remove(entry)
        rep.release(entry.demand)
        entry.done = True
        self.completed += 1
        ttft_ms = None
        if entry.first_token_t is not None:
            # journaled at 3 decimals; round HERE so replay cannot disagree
            ttft_ms = round((entry.first_token_t - entry.request.arrival_s) * 1e3, 3)
        self.slo.record(entry.request.cls, "ok", ttft_ms, now=now)
        self._admit_loop(rep, now)

    def _deadline_cancel(self, rep: _SimReplica, entry: _Entry, now: float) -> None:
        rep.running.remove(entry)
        rep.release(entry.demand)
        rep.scheduler.note_deadline_miss_running()
        self._shed(entry, "deadline_exceeded", now)
        self._admit_loop(rep, now)

    # ---------------------------------------------------------------- failover

    def _kill_replica(self, index: int, now: float) -> None:
        rep = self.replicas[index]
        if not rep.active:
            return
        rep.active = False
        rep.draining = False
        self.dead_replicas.append(index)
        self.router.on_replica_failed(index)
        orphans = [t.sink for t in rep.scheduler.drain()]
        orphans.extend(rep.running)
        for entry in orphans:
            # progress — running KV and preempt checkpoints alike — dies
            # with the replica; adoptees restart fresh on the survivor
            entry.epoch += 1
            entry.remaining_ms = None
            entry.first_token_t = None
            entry.ticket.resume = None
        rep.running = []
        rep.resume_queue = []
        rep.live_blocks = rep.cached_blocks = rep.pinned_blocks = 0
        survivors = self._active_replicas()
        for entry in orphans:
            if not survivors:
                self._shed(entry, "no_replicas", now)
                continue
            target = min(survivors, key=lambda r: (r.load(), r.index))
            # the live fleet adopts via requeue(preemption=False): deadline
            # and class ride along, the bound is bypassed (work is owed)
            target.scheduler.requeue(entry.ticket, preemption=False)
            self.failover_adoptions += 1
        for target in survivors:
            self._admit_loop(target, now)

    # -------------------------------------------------------------- autoscaling

    def _total_sheds(self) -> int:
        return sum(self.sheds.values())

    def _autoscale_tick(self, now: float) -> None:
        assert self.autoscaler is not None
        active = self._active_replicas()
        signals = [rep.scheduler.load_signal() for rep in active]
        sheds_now = self._total_sheds()
        shed_rate = (sheds_now - self._shed_total_last_tick) / max(
            1e-9, self.config.autoscale_interval_s
        )
        self._shed_total_last_tick = sheds_now
        delta = self.autoscaler.decide(now, signals, shed_rate)
        if delta > 0:
            self._scale_up(now)
        elif delta < 0:
            self._scale_down(now)

    def _scale_up(self, now: float) -> None:
        for rep in self.replicas:
            if not rep.active and rep.index not in self.dead_replicas:
                self._note_occupancy(now)
                rep.active = True
                rep.draining = False
                # warm the newcomer's affinity index with the fleet's hottest
                # digests so it attracts (not repels) the traffic it is for
                warm = self.config.autoscaler.warm_digests if self.config.autoscaler else 0
                if warm > 0:
                    self.router.warm_replica(rep.index, self.router.hot_digests(warm))
                self._max_active = max(self._max_active, self._occupied_count())
                return

    def _scale_down(self, now: float) -> None:
        candidates = self._active_replicas()
        if len(candidates) <= 1:
            return
        # retire the emptiest replica (highest index breaks ties: scale-down
        # walks back the same order scale-up walked forward)
        rep = min(candidates, key=lambda r: (len(r.running) + r.scheduler.depth, -r.index))
        rep.draining = True
        survivors = self._active_replicas()
        for ticket in rep.scheduler.drain():
            if ticket.resume is not None:
                # the checkpoint's blocks live on the retiring replica; the
                # adopting one cannot resume from them — demote to a fresh
                # admission and release the pin
                entry: _Entry = ticket.sink
                rep.pinned_blocks = max(0, rep.pinned_blocks - entry.demand)
                ticket.resume = None
                entry.remaining_ms = None
                entry.first_token_t = None
            target = min(survivors, key=lambda r: (r.load(), r.index))
            target.scheduler.requeue(ticket, preemption=False)
            self.rebalanced += 1
        rep.resume_queue = []
        for target in survivors:
            self._admit_loop(target, now)
        self._maybe_retire(rep, now)
        self._min_active = min(self._min_active, self._occupied_count())

    def _maybe_retire(self, rep: _SimReplica, now: float) -> None:
        if rep.draining and not rep.running and rep.scheduler.depth == 0:
            self._note_occupancy(now)
            rep.active = False
            rep.draining = False
            rep.live_blocks = rep.cached_blocks = rep.pinned_blocks = 0
            self.router.on_replica_rebuilding(rep.index)  # cache gone; sessions keep

    # --------------------------------------------------------------------- run

    def run(self) -> Dict[str, Any]:
        config = self.config
        for death in config.deaths:
            self._push(death.at_s, "death", death.replica)
        if self.autoscaler is not None:
            self._push(config.autoscale_interval_s, "autoscale", None)
        pointer = 0
        n = len(self.requests)
        now = 0.0
        while True:
            next_arrival = self.requests[pointer].arrival_s if pointer < n else None
            next_event_t = self._events[0][0] if self._events else None
            if next_arrival is None and next_event_t is None:
                break
            if next_event_t is None or (
                next_arrival is not None and next_arrival <= next_event_t
            ):
                now = max(now, next_arrival)
                self._note_occupancy(now)
                self._arrive(self.requests[pointer], now)
                pointer += 1
                continue
            t, _seq, kind, payload = heapq.heappop(self._events)
            now = max(now, t)
            self._note_occupancy(now)
            if kind == "finish" or kind == "deadline":
                index, entry, epoch = payload
                rep = self.replicas[index]
                if entry.epoch != epoch or entry.done or entry not in rep.running:
                    continue  # stale: preempted, cancelled, or replica died
                if kind == "finish":
                    self._finish(rep, entry, now)
                else:
                    self._deadline_cancel(rep, entry, now)
                self._maybe_retire(rep, now)
            elif kind == "death":
                self._kill_replica(int(payload), now)
            elif kind == "autoscale":
                self._autoscale_tick(now)
                # reschedule only while the sim can still make progress:
                # arrivals remain, or something is running (whose finish
                # event will drive admission). A queue with nothing running
                # and no arrivals left is wedged — ticking the autoscaler
                # at +5s forever would never unwedge it (the final sweep
                # below accounts for it instead).
                work_left = pointer < n or any(r.running for r in self.replicas)
                if work_left:
                    self._push(now + config.autoscale_interval_s, "autoscale", None)
        # final sweep: anything still queued when events ran out (e.g. a
        # head-of-line block with no replica left to drain it) must land in
        # the ledger — every request ends completed or shed, never lost
        for rep in self.replicas:
            for expired in rep.scheduler.take_expired(now):
                self._shed(expired.sink, "deadline_exceeded", now)
            for ticket in rep.scheduler.drain():
                self._shed(ticket.sink, "sim_ended", now)
        self._note_occupancy(now)
        self.report_ = self._report(now)
        return self.report_

    # ------------------------------------------------------------------ report

    def _scheduler_totals(self) -> Dict[str, int]:
        keys = (
            "submitted", "admitted", "shed_queue_full", "shed_deadline_infeasible",
            "deadline_misses_queued", "deadline_misses_running", "preemptions",
            "resumes",
        )
        totals = {key: 0 for key in keys}
        for rep in self.replicas:
            for key in keys:
                totals[key] += getattr(rep.scheduler, key)
        return totals

    def _report(self, end_t: float) -> Dict[str, Any]:
        duration = max(end_t, 1e-9)
        totals = self.slo.totals()
        good = sum(c["good"] for c in totals.values())
        total = sum(c["total"] for c in totals.values())
        avg_replicas = self._replica_seconds / duration
        return {
            "duration_s": round(duration, 3),
            "requests": len(self.requests),
            "completed": self.completed,
            "shed": dict(sorted(self.sheds.items())),
            "failover_adoptions": self.failover_adoptions,
            "rebalanced": self.rebalanced,
            "dead_replicas": list(self.dead_replicas),
            "scheduler": self._scheduler_totals(),
            "router": self.router.stats(),
            "replicas": {
                "initial": self.config.num_replicas,
                "ceiling": self.config.replica_ceiling,
                "min": self._min_active,
                "max": self._max_active,
                "avg": round(avg_replicas, 4),
                "replica_seconds": round(self._replica_seconds, 3),
            },
            "autoscaler": None if self.autoscaler is None else self.autoscaler.stats(),
            "slo": self.slo.report(now=end_t),
            "slo_totals": totals,
            "attainment": None if total == 0 else round(good / total, 6),
            "attainment_per_replica": (
                None
                if total == 0 or avg_replicas <= 0
                else round((good / total) / avg_replicas, 6)
            ),
        }


def replay_journal(
    records: Sequence[JournalRecord], slo: Optional[SLOConfig] = None
) -> Dict[str, Any]:
    """Re-derive the policy counters and SLO ledger from a journal ALONE.

    Every number here is computed from journal fields only — no access to
    the process that wrote it — so comparing the result against the live
    scheduler/telemetry counters is a bit-for-bit validation that the
    journal is a sufficient record of what the policies did (the tier-1
    golden replay test). Works on v1 journals too; v2 adds the block
    arithmetic fields (``block_demand`` / ``available_blocks``) that are
    checked for internal consistency when present.
    """
    tracker = SLOTracker(slo)
    sheds: Dict[str, int] = {}
    status_counts: Dict[str, int] = {}
    preemptions = 0
    resumes = 0
    failover_adoptions = 0
    deadline_misses_queued = 0
    deadline_misses_running = 0
    by_class = {name: 0 for name in PRIORITY_CLASSES}
    block_demand_violations = 0
    for i, rec in enumerate(records):
        status_counts[rec.status] = status_counts.get(rec.status, 0) + 1
        if rec.cls in by_class:
            by_class[rec.cls] += 1
        if rec.status == "shed":
            reason = rec.reason or "rejected"
            sheds[reason] = sheds.get(reason, 0) + 1
            if reason == "deadline_exceeded":
                # a queued expiry never got a slot; a running cancel did
                if rec.first_span("admitted") is None:
                    deadline_misses_queued += 1
                else:
                    deadline_misses_running += 1
        preemptions += rec.span_count("preempted")
        failover_adoptions += rec.span_count("failover_adopt")
        for span in rec.spans:
            if span.get("kind") == "queue_wait" and span.get("attrs", {}).get("resume"):
                resumes += 1
        demand = rec.block_demand
        available = rec.available_blocks
        if demand is not None and available is not None and rec.first_span("admitted"):
            # v2 invariant: nothing is ADMITTED into more blocks than the
            # pool could reclaim at admission time
            if demand > available:
                block_demand_violations += 1
        # virtual clock: journal emission order at 1ms spacing keeps every
        # record inside the rolling windows without touching wall time
        tracker.record(rec.cls, rec.status, rec.ttft_ms, now=i * 1e-3)
    return {
        "records": len(records),
        "status": dict(sorted(status_counts.items())),
        "by_class": by_class,
        "shed": dict(sorted(sheds.items())),
        "preemptions": preemptions,
        "resumes": resumes,
        "failover_adoptions": failover_adoptions,
        "deadline_misses_queued": deadline_misses_queued,
        "deadline_misses_running": deadline_misses_running,
        "block_demand_violations": block_demand_violations,
        "slo_totals": tracker.totals(),
    }
