"""Versioned loader for the serving telemetry journal (JSONL, one trace/line).

The journal is written by :class:`~unionml_tpu.serving.telemetry.Telemetry`
(``journal_path=``); its schema version rides on every record as ``"v"``.
This module is the ONLY place the simulator touches raw journal bytes, so
schema evolution is absorbed here:

- **v1** (PR 9): request_id / class / status / tokens / spans; admission
  spans carry prompt_tokens + budget only.
- **v2** (this PR): adds top-level ``session_id`` and admission-span
  ``block_demand`` + ``available_blocks`` (the paged-KV arithmetic at
  admission time), and the ``queue_wait`` span carries ``cls``. v1 records
  load fine — the new fields default to ``None`` and replay simply cannot
  validate block accounting for them (see ``docs/observability.md`` for
  the migration notes).

Unknown FUTURE versions are rejected loudly: silently misreading a v3
journal would poison a replay validation, which is the one thing this
loader must never do.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

__all__ = [
    "SUPPORTED_JOURNAL_VERSIONS",
    "JournalRecord",
    "load_journal",
    "parse_journal_record",
]

#: journal schema versions this loader understands (see module docstring)
SUPPORTED_JOURNAL_VERSIONS: Tuple[int, ...] = (1, 2)


@dataclass(frozen=True)
class JournalRecord:
    """One completed request as journaled — the simulator's unit of replay.

    ``spans`` is the raw span list (dicts) in emission order; the
    convenience accessors below pull out the fields replay and cost-model
    fitting need, returning ``None`` when a span or attribute is absent
    (v1 journals, dense engines, sheds that never queued).
    """

    version: int
    request_id: str
    created_unix: float
    cls: str
    status: str
    tokens_in: int
    tokens_out: int
    reason: Optional[str] = None
    session_id: Optional[str] = None
    ttft_ms: Optional[float] = None
    itl_ms: Optional[float] = None
    spans: List[Dict[str, Any]] = field(default_factory=list)

    def first_span(self, kind: str) -> Optional[Dict[str, Any]]:
        """The first span of ``kind`` (emission order), or ``None``."""
        for span in self.spans:
            if span.get("kind") == kind:
                return span
        return None

    def span_count(self, kind: str) -> int:
        """How many spans of ``kind`` the trace carries (preemptions etc.)."""
        return sum(1 for span in self.spans if span.get("kind") == kind)

    def _admission_attr(self, name: str) -> Optional[Any]:
        span = self.first_span("admission")
        if span is None:
            return None
        return span.get("attrs", {}).get(name)

    @property
    def queue_wait_ms(self) -> Optional[float]:
        span = self.first_span("queue_wait")
        return None if span is None else span.get("dur_ms")

    @property
    def block_demand(self) -> Optional[int]:
        """Blocks the request needed at admission (v2, paged engines)."""
        value = self._admission_attr("block_demand")
        return None if value is None else int(value)

    @property
    def available_blocks(self) -> Optional[int]:
        """Counter-derived reclaimable blocks observed at admission (v2)."""
        value = self._admission_attr("available_blocks")
        return None if value is None else int(value)

    @property
    def deadline_ms(self) -> Optional[float]:
        value = self._admission_attr("deadline_ms")
        return None if value is None else float(value)

    @property
    def replica(self) -> Optional[int]:
        """The fleet replica the request was routed to (solo: ``None``)."""
        span = self.first_span("route")
        if span is None:
            return None
        value = span.get("attrs", {}).get("replica")
        return None if value is None else int(value)


def parse_journal_record(obj: Dict[str, Any]) -> JournalRecord:
    """Build a :class:`JournalRecord` from one decoded journal line.

    Accepts every version in :data:`SUPPORTED_JOURNAL_VERSIONS` (records
    with no ``"v"`` at all are treated as v1 — the field predates the
    versioning convention by zero releases, but a truncated writer should
    not brick a replay). Raises ``ValueError`` for future versions or
    records missing the required identity fields.
    """
    if not isinstance(obj, dict):
        raise ValueError(f"journal record must be an object, got {type(obj).__name__}")
    version = int(obj.get("v", 1))
    if version not in SUPPORTED_JOURNAL_VERSIONS:
        raise ValueError(
            f"unsupported journal schema v{version} "
            f"(supported: {list(SUPPORTED_JOURNAL_VERSIONS)}); "
            "refusing to misread a future journal"
        )
    try:
        request_id = str(obj["request_id"])
        status = str(obj["status"])
    except KeyError as missing:
        raise ValueError(f"journal record missing required field {missing}") from None
    spans = obj.get("spans") or []
    if not isinstance(spans, list):
        raise ValueError(f"journal spans must be a list, got {type(spans).__name__}")
    return JournalRecord(
        version=version,
        request_id=request_id,
        created_unix=float(obj.get("created_unix", 0.0)),
        cls=str(obj.get("class", "standard")),
        status=status,
        tokens_in=int(obj.get("tokens_in", 0)),
        tokens_out=int(obj.get("tokens_out", 0)),
        reason=obj.get("reason"),
        session_id=obj.get("session_id"),  # v2; absent in v1
        ttft_ms=None if obj.get("ttft_ms") is None else float(obj["ttft_ms"]),
        itl_ms=None if obj.get("itl_ms") is None else float(obj["itl_ms"]),
        spans=spans,
    )


def load_journal(path: str) -> List[JournalRecord]:
    """Parse a journal JSONL file into records (emission order preserved).

    Blank lines are skipped; a malformed line raises with its line number —
    replay validation on a corrupt journal must fail, not shrug.
    """
    records: List[JournalRecord] = []
    with open(path, "r", encoding="utf-8") as handle:
        for lineno, line in enumerate(handle, start=1):
            line = line.strip()
            if not line:
                continue
            try:
                records.append(parse_journal_record(json.loads(line)))
            except (ValueError, json.JSONDecodeError) as exc:
                raise ValueError(f"{path}:{lineno}: bad journal line: {exc}") from exc
    return records
