"""Replica-count autoscaler scored against the scheduler's own signals.

The policy consumes exactly what the fleet already exports — each
replica's :meth:`~unionml_tpu.serving.scheduler.SLOScheduler.load_signal`
(queue depth, queue-wait EMAs, paged-pool occupancy) plus the fleet-wide
shed rate — and emits an integer replica delta. It is deliberately pure
host arithmetic with an injected clock so the SAME object runs inside the
discrete-event simulator (where it is validated against static
provisioning, ``bench_sim.py``) and against a live fleet's signals.

Scale-up triggers on ANY pressure source (queue-wait EMA above target,
block-pool pressure above threshold, or live shedding): these fail at
different times — the pool saturates before queue waits move when decodes
are long, shedding spikes before either on a flash crowd. Scale-down
requires EVERY signal comfortable AND a sustained trajectory (consecutive
calm ticks), because adding a replica is cheap but removing one discards
a warm radix cache. Both directions respect ``cooldown_s`` so the policy
cannot flap on its own control lag, and scale-up cooldown is waived when
shedding is active (dropping traffic now outweighs smoothing).

On scale-up the caller should warm the new replica's router index from
:meth:`~unionml_tpu.serving.fleet.Router.hot_digests` (see
``Router.warm_replica``) — a cold affinity index repels exactly the
traffic that would warm it.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Optional, Sequence

__all__ = ["Autoscaler", "AutoscalerConfig"]


@dataclass(frozen=True)
class AutoscalerConfig:
    """Thresholds and pacing for :class:`Autoscaler`.

    :param min_replicas: floor (never scale below).
    :param max_replicas: ceiling (never scale above).
    :param target_queue_wait_ms: mean per-replica queue-wait EMA above which
        the fleet is considered behind.
    :param low_queue_wait_ms: EMA below which a replica is a scale-down
        candidate (hysteresis: well under the target).
    :param pool_pressure_high: block-pool pressure (1 − reclaimable
        fraction) above which paged replicas are memory-bound.
    :param shed_rate_high: sheds/s fleet-wide above which capacity is
        actively dropping traffic (waives the scale-up cooldown).
    :param cooldown_s: minimum time between scaling actions.
    :param calm_ticks: consecutive comfortable evaluations required before
        a scale-down (trajectory, not a single quiet sample).
    :param warm_digests: how many hot prefix digests to seed into a new
        replica's router index on scale-up.
    """

    min_replicas: int = 1
    max_replicas: int = 8
    target_queue_wait_ms: float = 250.0
    low_queue_wait_ms: float = 50.0
    pool_pressure_high: float = 0.85
    shed_rate_high: float = 0.5
    cooldown_s: float = 30.0
    calm_ticks: int = 3
    warm_digests: int = 128

    def __post_init__(self) -> None:
        if not (1 <= self.min_replicas <= self.max_replicas):
            raise ValueError(
                f"need 1 <= min_replicas <= max_replicas, got "
                f"{self.min_replicas}..{self.max_replicas}"
            )
        if self.low_queue_wait_ms >= self.target_queue_wait_ms:
            raise ValueError("low_queue_wait_ms must sit below target_queue_wait_ms")


class Autoscaler:
    """Single-threaded policy object: call :meth:`decide` on a fixed tick.

    Not thread-safe by design — the simulator ticks it on the virtual
    clock; a live deployment ticks it from one control loop.
    """

    def __init__(self, config: Optional[AutoscalerConfig] = None) -> None:
        self.config = config or AutoscalerConfig()
        self._last_action_t: Optional[float] = None
        self._calm_streak = 0
        # lifetime counters (sim report / live stats)
        self.ups = 0
        self.downs = 0
        self.holds = 0

    def decide(
        self,
        now: float,
        signals: Sequence[Dict[str, Any]],
        shed_rate_per_s: float = 0.0,
    ) -> int:
        """Return the replica delta (+1, −1, or 0) for this tick.

        ``signals`` is one ``load_signal()`` dict per ACTIVE replica;
        ``shed_rate_per_s`` is the fleet's shed throughput since the last
        tick. The caller applies the delta (and the router warm-up).
        """
        cfg = self.config
        n = len(signals)
        if n == 0:
            return 0
        # an idle replica's queue-wait EMA is FROZEN at whatever the last
        # storm left there (EMAs only update on pops), so score a replica's
        # wait only while something is actually queued on it — otherwise a
        # replica that stopped receiving traffic pins the fleet "behind"
        # forever and scale-down never fires
        waits = [
            (s.get("queue_wait_ema_ms") or 0.0) if (s.get("depth") or 0) > 0 else 0.0
            for s in signals
        ]
        mean_wait = sum(waits) / n
        pressures = []
        for s in signals:
            pool = s.get("pool")
            if pool:
                pressures.append(float(pool.get("pressure", 0.0)))
        max_pressure = max(pressures) if pressures else 0.0
        behind = (
            mean_wait > cfg.target_queue_wait_ms
            or max_pressure > cfg.pool_pressure_high
            or shed_rate_per_s > cfg.shed_rate_high
        )
        comfortable = (
            mean_wait < cfg.low_queue_wait_ms
            and max_pressure < cfg.pool_pressure_high / 2.0
            and shed_rate_per_s == 0.0
        )
        self._calm_streak = self._calm_streak + 1 if comfortable else 0
        in_cooldown = (
            self._last_action_t is not None
            and now - self._last_action_t < cfg.cooldown_s
        )
        if behind and n < cfg.max_replicas:
            # shedding waives the cooldown: smoothing is pointless while
            # requests are being dropped on the floor
            if not in_cooldown or shed_rate_per_s > cfg.shed_rate_high:
                self._last_action_t = now
                self._calm_streak = 0
                self.ups += 1
                return 1
        elif (
            self._calm_streak >= cfg.calm_ticks
            and n > cfg.min_replicas
            and not in_cooldown
        ):
            self._last_action_t = now
            self._calm_streak = 0
            self.downs += 1
            return -1
        self.holds += 1
        return 0

    def stats(self) -> Dict[str, int]:
        return {"ups": self.ups, "downs": self.downs, "holds": self.holds}
