"""Exception hierarchy for unionml_tpu.

Reference parity: ``unionml/exceptions.py:4`` defines only ``ModelArtifactNotFound``; the
rebuild grows a small hierarchy covering the stage runtime, backend, and scheduling
subsystems (SURVEY.md §2 row 14).
"""


class UnionMLError(Exception):
    """Base class for all unionml_tpu errors."""


class ModelArtifactNotFound(UnionMLError):
    """Raised when a model artifact cannot be resolved from any source."""


class VersionFetchError(UnionMLError):
    """Raised when an app version cannot be derived (e.g. dirty git tree).

    Reference parity: ``unionml/remote.py:26-27``.
    """


class StageError(UnionMLError):
    """Raised when a stage fails to execute or compile."""


class WorkflowError(UnionMLError):
    """Raised when a workflow graph is malformed or fails to execute."""


class BackendError(UnionMLError):
    """Raised by the execution backend (job submission, artifact store)."""


class ScheduleError(UnionMLError):
    """Raised for invalid schedule specifications."""


class TrackingError(UnionMLError):
    """Raised when a tracked instance cannot be resolved to a module-level variable."""
