"""unionml-tpu: a TPU-native ML microservice framework.

The two core exports mirror the reference's public surface
(``unionml/__init__.py:4-5``): :class:`~unionml_tpu.dataset.Dataset` and
:class:`~unionml_tpu.model.Model`. Everything the user registers through their
decorators becomes jit/pjit-compiled stages executed locally, behind an HTTP endpoint
with a resident XLA predictor, or on the execution backend with versioned artifacts and
schedules.
"""

from unionml_tpu.dataset import Dataset
from unionml_tpu.model import BaseHyperparameters, Model, ModelArtifact

try:  # installed-package metadata wins (reference __init__.py version-from-metadata parity)
    from importlib.metadata import version as _pkg_version

    __version__ = _pkg_version("unionml-tpu")
except Exception:  # source checkout without package metadata: the fallback version IS the handling
    __version__ = "0.1.0"

__all__ = ["Dataset", "Model", "ModelArtifact", "BaseHyperparameters", "__version__"]
