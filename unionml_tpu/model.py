"""Model: declarative spec for training, evaluation, prediction, and deployment.

Reference parity: ``unionml/model.py:59-1566`` — the same decorator slots (``trainer``,
``predictor``, ``evaluator`` required; ``init``/``saver``/``loader`` defaulted), task and
workflow factories, local ``train``/``predict``, persistence, scheduling, and the full
``remote_*`` surface.

TPU-native deltas:

- ``trainer``/``predictor``/``evaluator`` are wrapped as :class:`~unionml_tpu.stage.TracedFunction`
  — ``jax.jit``-compiled when their inputs are jax pytrees, eager for opaque model objects
  (sklearn/torch/keras). This is the BASELINE.json north-star requirement.
- the remote backend is an in-framework execution backend
  (:mod:`unionml_tpu.backend`) whose job specs request TPU pod-slice resources
  (accelerator/topology/host_count) — never GPUs — replacing Flyte + docker registries.
- default persistence understands JAX pytrees/flax states in addition to
  sklearn/torch/keras model objects.
"""

import inspect
import os
from collections import OrderedDict
from dataclasses import asdict, field, is_dataclass
from datetime import timedelta
from inspect import Parameter, signature
from pathlib import Path
from typing import IO, Any, Callable, Dict, List, NamedTuple, Optional, Tuple, Type, Union, get_origin

from unionml_tpu import type_guards
from unionml_tpu._logging import logger
from unionml_tpu.dataset import Dataset
from unionml_tpu.defaults import DEFAULT_RESOURCES, Resources
from unionml_tpu.exceptions import ModelArtifactNotFound
from unionml_tpu.schedule import Schedule, ScheduleType
from unionml_tpu.stage import Stage, TracedFunction, _scalarize, stage
from unionml_tpu.tracker import TrackedInstance
from unionml_tpu.utils import make_json_dataclass
from unionml_tpu.workflow import Workflow

_EMPTY = Parameter.empty


class BaseHyperparameters:
    """Base class for synthesized hyperparameter dataclasses (``model.py:35-43``)."""


class ModelArtifact(NamedTuple):
    """A trained model object plus the hyperparameters and metrics that produced it."""

    model_object: Any
    hyperparameters: Optional[Union[BaseHyperparameters, dict]] = None
    metrics: Optional[Dict[str, float]] = None


class Model(TrackedInstance):
    """Specification of a trainable, servable, deployable model."""

    def __init__(
        self,
        name: str = "model",
        init: Union[Type, Callable, None] = None,
        *,
        dataset: Dataset,
        hyperparameter_config: Optional[Dict[str, Type]] = None,
    ):
        super().__init__()
        self.name = name
        self._init_callable = init
        self._hyperparameter_config = hyperparameter_config
        self._dataset = dataset
        self._artifact: Optional[ModelArtifact] = None

        self._init: Callable = self._default_init
        self._saver: Callable = self._default_saver
        self._loader: Callable = self._default_loader
        self._trainer: Optional[Callable] = None
        self._predictor: Optional[Callable] = None
        self._evaluator: Optional[Callable] = None

        # deployment configuration (set via Model.remote)
        self._backend = None
        self._config_file: Optional[str] = None
        self._project: Optional[str] = None
        self._domain: Optional[str] = None
        self._resources: Optional[Resources] = None
        self._patch_destination_dir: Optional[str] = None

        if self._dataset.name is None:
            self._dataset.name = f"{self.name}.dataset"

        self._train_stage: Optional[Stage] = None
        self._predict_stage: Optional[Stage] = None
        self._predict_from_features_stage: Optional[Stage] = None
        self._predict_callbacks: Tuple[Callable, ...] = ()

        self._train_stage_kwargs: Optional[Dict[str, Any]] = None
        self._predict_stage_kwargs: Optional[Dict[str, Any]] = None

        self._hyperparameter_type: Optional[Type] = None

        self._training_schedules: List[Schedule] = []
        self._prediction_schedules: List[Schedule] = []

    # ------------------------------------------------------------------ properties

    @property
    def artifact(self) -> Optional[ModelArtifact]:
        """The in-memory model artifact (set by train/load/remote_load)."""
        return self._artifact

    @artifact.setter
    def artifact(self, new_value: ModelArtifact) -> None:
        self._artifact = new_value

    @property
    def dataset(self) -> Dataset:
        return self._dataset

    @property
    def predict_callbacks(self) -> Tuple[Callable, ...]:
        return self._predict_callbacks

    @predict_callbacks.setter
    def predict_callbacks(self, callbacks) -> None:
        if self._predict_callbacks:
            raise ValueError("Predict callbacks can only be set once on a model.")
        self._predict_callbacks = tuple(callbacks)

    @property
    def hyperparameter_type(self) -> Type:
        """Synthesize the hyperparameter dataclass type (``model.py:169-204``).

        Resolution order: explicit ``hyperparameter_config`` > single dict-annotated init
        argument > partially annotated signature (defaults fill types) > fully annotated
        signature.
        """
        if self._hyperparameter_type is None:
            self._hyperparameter_type = self._synthesize_hyperparameter_type(self._hyperparameter_config)
        return self._hyperparameter_type

    def _synthesize_hyperparameter_type(self, config: Optional[Dict[str, Any]]) -> Type:
        """Pure derivation of the hyperparameter type from an explicit config or the init
        signature — no instance state is read besides the init slots, none is written.
        (Thread-safety: ``train``/``remote_train`` call this with an ad-hoc config instead
        of temporarily mutating ``_hyperparameter_config``.)
        """
        init_fn = self._init_callable if self._init == self._default_init else self._init
        init_fn = init_fn or self._init_callable
        sig_params = [] if init_fn is None else [*signature(init_fn).parameters.values()]
        # drop a leading `self`-like hyperparameters param when init is the default bound method
        specs: List[Any] = []

        if config is not None:
            for hname, htype in config.items():
                specs.append((hname, htype))
        elif len(sig_params) == 1 and sig_params[0].annotation is dict:
            return dict
        elif any(p.annotation is _EMPTY for p in sig_params):
            for param in sig_params:
                if param.annotation is not _EMPTY:
                    htype: Any = param.annotation
                elif param.default is not None and param.default is not _EMPTY:
                    htype = type(param.default)
                else:
                    htype = Optional[Any]
                default = None if param.default is _EMPTY else param.default
                specs.append((param.name, htype, field(default=default)))
        else:
            for param in sig_params:
                default = None if param.default is _EMPTY else param.default
                specs.append((param.name, param.annotation, field(default=default)))

        return make_json_dataclass("Hyperparameters", specs, bases=(BaseHyperparameters,))

    def _resolve_hyperparameter_type(self, hyperparameters: Any) -> Type:
        """The type to wrap ``hyperparameters`` in for one call: the declared/synthesized
        type when a config or annotated init exists, else a type inferred from the ad-hoc
        dict — derived without mutating shared state (safe under concurrent train/serve).
        """
        if isinstance(hyperparameters, dict) and self._hyperparameter_config is None and hyperparameters:
            return self._synthesize_hyperparameter_type({k: type(v) for k, v in hyperparameters.items()})
        return self.hyperparameter_type

    @property
    def model_type(self) -> Optional[Type]:
        """The model-object type implied by the init slot (``model.py:1420-1423``)."""
        init = self._init_callable if self._init == self._default_init else (self._init or self._init_callable)
        if init is None:
            return None
        if inspect.isclass(init):
            return init
        annotation = signature(init).return_annotation
        return None if annotation is _EMPTY else annotation

    @property
    def prediction_type(self) -> Type:
        return signature(self._predictor).return_annotation

    @property
    def train_workflow_name(self) -> str:
        return f"{self.name}.train"

    @property
    def predict_workflow_name(self) -> str:
        return f"{self.name}.predict"

    @property
    def predict_from_features_workflow_name(self) -> str:
        return f"{self.name}.predict_from_features"

    @property
    def config_file(self) -> Optional[str]:
        return self._config_file

    @property
    def resources(self) -> Optional[Resources]:
        """TPU pod-slice resources requested for deployed jobs."""
        return self._resources

    @property
    def training_schedules(self) -> List[Schedule]:
        return self._training_schedules

    @property
    def training_schedule_names(self) -> List[str]:
        return [s.name for s in self._training_schedules]

    @property
    def prediction_schedules(self) -> List[Schedule]:
        return self._prediction_schedules

    @property
    def prediction_schedule_names(self) -> List[str]:
        return [s.name for s in self._prediction_schedules]

    # ------------------------------------------------------------------ decorators

    def init(self, fn: Callable) -> Callable:
        """Register a function that creates a model object from hyperparameters."""
        self._init = fn
        return fn

    def _expected_parser_types(self) -> Tuple[Any, ...]:
        """Expected positional data types for trainer/evaluator (``model.py:276-287``).

        TPU-native: with ``device_format="jax"`` parsed splits arrive as device arrays,
        so trainer/evaluator data arguments are ``jax.Array`` typed.
        """
        import pandas as pd

        default_parser = self._dataset._parser == self._dataset._default_parser
        if default_parser:
            data_type = self._dataset.dataset_datatype["data"]
            # the default parser splits DataFrames AND dict datasets into (features, targets)
            splits_two = data_type is pd.DataFrame or data_type is dict or get_origin(data_type) is dict
            expected = (data_type, data_type) if splits_two else (data_type,)
        else:
            expected = self._dataset.parser_return_types

        if self._dataset._device_format == "jax":
            import jax

            return (jax.Array,) * len(expected)
        return expected

    def trainer(
        self,
        fn: Optional[Callable] = None,
        *,
        jit: Union[bool, str] = False,
        static_argnames: Tuple[str, ...] = (),
        donate_argnums: Tuple[int, ...] = (),
        **train_stage_kwargs,
    ):
        """Register the training function.

        ``jit=True`` compiles the whole trainer with XLA (appropriate when the loop is
        expressed with ``lax`` control flow); the default runs the trainer eagerly, with
        the expectation that jax-native trainers jit their inner step (see
        :func:`unionml_tpu.parallel.data_parallel_step`).
        """
        if fn is None:
            return lambda f: self.trainer(
                f, jit=jit, static_argnames=static_argnames, donate_argnums=donate_argnums, **train_stage_kwargs
            )

        type_guards.guard_trainer(fn, self.model_type, self._expected_parser_types())
        self._trainer = TracedFunction(
            fn, jit=jit, static_argnames=static_argnames, donate_argnums=donate_argnums
        ) if jit else fn
        self._train_stage_kwargs = {"requests": DEFAULT_RESOURCES, "limits": DEFAULT_RESOURCES, **train_stage_kwargs}
        self._train_stage = None

        if not hasattr(fn, "__unionml_model__"):
            fn.__unionml_model__ = self  # type: ignore[attr-defined]
        for sched in getattr(fn, "__unionml_schedules__", []):
            self.add_trainer_schedule(sched)
        return fn

    def predictor(
        self,
        fn: Optional[Callable] = None,
        *,
        callbacks: Optional[List[Callable]] = None,
        jit: Union[bool, str] = "auto",
        static_argnames: Tuple[str, ...] = (),
        **predict_stage_kwargs,
    ):
        """Register the prediction function; jit-compiled by default when traceable."""
        if fn is None:
            return lambda f: self.predictor(
                f, callbacks=callbacks, jit=jit, static_argnames=static_argnames, **predict_stage_kwargs
            )

        type_guards.guard_predictor(fn, self.model_type, self._dataset.feature_type)
        self._predictor = TracedFunction(fn, jit=jit, static_argnames=static_argnames) if jit else fn
        self._predict_stage_kwargs = {
            "requests": DEFAULT_RESOURCES,
            "limits": DEFAULT_RESOURCES,
            **predict_stage_kwargs,
        }
        self._predict_stage = None
        self._predict_from_features_stage = None

        if callbacks is not None:
            for cb in callbacks:
                if not callable(cb):
                    raise ValueError("Callback must be a callable function.")
                type_guards.guard_prediction_callback(
                    callback=cb,
                    predictor=fn,
                    expected_model_type=self.model_type,
                    expected_data_type=self._dataset.feature_type,
                )
            self.predict_callbacks = tuple(callbacks)

        if not hasattr(fn, "__unionml_model__"):
            fn.__unionml_model__ = self  # type: ignore[attr-defined]
        for sched in getattr(fn, "__unionml_schedules__", []):
            self.add_predictor_schedule(sched)
        return fn

    def evaluator(
        self,
        fn: Optional[Callable] = None,
        *,
        jit: Union[bool, str] = "auto",
        static_argnames: Tuple[str, ...] = (),
    ):
        """Register the metric function; jit-compiled by default when traceable."""
        if fn is None:
            return lambda f: self.evaluator(f, jit=jit, static_argnames=static_argnames)
        type_guards.guard_evaluator(fn, self.model_type, self._expected_parser_types())
        self._evaluator = TracedFunction(fn, jit=jit, static_argnames=static_argnames) if jit else fn
        return fn

    def saver(self, fn: Callable) -> Callable:
        """Register a function serializing (model_object, hyperparameters) to a file."""
        self._saver = fn
        return fn

    def loader(self, fn: Callable) -> Callable:
        """Register a function deserializing a model object from a file."""
        self._loader = fn
        return fn

    # ------------------------------------------------------------------ schedules

    def add_trainer_schedule(self, schedule: Schedule) -> None:
        if schedule.type != ScheduleType.trainer:
            raise ValueError(f"Expected schedule type {ScheduleType.trainer}, found {schedule.type}")
        if schedule.name in self.training_schedule_names:
            raise ValueError(
                f"Scheduled job {schedule.name} must have a unique name. Existing: {self.training_schedule_names}"
            )
        self._training_schedules.append(schedule)

    def add_predictor_schedule(self, schedule: Schedule) -> None:
        if schedule.type != ScheduleType.predictor:
            raise ValueError(f"Expected schedule type {ScheduleType.predictor}, found {schedule.type}")
        if schedule.name in self.prediction_schedule_names:
            raise ValueError(
                f"Scheduled job {schedule.name} must have a unique name. Existing: {self.prediction_schedule_names}"
            )
        self._prediction_schedules.append(schedule)

    def schedule_training(
        self,
        name: str,
        *,
        expression: Optional[str] = None,
        offset: Optional[str] = None,
        fixed_rate: Optional[timedelta] = None,
        reader_time_arg: Optional[str] = None,
        activate_on_deploy: bool = True,
        launchplan_kwargs: Optional[dict] = None,
        hyperparameters: Optional[Dict[str, Any]] = None,
        loader_kwargs: Optional[Dict[str, Any]] = None,
        splitter_kwargs: Optional[Dict[str, Any]] = None,
        parser_kwargs: Optional[Dict[str, Any]] = None,
        trainer_kwargs: Optional[Dict[str, Any]] = None,
        **reader_kwargs,
    ) -> None:
        """Register a scheduled training job, activated at deploy time (``model.py:786-855``)."""
        if name in self.training_schedule_names:
            raise ValueError(
                f"Scheduled job {name} must have a unique name. Existing: {self.training_schedule_names}"
            )
        schedule = Schedule(
            type=ScheduleType.trainer,
            name=name,
            expression=expression,
            offset=offset,
            fixed_rate=fixed_rate,
            time_arg=reader_time_arg,
            inputs={
                "hyperparameters": self.hyperparameter_type(**(hyperparameters or {})),
                "loader_kwargs": self._dataset.loader_kwargs_type(**(loader_kwargs or {})),
                "splitter_kwargs": self._dataset.splitter_kwargs_type(**(splitter_kwargs or {})),
                "parser_kwargs": self._dataset.parser_kwargs_type(**(parser_kwargs or {})),
                **{**reader_kwargs, **(trainer_kwargs or {})},
            },
            activate_on_deploy=activate_on_deploy,
            launchplan_kwargs=launchplan_kwargs,
        )
        self._training_schedules.append(schedule)

    def schedule_prediction(
        self,
        name: str,
        *,
        expression: Optional[str] = None,
        offset: Optional[str] = None,
        fixed_rate: Optional[timedelta] = None,
        reader_time_arg: Optional[str] = None,
        activate_on_deploy: bool = True,
        launchplan_kwargs: Optional[dict] = None,
        model_object: Optional[Any] = None,
        model_version: Optional[str] = None,
        app_version: Optional[str] = None,
        model_file: Optional[Union[str, Path]] = None,
        loader_kwargs: Optional[dict] = None,
        **reader_kwargs,
    ) -> None:
        """Register a scheduled batch-prediction job (``model.py:857-934``)."""
        if name in self.prediction_schedule_names:
            raise ValueError(
                f"Scheduled job {name} must have a unique name. Existing: {self.prediction_schedule_names}"
            )
        from unionml_tpu.backend import wire_encode_value

        resolved = self.resolve_model_artifact(
            model_object=model_object,
            model_version=model_version,
            app_version=app_version,
            model_file=model_file,
            loader_kwargs=loader_kwargs,
        )
        # an explicit in-memory model_object carries no hyperparameters; fall back to
        # the current artifact's so non-picklable objects can be rebuilt when firing
        hp = resolved.hyperparameters
        if hp is None and self._artifact is not None and resolved.model_object is self._artifact.model_object:
            hp = self._artifact.hyperparameters
        model_object_input = wire_encode_value(resolved.model_object, hp)
        schedule = Schedule(
            type=ScheduleType.predictor,
            name=name,
            expression=expression,
            offset=offset,
            fixed_rate=fixed_rate,
            time_arg=reader_time_arg,
            inputs={"model_object": model_object_input, **reader_kwargs},
            activate_on_deploy=activate_on_deploy,
            launchplan_kwargs=launchplan_kwargs,
        )
        self._prediction_schedules.append(schedule)

    # ------------------------------------------------------------------ stage factories

    @property
    def trainer_params(self) -> Dict[str, Parameter]:
        """Keyword-only trainer parameters exposed as workflow inputs (``model.py:416-423``)."""
        trainer_fn = getattr(self._trainer, "fn", self._trainer)
        return {
            name: param
            for name, param in signature(trainer_fn).parameters.items()
            if param.kind == Parameter.KEYWORD_ONLY
        }

    def train_task(self) -> Stage:
        """Build (once) the training stage (``model.py:512-578``)."""
        if self._train_stage is not None:
            return self._train_stage

        *_, hp_param = signature(self._init).parameters.values()
        hp_param = hp_param.replace(name="hyperparameters", annotation=self.hyperparameter_type)
        [(data_arg_name, data_arg_type)] = self._dataset.dataset_datatype.items()

        trainer_fn = getattr(self._trainer, "fn", self._trainer)
        evaluator_fn = getattr(self._evaluator, "fn", self._evaluator)
        artifact_type = NamedTuple(  # type: ignore[misc]
            "ModelArtifact",
            model_object=signature(trainer_fn).return_annotation,
            hyperparameters=self.hyperparameter_type,
            metrics=Dict[str, signature(evaluator_fn).return_annotation],
        )

        input_parameters = OrderedDict(
            (p.name, p)
            for p in [
                hp_param,
                Parameter(data_arg_name, kind=Parameter.KEYWORD_ONLY, annotation=data_arg_type),
                *[
                    Parameter(arg, kind=Parameter.KEYWORD_ONLY, annotation=dict)
                    for arg in ("loader_kwargs", "splitter_kwargs", "parser_kwargs")
                ],
                *self.trainer_params.values(),
            ]
        )

        @stage(
            unionml_obj=self,
            input_parameters=input_parameters,
            return_annotation=artifact_type,
            **(self._train_stage_kwargs or {}),
        )
        def train_task(**kwargs):
            hyperparameters = kwargs["hyperparameters"]
            raw_data = kwargs[data_arg_name]
            trainer_kwargs = {p: kwargs[p] for p in self.trainer_params}
            hp_dict = asdict(hyperparameters) if is_dataclass(hyperparameters) else dict(hyperparameters or {})

            training_data = self._dataset.get_data(
                raw_data,
                loader_kwargs=_as_dict(kwargs.get("loader_kwargs")),
                splitter_kwargs=_as_dict(kwargs.get("splitter_kwargs")),
                parser_kwargs=_as_dict(kwargs.get("parser_kwargs")),
            )
            model_object = self._trainer(
                self._init_model_object(hp_dict),
                *training_data["train"],
                **trainer_kwargs,
            )
            metrics = {
                split: _scalarize(self._evaluator(model_object, *training_data[split])) for split in training_data
            }
            return model_object, hyperparameters, metrics

        self._train_stage = train_task
        return train_task

    def predict_task(self) -> Stage:
        """Build (once) the predict-from-raw-data stage (``model.py:580-617``)."""
        if self._predict_stage is not None:
            return self._predict_stage

        predictor_fn = getattr(self._predictor, "fn", self._predictor)
        predictor_sig = signature(predictor_fn)
        model_param, *_ = predictor_sig.parameters.values()
        model_param = model_param.replace(name="model_object", kind=Parameter.KEYWORD_ONLY)
        [(data_arg_name, data_arg_type)] = self._dataset.dataset_datatype.items()
        data_param = Parameter(data_arg_name, kind=Parameter.KEYWORD_ONLY, annotation=data_arg_type)

        @stage(
            unionml_obj=self,
            input_parameters=OrderedDict([(p.name, p) for p in (model_param, data_param)]),
            return_annotation=predictor_sig.return_annotation,
            **(self._predict_stage_kwargs or {}),
        )
        def predict_task(**kwargs):
            model_object = kwargs["model_object"]
            parsed = self._dataset._parser(kwargs[data_arg_name], **self._dataset.parser_kwargs)
            features = self._dataset._feature_transformer(parsed[self._dataset._parser_feature_key])
            features = self._dataset.finalize_features(features)
            predictions = self._predictor(model_object, features)
            self._run_predict_callbacks(model_object, features, predictions)
            return predictions

        self._predict_stage = predict_task
        return predict_task

    def predict_from_features_task(self) -> Stage:
        """Build (once) the predict-from-features stage (``model.py:619-653``)."""
        if self._predict_from_features_stage is not None:
            return self._predict_from_features_stage

        predictor_fn = getattr(self._predictor, "fn", self._predictor)
        predictor_sig = signature(predictor_fn)
        model_param, *_ = predictor_sig.parameters.values()
        model_param = model_param.replace(name="model_object", kind=Parameter.KEYWORD_ONLY)
        [(_, data_arg_type)] = self._dataset.dataset_datatype.items()
        features_param = Parameter("features", kind=Parameter.KEYWORD_ONLY, annotation=data_arg_type)

        @stage(
            unionml_obj=self,
            input_parameters=OrderedDict([("model_object", model_param), ("features", features_param)]),
            return_annotation=predictor_sig.return_annotation,
            **(self._predict_stage_kwargs or {}),
        )
        def predict_from_features_task(**kwargs):
            model_object, features = kwargs["model_object"], kwargs["features"]
            predictions = self._predictor(model_object, features)
            self._run_predict_callbacks(model_object, features, predictions)
            return predictions

        self._predict_from_features_stage = predict_from_features_task
        return predict_from_features_task

    def _run_predict_callbacks(self, model_object, features, predictions) -> None:
        """Run post-prediction callbacks, swallowing exceptions (``model.py:608-612``)."""
        for callback in self._predict_callbacks:
            try:
                callback(model_object, features, predictions)
            except Exception as exc:
                logger.exception("Error in post-prediction callback[%s]: %s", callback.__name__, exc)

    # ------------------------------------------------------------------ workflow factories

    def train_workflow(self) -> Workflow:
        """Wire dataset_task -> train_task into a workflow (``model.py:425-471``)."""
        dataset_task = self._dataset.dataset_task()
        train_task = self.train_task()

        wf = Workflow(self.train_workflow_name)
        wf.add_workflow_input("hyperparameters", self.hyperparameter_type)
        wf.add_workflow_input("loader_kwargs", self._dataset.loader_kwargs_type)
        wf.add_workflow_input("splitter_kwargs", self._dataset.splitter_kwargs_type)
        wf.add_workflow_input("parser_kwargs", self._dataset.parser_kwargs_type)
        _add_stage_inputs(wf, dataset_task)
        trainer_param_types = {k: v.annotation for k, v in self.trainer_params.items()}
        for arg, param in self.trainer_params.items():
            if param.default is _EMPTY:
                wf.add_workflow_input(arg, param.annotation)
            else:
                wf.add_workflow_input(arg, param.annotation, default=param.default)

        dataset_node = wf.add_entity(
            dataset_task, **{k: wf.inputs[k] for k in dataset_task.python_interface.inputs}
        )
        (_, data_promise), *_ = dataset_node.outputs.items()
        [(data_arg_name, _)] = self._dataset.dataset_datatype.items()
        train_node = wf.add_entity(
            train_task,
            hyperparameters=wf.inputs["hyperparameters"],
            **{data_arg_name: data_promise},
            **{arg: wf.inputs[arg] for arg in trainer_param_types},
            **{arg: wf.inputs[arg] for arg in ("loader_kwargs", "splitter_kwargs", "parser_kwargs")},
        )
        wf.add_workflow_output("model_object", train_node.outputs["model_object"])
        wf.add_workflow_output("hyperparameters", train_node.outputs["hyperparameters"])
        wf.add_workflow_output("metrics", train_node.outputs["metrics"])
        return wf

    def predict_workflow(self) -> Workflow:
        """Wire dataset_task -> predict_task (``model.py:473-495``)."""
        dataset_task = self._dataset.dataset_task()
        predict_task = self.predict_task()

        wf = Workflow(self.predict_workflow_name)
        wf.add_workflow_input("model_object", predict_task.python_interface.inputs["model_object"])
        _add_stage_inputs(wf, dataset_task)

        dataset_node = wf.add_entity(
            dataset_task, **{k: wf.inputs[k] for k in dataset_task.python_interface.inputs}
        )
        (_, data_promise), *_ = dataset_node.outputs.items()
        [(data_arg_name, _)] = self._dataset.dataset_datatype.items()
        predict_node = wf.add_entity(
            predict_task, model_object=wf.inputs["model_object"], **{data_arg_name: data_promise}
        )
        for output_name, promise in predict_node.outputs.items():
            wf.add_workflow_output(output_name, promise)
        return wf

    def predict_from_features_workflow(self) -> Workflow:
        """Single-node workflow around predict_from_features_task (``model.py:497-510``)."""
        predict_task = self.predict_from_features_task()
        wf = Workflow(self.predict_from_features_workflow_name)
        for arg, annotation in predict_task.python_interface.inputs.items():
            wf.add_workflow_input(arg, annotation)
        node = wf.add_entity(predict_task, **{k: wf.inputs[k] for k in wf.inputs})
        for output_name, promise in node.outputs.items():
            wf.add_workflow_output(output_name, promise)
        return wf

    # ------------------------------------------------------------------ local execution

    def train(
        self,
        hyperparameters: Optional[Dict[str, Any]] = None,
        loader_kwargs: Optional[Dict[str, Any]] = None,
        splitter_kwargs: Optional[Dict[str, Any]] = None,
        parser_kwargs: Optional[Dict[str, Any]] = None,
        trainer_kwargs: Optional[Dict[str, Any]] = None,
        **reader_kwargs,
    ) -> Tuple[Any, Any]:
        """Train locally through the full reader->...->evaluator graph (``model.py:655-709``)."""
        trainer_kwargs = trainer_kwargs or {}

        # infer hyperparameter types from the provided dict when no config exists
        # (pure derivation — no shared-state mutation, safe under concurrent calls)
        hp_type = self._resolve_hyperparameter_type(hyperparameters)
        hp_value = hyperparameters if hp_type is dict else hp_type(**(hyperparameters or {}))
        model_obj, hyperparameters_out, metrics = self.train_workflow()(
            hyperparameters=hp_value if hp_value is not None else {},
            loader_kwargs=self._dataset.loader_kwargs_type(**(loader_kwargs or {})),
            splitter_kwargs=self._dataset.splitter_kwargs_type(**(splitter_kwargs or {})),
            parser_kwargs=self._dataset.parser_kwargs_type(**(parser_kwargs or {})),
            **{**reader_kwargs, **trainer_kwargs},
        )

        self.artifact = ModelArtifact(model_obj, hyperparameters_out, metrics)
        return model_obj, metrics

    def predict(self, features: Any = None, **reader_kwargs):
        """Generate predictions locally (``model.py:711-741``)."""
        if features is None and not reader_kwargs:
            # a zero-arg call is valid when the reader itself needs no arguments
            # (serving's {"inputs": {}} payload means "run the reader with defaults")
            reader = getattr(self._dataset, "_reader", None)
            reader_ok = reader is not None and all(
                p.default is not _EMPTY or p.kind in (Parameter.VAR_KEYWORD, Parameter.VAR_POSITIONAL)
                for p in signature(reader).parameters.values()
            )
            if not reader_ok:
                raise ValueError("At least one of features or **reader_kwargs must be provided")
        if self.artifact is None:
            raise RuntimeError(
                "ModelArtifact not found: train a model with .train() or load one before predicting."
            )
        if features is None:
            return self.predict_workflow()(model_object=self.artifact.model_object, **reader_kwargs)
        return self.predict_from_features_workflow()(
            model_object=self.artifact.model_object,
            features=self._dataset.get_features(features),
        )

    # ------------------------------------------------------------------ persistence

    def save(self, file: Union[str, os.PathLike, IO], *args, **kwargs):
        """Serialize the current model artifact to disk (``model.py:743-747``)."""
        if self.artifact is None:
            raise AttributeError("`artifact` property is None. Call the `train` method to train a model first")
        return self._saver(self.artifact.model_object, self.artifact.hyperparameters, file, *args, **kwargs)

    def load(self, file: Union[str, os.PathLike, IO], *args, **kwargs):
        """Deserialize a model object and set the artifact (``model.py:749-757``)."""
        self.artifact = ModelArtifact(self._loader(file, *args, **kwargs))
        return self.artifact.model_object

    def load_from_env(self, env_var: str = "UNIONML_MODEL_PATH", *args, **kwargs):
        """Load from a path stored in an environment variable (``model.py:759-769``)."""
        model_path = os.getenv(env_var)
        if model_path is None:
            raise ValueError(f"env var for model path {env_var} doesn't exist.")
        return self.load(model_path, *args, **kwargs)

    def _default_init(self, hyperparameters: dict) -> Any:
        if self._init_callable is None:
            raise ValueError(
                "When using the default init, you must pass the `init` argument to the Model constructor."
            )
        return self._init_callable(**hyperparameters)

    def _init_model_object(self, hyperparameters: dict) -> Any:
        if self._init == self._default_init:
            return self._default_init(hyperparameters)
        return self._init(hyperparameters=hyperparameters)

    def _default_saver(
        self,
        model_obj: Any,
        hyperparameters: Union[dict, BaseHyperparameters, None],
        file: Union[str, os.PathLike, IO],
        *args,
        **kwargs,
    ) -> Any:
        """Framework-aware default serialization; see :mod:`unionml_tpu.checkpoint`."""
        from unionml_tpu.checkpoint import default_save

        hp = asdict(hyperparameters) if hyperparameters is not None and is_dataclass(hyperparameters) else hyperparameters
        return default_save(model_obj, hp, file, model_type=self.model_type, *args, **kwargs)

    def _default_loader(self, file: Union[str, os.PathLike, IO], *args, **kwargs) -> Any:
        """Framework-aware default deserialization; see :mod:`unionml_tpu.checkpoint`."""
        from unionml_tpu.checkpoint import default_load

        return default_load(
            file,
            model_type=self.model_type,
            init_fn=(self._init_model_object if (self._init_callable or self._init != self._default_init) else None),
            *args,
            **kwargs,
        )

    def resolve_model_artifact(
        self,
        model_object: Optional[Any] = None,
        model_version: Optional[str] = None,
        app_version: Optional[str] = None,
        model_file: Optional[Union[str, Path]] = None,
        loader_kwargs: Optional[dict] = None,
    ) -> ModelArtifact:
        """Resolve an artifact from object / backend version / file / self (``model.py:1521-1566``)."""
        if sum(x is not None for x in (model_object, model_version, model_file)) > 1:
            raise ValueError("You can specify only one of 'model_object', 'model_version', or 'model_file'.")
        if model_object is not None:
            return ModelArtifact(model_object)
        if model_version is not None:
            from unionml_tpu import remote

            return remote.get_model_artifact(self, app_version=app_version, model_version=model_version)
        if model_file is not None:
            return ModelArtifact(self.load(model_file, **(loader_kwargs or {})))
        if self.artifact is not None:
            return self.artifact
        raise ModelArtifactNotFound(
            "Model object not found: specify one of model_version, model_file, or model_object, or train a "
            "model locally with .train(...) first."
        )

    # ------------------------------------------------------------------ serving

    def serve(
        self,
        app: Any = None,
        remote: bool = False,
        app_version: Optional[str] = None,
        model_version: str = "latest",
        **serving_kwargs,
    ):
        """Attach this model's endpoints to a serving app (``model.py:771-784``).

        ``app=None`` builds the framework's native aiohttp app with a resident compiled
        predictor; a FastAPI instance is also accepted when fastapi is installed.
        """
        from unionml_tpu.serving import serving_app

        return serving_app(
            self, app, remote=remote, app_version=app_version, model_version=model_version, **serving_kwargs
        )

    # ------------------------------------------------------------------ remote backend surface

    def remote(
        self,
        backend: Any = None,
        *,
        config_file: Optional[str] = None,
        project: Optional[str] = None,
        domain: Optional[str] = None,
        resources: Optional[Resources] = None,
        accelerator: Optional[str] = None,
        topology: Optional[str] = None,
        host_count: int = 1,
        patch_destination_dir: Optional[str] = None,
    ) -> None:
        """Configure the execution backend for deployment (``model.py:936-965``).

        Instead of docker registry / dockerfile configuration, the TPU-native deployment
        config carries the pod-slice shape: ``accelerator`` (e.g. ``"v5litepod-8"``),
        ``topology`` (e.g. ``"2x4"``) and ``host_count`` — these become the job spec's
        TPU resource request (never a GPU request).
        """
        self._backend = backend
        self._config_file = config_file
        self._project = project
        self._domain = domain
        self._patch_destination_dir = patch_destination_dir
        if resources is not None:
            self._resources = resources
        elif accelerator is not None:
            self._resources = Resources(accelerator=accelerator, topology=topology, host_count=host_count)

    @property
    def _remote(self):
        """Lazily build the backend client from config (``model.py:967-981``)."""
        if self._backend is not None and not isinstance(self._backend, str):
            return self._backend
        from unionml_tpu.backend import backend_from_config

        self._backend = backend_from_config(
            self._backend if isinstance(self._backend, str) else None,
            config_file=self._config_file,
            project=self._project,
            domain=self._domain,
        )
        return self._backend

    def _require_backend(self):
        backend = self._remote
        if backend is None:
            raise RuntimeError("First configure the remote backend with the `Model.remote` method")
        return backend

    def remote_deploy(
        self,
        app_version: Optional[str] = None,
        allow_uncommitted: bool = False,
        patch: bool = False,
        schedule: bool = True,
    ) -> str:
        """Deploy app workflows (and schedules) to the backend (``model.py:983-1083``)."""
        from unionml_tpu import remote

        return remote.deploy_app(
            self,
            backend=self._require_backend(),
            app_version=app_version,
            allow_uncommitted=allow_uncommitted,
            patch=patch,
            schedule=schedule,
        )

    def remote_train(
        self,
        app_version: Optional[str] = None,
        wait: bool = True,
        *,
        hyperparameters: Optional[Dict[str, Any]] = None,
        loader_kwargs: Optional[Dict[str, Any]] = None,
        splitter_kwargs: Optional[Dict[str, Any]] = None,
        parser_kwargs: Optional[Dict[str, Any]] = None,
        trainer_kwargs: Optional[Dict[str, Any]] = None,
        **reader_kwargs,
    ):
        """Run a training job on the backend (``model.py:1085-1158``)."""
        backend = self._require_backend()

        hp_type = self._resolve_hyperparameter_type(hyperparameters)
        hp_value = hyperparameters if hp_type is dict else hp_type(**(hyperparameters or {}))
        inputs = {
            "hyperparameters": hp_value if hp_value is not None else {},
            "loader_kwargs": self._dataset.loader_kwargs_type(**(loader_kwargs or {})),
            "splitter_kwargs": self._dataset.splitter_kwargs_type(**(splitter_kwargs or {})),
            "parser_kwargs": self._dataset.parser_kwargs_type(**(parser_kwargs or {})),
            **{**reader_kwargs, **(trainer_kwargs or {})},
        }
        execution = backend.execute(self, self.train_workflow_name, inputs=inputs, app_version=app_version)

        logger.info("Executing %s, execution name: %s", self.train_workflow_name, execution.id)
        if not wait:
            return execution
        self.remote_wait(execution)
        self.remote_load(execution)
        return self.artifact

    def remote_predict(
        self,
        app_version: Optional[str] = None,
        model_version: Optional[str] = None,
        wait: bool = True,
        *,
        features: Any = None,
        **reader_kwargs,
    ):
        """Run a batch-prediction job on the backend (``model.py:1160-1226``)."""
        backend = self._require_backend()
        from unionml_tpu import remote

        from unionml_tpu.backend import wire_encode_value

        model_artifact = remote.get_model_artifact(self, app_version=app_version, model_version=model_version)
        inputs: Dict[str, Any] = {
            "model_object": wire_encode_value(model_artifact.model_object, model_artifact.hyperparameters)
        }
        if features is None:
            workflow_name = self.predict_workflow_name
            inputs.update(reader_kwargs)
        else:
            workflow_name = self.predict_from_features_workflow_name
            inputs["features"] = self._dataset.get_features(features)

        execution = backend.execute(self, workflow_name, inputs=inputs, app_version=app_version)
        logger.info("Executing %s, execution name: %s", workflow_name, execution.id)
        if not wait:
            return execution
        execution = self.remote_wait(execution)
        predictions, *_ = execution.outputs.values()
        return predictions

    def remote_wait(self, execution, **kwargs):
        """Block until an execution completes (``model.py:1228-1232``)."""
        return self._require_backend().wait(execution, **kwargs)

    def _remote_load_model_artifact(self, execution) -> ModelArtifact:
        backend = self._require_backend()
        if not execution.is_done:
            logger.info("Waiting for execution %s to complete...", execution.id)
            execution = backend.wait(execution)
        from unionml_tpu.backend import wire_decode_value

        outputs = execution.outputs
        model_object = wire_decode_value(outputs["model_object"], self)
        return ModelArtifact(model_object, outputs.get("hyperparameters"), outputs.get("metrics"))

    def remote_load(self, execution) -> None:
        """Set ``self.artifact`` from a completed training execution (``model.py:1263-1270``)."""
        self.artifact = self._remote_load_model_artifact(execution)

    def remote_fetch_model(self, execution) -> ModelArtifact:
        return self._remote_load_model_artifact(execution)

    def remote_fetch_predictions(self, execution) -> Any:
        backend = self._require_backend()
        if not execution.is_done:
            execution = backend.wait(execution)
        predictions, *_ = execution.outputs.values()
        return predictions

    def remote_list_model_versions(self, app_version: Optional[str] = None, limit: int = 10) -> List[str]:
        """Model versions (training execution ids), newest first (``model.py:1272-1282``)."""
        from unionml_tpu import remote

        return remote.list_model_versions(self, app_version=app_version, limit=limit)

    def remote_list_prediction_ids(self, app_version: Optional[str] = None, limit: int = 10) -> List[str]:
        from unionml_tpu import remote

        return remote.list_prediction_ids(self, app_version=app_version, limit=limit)

    def remote_activate_schedules(
        self, app_version: Optional[str] = None, schedule_names: Optional[List[str]] = None
    ) -> None:
        """Activate deployed schedules (``model.py:1317-1346``)."""
        backend = self._require_backend()
        for sched in [*self.training_schedules, *self.prediction_schedules]:
            if schedule_names and sched.name not in schedule_names:
                continue
            logger.info("Activating schedule %s", sched.name)
            backend.activate_schedule(self, sched, app_version=app_version)

    def remote_deactivate_schedules(
        self, app_version: Optional[str] = None, schedule_names: Optional[List[str]] = None
    ) -> None:
        """Deactivate deployed schedules (``model.py:1348-1377``)."""
        backend = self._require_backend()
        for sched in [*self.training_schedules, *self.prediction_schedules]:
            if schedule_names and sched.name not in schedule_names:
                continue
            logger.info("Deactivating schedule %s", sched.name)
            backend.deactivate_schedule(self, sched, app_version=app_version)

    def remote_list_scheduled_training_runs(
        self, schedule_name: str, app_version: Optional[str] = None, limit: int = 5
    ) -> List[Any]:
        """Executions kicked off by a training schedule (``model.py:1379-1399``)."""
        if schedule_name not in self.training_schedule_names:
            raise ValueError(
                f"Schedule '{schedule_name}' does not exist. Must be one of {self.training_schedule_names}"
            )
        return self._require_backend().list_scheduled_runs(schedule_name, app_version=app_version, limit=limit)

    def remote_list_scheduled_prediction_runs(
        self, schedule_name: str, app_version: Optional[str] = None, limit: int = 5
    ) -> List[Any]:
        if schedule_name not in self.prediction_schedule_names:
            raise ValueError(
                f"Schedule '{schedule_name}' does not exist. Must be one of {self.prediction_schedule_names}"
            )
        return self._require_backend().list_scheduled_runs(schedule_name, app_version=app_version, limit=limit)


def _add_stage_inputs(wf: Workflow, task: Stage) -> None:
    """Expose a stage's parameters (with their defaults) as workflow inputs."""
    for arg, param in task.inputs.items():
        if param.default is _EMPTY:
            wf.add_workflow_input(arg, param.annotation)
        else:
            wf.add_workflow_input(arg, param.annotation, default=param.default)


def _as_dict(value: Any) -> Optional[Dict[str, Any]]:
    """Normalize kwargs payloads that may be dataclasses, dicts, or None."""
    if value is None:
        return None
    if is_dataclass(value):
        return asdict(value)
    return dict(value)
