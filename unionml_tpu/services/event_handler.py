"""Serverless event handler: drive predictions from API-Gateway / storage events.

Reference parity: the AWS-Lambda pattern the reference ships via templates and tests
(``tests/unit/test_aws_lambda_handler.py`` drives Mangum with synthetic API-Gateway and
S3 event payloads). Here the handler is framework-owned and dependency-free: it
understands HTTP-style events (API Gateway v1/v2 shapes) carrying the same
``{"features": ...}`` / ``{"inputs": ...}`` body as the HTTP server, and storage-style
events whose records reference feature files (routed through the dataset's
``feature_loader`` via ``pathlib.Path``).
"""

import json
from pathlib import Path
from typing import Any, Callable, Dict, List, Optional

from unionml_tpu._logging import logger
from unionml_tpu.serving.app import jsonable, load_model_artifact


def _http_body(event: Dict[str, Any]) -> Optional[Dict[str, Any]]:
    """Extract a JSON body from API-Gateway v1/v2-shaped events."""
    if "body" not in event:
        return None
    body = event["body"]
    if body is None:
        return {}
    if isinstance(body, str):
        try:
            return json.loads(body)
        except json.JSONDecodeError:
            return None
    return body


def _storage_paths(event: Dict[str, Any]) -> List[str]:
    """Extract object paths from storage-notification-shaped events (s3/gcs records)."""
    paths = []
    for record in event.get("Records", []):
        s3 = record.get("s3")
        if s3:
            paths.append(f"{s3['bucket']['name']}/{s3['object']['key']}")
            continue
        if "bucket" in record and "name" in record:
            paths.append(f"{record['bucket']}/{record['name']}")
    return paths


def make_event_handler(
    model: Any,
    model_path: Optional[str] = None,
    path_resolver: Optional[Callable[[str], Path]] = None,
) -> Callable[[Dict[str, Any], Any], Dict[str, Any]]:
    """Build a ``handler(event, context)`` callable for serverless runtimes.

    :param model_path: optional explicit model file; defaults to ``UNIONML_MODEL_PATH``.
    :param path_resolver: maps a storage object path (``bucket/key``) to a local
        ``Path`` holding the downloaded features (inject your blob client here).
    """

    def handler(event: Dict[str, Any], context: Any = None) -> Dict[str, Any]:
        try:
            load_model_artifact(model, model_path=model_path)
        except Exception as exc:
            logger.exception("Model load failed")
            return {"statusCode": 500, "body": json.dumps({"detail": f"Model load failed: {exc}"})}

        try:
            body = _http_body(event)
            if body is None and isinstance(event.get("body"), str):
                return {"statusCode": 400, "body": json.dumps({"detail": "Request body must be valid JSON."})}
            if body is not None:
                inputs = body.get("inputs")
                features = body.get("features")
                if inputs is None and features is None:
                    return {
                        "statusCode": 500,
                        "body": json.dumps({"detail": "inputs or features must be supplied."}),
                    }
                predictions = model.predict(**inputs) if inputs else model.predict(features=features)
                return {"statusCode": 200, "body": json.dumps(jsonable(predictions))}

            paths = _storage_paths(event)
            if paths:
                results = {}
                for object_path in paths:
                    local = path_resolver(object_path) if path_resolver else Path(object_path)
                    results[object_path] = jsonable(model.predict(features=local))
                return {"statusCode": 200, "body": json.dumps(results)}

            return {"statusCode": 400, "body": json.dumps({"detail": "Unrecognized event shape."})}
        except Exception as exc:
            logger.exception("Prediction failed")
            return {"statusCode": 500, "body": json.dumps({"detail": f"Prediction failed: {exc}"})}

    return handler
