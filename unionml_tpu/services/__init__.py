"""Optional serving adapters: BentoML-style packaging and serverless event handlers.

Reference parity: ``unionml/services/__init__.py:4-6`` conditionally exposes the
bentoml integration; the serverless handler replaces the reference's Mangum/AWS-Lambda
*pattern* (shipped only via templates/tests there) with a first-class adapter.
"""

from unionml_tpu.services.bentoml_service import (  # noqa: F401
    BentoMLService,
    create_runnable,
    create_service,
    infer_io_descriptors,
)
from unionml_tpu.services.event_handler import make_event_handler

__all__ = [
    "BentoMLService",
    "create_runnable",
    "create_service",
    "infer_io_descriptors",
    "make_event_handler",
]
