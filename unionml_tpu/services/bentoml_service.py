"""BentoML adapter: package a unionml-tpu model as a bentoml service.

Reference parity: ``unionml/services/bentoml.py:31-247`` — a wrapper binding a Model to
bentoml's model store, runner, and service machinery, with IO descriptors inferred from
the dataset's feature type and the predictor's return type.

TPU-native delta: the runnable advertises TPU support (``SUPPORTED_RESOURCES`` includes
``"google.com/tpu"``; the reference's runnable lists ``"nvidia.com/gpu"`` at
``services/bentoml.py:202``), and the runnable holds a
:class:`~unionml_tpu.serving.resident.ResidentPredictor` so batch inference runs the
compiled executable.

The module imports WITHOUT bentoml installed: every entry point resolves the
``bentoml`` module attribute at call time (and raises a clear ImportError when
absent), so the adapter logic is executable — and contract-testable — against a
duck-typed stand-in injected over the module attribute.
"""

from typing import Any, Callable, List, Optional

try:
    import bentoml
except ImportError:  # adapter stays importable; entry points raise on use
    bentoml = None  # type: ignore[assignment]

from unionml_tpu._logging import logger
from unionml_tpu.serving.resident import ResidentPredictor


def _bentoml():
    if bentoml is None:
        raise ImportError(
            "bentoml is not installed; install it (pip install bentoml) to use the "
            "BentoML serving adapter."
        )
    return bentoml


def infer_io_descriptors(model: Any):
    """Infer bentoml input/output IO descriptors from the app's types.

    Reference parity: ``services/bentoml.py:216-247``. The INPUT is always JSON: the
    API handler receives the raw wire payload and routes it through the dataset's
    feature pipeline (which owns deserialization), so the input descriptor must not
    pre-coerce it — only the OUTPUT descriptor is inferred from the predictor's
    return annotation (DataFrames -> PandasDataFrame, arrays -> NumpyNdarray).
    """
    import numpy as np
    import pandas as pd

    bentoml = _bentoml()

    def descriptor(tp):
        try:
            if isinstance(tp, type) and issubclass(tp, pd.DataFrame):
                return bentoml.io.PandasDataFrame()
            if isinstance(tp, type) and issubclass(tp, np.ndarray):
                return bentoml.io.NumpyNdarray()
        except TypeError:
            pass
        module = getattr(tp, "__module__", "")
        if module.startswith(("jax", "jaxlib")):
            return bentoml.io.NumpyNdarray()
        return bentoml.io.JSON()

    try:
        prediction_type = model.prediction_type  # raises when no predictor registered yet
    except TypeError:
        prediction_type = None
    return bentoml.io.JSON(), descriptor(prediction_type)


def create_runnable(model: Any, tag: str) -> type:
    """Function-form runnable factory (``services/bentoml.py:create_runnable`` parity)."""
    return BentoMLService(model).create_runnable(tag)


def create_service(model: Any, tag: str, name: str = None, enable_async: bool = False):
    """Function-form service factory (``services/bentoml.py:create_service`` parity)."""
    return BentoMLService(model).configure(tag, name=name, enable_async=enable_async)


class BentoMLService:
    """Binds a unionml-tpu Model to bentoml save/load/serve."""

    def __init__(self, model: Any, framework: str = "picklable_model"):
        self._model = model
        self._framework = framework
        self._svc: Optional["bentoml.Service"] = None
        self._runner = None

    @property
    def model(self) -> Any:
        return self._model

    @property
    def svc(self) -> "bentoml.Service":
        if self._svc is None:
            raise RuntimeError("Call BentoMLService.configure(...) first.")
        return self._svc

    def save_model(self, name: Optional[str] = None, **save_kwargs) -> Any:
        """Store the trained model object in the bentoml model store."""
        if self._model.artifact is None:
            raise RuntimeError("Train or load a model before saving it to the bento store.")
        name = name or self._model.name
        module = getattr(_bentoml(), self._framework)
        return module.save_model(name, self._model.artifact.model_object, **save_kwargs)

    def load_model(self, tag: str) -> Any:
        module = getattr(_bentoml(), self._framework)
        return module.load_model(tag)

    def create_runnable(self, tag: str) -> type:
        """A bentoml Runnable whose resources include TPU (never only-GPU)."""
        service = self
        bentoml = _bentoml()

        class UnionMLTPURunnable(bentoml.Runnable):
            SUPPORTED_RESOURCES = ("cpu", "google.com/tpu")
            SUPPORTS_CPU_MULTI_THREADING = True

            def __init__(self):
                from unionml_tpu.model import ModelArtifact

                model_object = service.load_model(tag)
                service._model.artifact = ModelArtifact(model_object)
                self._resident = ResidentPredictor(service._model)
                self._resident.setup()

            @bentoml.Runnable.method(batchable=False)
            def predict(self, features: Any) -> Any:
                return self._resident.predict(features=features)

        return UnionMLTPURunnable

    def configure(
        self,
        tag: str,
        name: Optional[str] = None,
        enable_async: bool = False,
        supported_resources: Optional[List[str]] = None,
    ) -> "bentoml.Service":
        """Build the runner + service (``services/bentoml.py:72-131`` analogue)."""
        bentoml = _bentoml()
        runnable = self.create_runnable(tag)
        if supported_resources:
            runnable.SUPPORTED_RESOURCES = tuple(supported_resources)
        self._runner = bentoml.Runner(runnable, name=f"{self._model.name}-runner")
        svc = bentoml.Service(name or self._model.name, runners=[self._runner])
        handler = self._make_api(enable_async)
        input_io, output_io = infer_io_descriptors(self._model)
        svc.api(input=input_io, output=output_io)(handler)
        self._svc = svc
        return svc

    def _make_api(self, enable_async: bool) -> Callable:
        runner = self._runner

        # ResidentPredictor.predict runs the dataset's feature pipeline itself —
        # the raw payload goes straight through to avoid double transformation
        if enable_async:

            async def predict(payload: Any) -> Any:
                return await runner.predict.async_run(payload)

            return predict

        def predict(payload: Any) -> Any:
            return runner.predict.run(payload)

        return predict
