"""Decoration-time signature validation for Dataset/Model component functions.

Reference parity: ``unionml/type_guards.py:79-254`` — every ``guard_*`` below enforces the
same contract as its reference namesake (same error conditions, validated by the
table-driven matrices in ``tests/unit/test_type_guards.py``). TPU-native extension: array
types are cross-compatible — ``jax.Array``, ``jnp.ndarray``, ``np.ndarray`` and
``jax.ShapeDtypeStruct`` annotations are treated as one family so a reader annotated with
numpy arrays can feed a jit-traced trainer annotated with jax arrays.
"""

import inspect
from typing import Any, Callable, Dict, Iterable, List, Mapping, Optional, Tuple, Type, get_args, get_origin

import jax
import numpy as np

_EMPTY = inspect.Parameter.empty

#: required keyword parameters (name -> type) for the splitter slot
SPLITTER_REQUIRED_KWARGS: Dict[str, object] = {"test_size": float, "shuffle": bool, "random_state": int}

#: required keyword parameters (name -> type) for the parser slot
PARSER_REQUIRED_KWARGS: Dict[str, object] = {"features": Optional[List[str]], "targets": List[str]}

#: annotations considered interchangeable for array data moving between stages
_ARRAY_FAMILY: Tuple[object, ...] = (jax.Array, np.ndarray, jax.ShapeDtypeStruct)


def _is_array_type(tp: object) -> bool:
    if tp in _ARRAY_FAMILY:
        return True
    return getattr(tp, "__module__", "").startswith(("jax", "jaxlib")) and "Array" in getattr(tp, "__name__", "")


def types_compatible(actual: object, expected: object) -> bool:
    """True when ``actual`` may flow into a slot expecting ``expected``.

    Compatibility rules (same shape as the reference's ``_check_input_data_type``,
    ``type_guards.py:28-40``): ``Any`` on either side passes; exact equality passes;
    membership of one side in the other's Union/generic args passes. Added rule: both
    being array types passes.
    """
    if actual is Any or expected is Any or actual is _EMPTY:
        return True
    if expected is None or expected is _EMPTY:
        # unknown expected type (e.g. un-annotated init callable): nothing to enforce
        return True
    if actual == expected:
        return True
    if expected in get_args(actual) or actual in get_args(expected):
        return True
    if _is_array_type(actual) and _is_array_type(expected):
        return True
    # parameterized containers whose args differ only by array family are compatible:
    # Dict[str, np.ndarray] features arrive as Dict[str, jax.Array] after the
    # device-format conversion (tokenized multi-input models)
    actual_origin, expected_origin = get_origin(actual), get_origin(expected)
    if actual_origin is not None and actual_origin == expected_origin:
        actual_args, expected_args = get_args(actual), get_args(expected)
        if len(actual_args) == len(expected_args) and all(
            types_compatible(a, e) for a, e in zip(actual_args, expected_args)
        ):
            return True
    return False


def _require_compatible(fn_name: str, position: str, actual: object, expected: object) -> None:
    if not types_compatible(actual, expected):
        raise TypeError(
            f"'{fn_name}': the {position} must be compatible with the expected type {expected}; found {actual}"
        )


def _positional_annotations(params: List[inspect.Parameter]) -> List[object]:
    positional_kinds = {inspect.Parameter.POSITIONAL_OR_KEYWORD, inspect.Parameter.POSITIONAL_ONLY}
    return [p.annotation for p in params if p.kind in positional_kinds]


def _splits_container(tp: object) -> bool:
    """True when ``tp`` is a tuple/list/NamedTuple generic holding data splits."""
    if get_origin(tp) in {tuple, list}:
        return True
    return getattr(tp, "__bases__", None) == (tuple,)


def _require_splits_container(fn_name: str, tp: object) -> None:
    if not _splits_container(tp):
        raise TypeError(
            f"'{fn_name}' must return a List, Tuple, or NamedTuple of data splits; found {tp}"
        )


def _require_split_element_types(fn_name: str, container: object, expected: object, source: str) -> None:
    for element_type in get_args(container):
        if element_type != expected and not (_is_array_type(element_type) and _is_array_type(expected)):
            raise TypeError(
                f"'{fn_name}': elements of the output container must match the '{source}' output "
                f"type {expected}; found {container}"
            )


def _require_keyword_params(fn_name: str, params: Mapping[str, inspect.Parameter], required: Dict[str, object]) -> None:
    for position, (argname, argtype) in enumerate(required.items()):
        param = params.get(argname)
        if param is None:
            raise TypeError(
                f"'{fn_name}' must accept an argument '{argname}' of type {argtype} at position "
                f"{position + 1}; found signature {dict(params)}"
            )
        if param.annotation != argtype:
            raise TypeError(f"'{fn_name}': argument '{argname}' must be annotated {argtype}; found {param.annotation}")


def _require_arity(fn_name: str, actual_types: List[object], expected_types: Iterable[object]) -> None:
    expected_types = list(expected_types)
    if len(actual_types) != len(expected_types):
        raise TypeError(
            f"'{fn_name}': positional data arguments must match {expected_types}; found {actual_types}"
        )


def guard_reader(reader: Callable) -> None:
    """The reader must declare a return annotation (``type_guards.py:79-86``)."""
    if inspect.signature(reader).return_annotation is _EMPTY:
        raise TypeError("The dataset.reader function must declare a return type annotation.")


def guard_loader(loader: Callable, expected_data_type: object) -> None:
    """The loader's first argument must accept the reader output (``type_guards.py:88-92``)."""
    params = list(inspect.signature(loader).parameters.values())
    _require_compatible("loader", "first argument", params[0].annotation, expected_data_type)


def guard_splitter(splitter: Callable, expected_data_type: object, source: str) -> None:
    """Splitter contract: data in, container of same-typed splits out (``type_guards.py:95-104``)."""
    sig = inspect.signature(splitter)
    params = list(sig.parameters.values())
    _require_compatible("splitter", "first argument", params[0].annotation, expected_data_type)
    _require_splits_container("splitter", sig.return_annotation)
    _require_split_element_types("splitter", sig.return_annotation, expected_data_type, source)
    _require_keyword_params("splitter", sig.parameters, SPLITTER_REQUIRED_KWARGS)


def guard_parser(parser: Callable, expected_data_type: object, source: str) -> None:
    """Parser contract: data in, (features, targets) container out (``type_guards.py:107-115``)."""
    sig = inspect.signature(parser)
    params = list(sig.parameters.values())
    _require_compatible("parser", "first argument", params[0].annotation, expected_data_type)
    _require_splits_container("parser", sig.return_annotation)
    _require_keyword_params("parser", sig.parameters, PARSER_REQUIRED_KWARGS)


def guard_trainer(trainer: Callable, expected_model_type: object, expected_data_types: Iterable[object]) -> None:
    """Trainer contract: (model, *data) -> model (``type_guards.py:118-132``)."""
    sig = inspect.signature(trainer)
    params = list(sig.parameters.values())
    _require_compatible("trainer", "first argument (model object)", params[0].annotation, expected_model_type)
    _require_compatible("trainer", "return annotation", sig.return_annotation, expected_model_type)
    actual_data_types = _positional_annotations(params[1:])
    _require_arity("trainer", actual_data_types, expected_data_types)
    for actual, expected in zip(actual_data_types, expected_data_types):
        _require_compatible("trainer", "data argument", actual, expected)


def guard_evaluator(evaluator: Callable, expected_model_type: object, expected_data_types: Iterable[object]) -> None:
    """Evaluator contract: (model, *data) -> metric (``type_guards.py:135-148``)."""
    sig = inspect.signature(evaluator)
    params = list(sig.parameters.values())
    _require_compatible("evaluator", "first argument (model object)", params[0].annotation, expected_model_type)
    actual_data_types = _positional_annotations(params[1:])
    _require_arity("evaluator", actual_data_types, expected_data_types)
    for actual, expected in zip(actual_data_types, expected_data_types):
        _require_compatible("evaluator", "data argument", actual, expected)


def guard_predictor(predictor: Callable, expected_model_type: object, expected_data_type: object) -> None:
    """Predictor contract: (model, features) -> predictions, annotated (``type_guards.py:151-169``)."""
    sig = inspect.signature(predictor)
    params = list(sig.parameters.values())
    actual_data_types = _positional_annotations(params[1:])
    if len(actual_data_types) != 1:
        raise TypeError(f"'predictor' must take a single 'features' argument; found {actual_data_types}")
    _require_compatible("predictor", "first argument (model object)", params[0].annotation, expected_model_type)
    _require_compatible("predictor", "features argument", actual_data_types[0], expected_data_type)
    if sig.return_annotation is _EMPTY:
        raise TypeError("The 'predictor' function needs a return type annotation.")


def guard_prediction_callback(
    callback: Callable,
    predictor: Callable,
    expected_model_type: object,
    expected_data_type: object,
) -> None:
    """Callback contract: (model, features, predictions) -> None (``type_guards.py:172-233``)."""
    expected_prediction_type = inspect.signature(predictor).return_annotation
    if expected_prediction_type is _EMPTY:
        raise TypeError("The 'predictor' function needs a return type annotation.")

    sig = inspect.signature(callback)
    if sig.return_annotation is not _EMPTY and sig.return_annotation is not None:
        raise TypeError(f"'callback[{callback.__name__}]' must have None as its return annotation.")

    params = list(sig.parameters.values())
    trailing = _positional_annotations(params[1:])
    if len(trailing) != 2:
        raise TypeError(
            f"'callback[{callback.__name__}]' must take both 'features' and 'prediction' arguments; found {trailing}"
        )
    name = f"callback[{callback.__name__}]"
    _require_compatible(name, "first argument (model object)", params[0].annotation, expected_model_type)
    _require_compatible(name, "second argument (features)", trailing[0], expected_data_type)
    _require_compatible(name, "third argument (predictions)", trailing[1], expected_prediction_type)


def guard_feature_loader(feature_loader: Callable, expected_data_type: object) -> None:
    """Feature loader contract: exactly one argument (``type_guards.py:235-244``)."""
    sig = inspect.signature(feature_loader)
    params = list(sig.parameters.values())
    if len(params) != 1:
        raise TypeError("The 'feature_loader' must take a single argument of raw features or a reference to them.")
    _require_compatible("feature_loader", "argument", params[0].annotation, expected_data_type)


def guard_feature_transformer(feature_transformer: Callable, expected_data_type: object) -> None:
    """Feature transformer contract: exactly one argument (``type_guards.py:247-254``)."""
    sig = inspect.signature(feature_transformer)
    params = list(sig.parameters.values())
    if len(params) != 1:
        raise TypeError("The 'feature_transformer' must take a single argument representing loaded features.")
    _require_compatible("feature_transformer", "argument", params[0].annotation, expected_data_type)
