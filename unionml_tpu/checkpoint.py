"""Persistence: framework-aware model serialization + step-level pytree checkpointing.

Reference parity: the default saver/loader at ``unionml/model.py:1432-1519`` (joblib for
sklearn, ``torch.save(state_dict)`` for pytorch, ``model.save`` for keras). TPU-native
additions:

- JAX pytrees (flax params / optax states / ``TrainState``) get a first-class default:
  device arrays are pulled to host and serialized with flax's msgpack when available,
  falling back to joblib — works with both paths and file-like objects.
- :class:`Checkpointer` provides orbax-backed step-level checkpointing (async save,
  sharded restore) for long-running trainers — the step-resume capability SURVEY.md §5
  flags as required for the BERT config, which the reference lacks entirely.
"""

import os
from pathlib import Path
from typing import IO, Any, Callable, Optional, Union

import jax
import joblib
import numpy as np

from unionml_tpu._logging import logger
from unionml_tpu.utils import is_flax_module, is_keras_model, is_pytorch_model, is_sklearn_model

FileLike = Union[str, os.PathLike, IO]

#: tag embedded in serialized payloads so the loader can dispatch without the model type
_FORMAT_KEY = "__unionml_tpu_format__"


def _is_jax_pytree(obj: Any) -> bool:
    """True when obj is a non-trivial pytree whose leaves are all arrays/scalars."""
    leaves = jax.tree_util.tree_leaves(obj)
    if not leaves:
        return False
    if len(leaves) == 1 and leaves[0] is obj and not isinstance(obj, (jax.Array, np.ndarray)):
        return False
    return all(isinstance(leaf, (jax.Array, np.ndarray, np.generic, float, int, bool)) for leaf in leaves)


def pytree_to_host(tree: Any) -> Any:
    """Pull every device array in a pytree back to host numpy."""
    return jax.tree_util.tree_map(
        lambda leaf: np.asarray(jax.device_get(leaf)) if isinstance(leaf, jax.Array) else leaf, tree
    )


def extract_state(obj: Any) -> Any:
    """Pure-data state of a model object (arrays only; no callables/transform objects).

    For flax struct dataclasses (``TrainState`` etc.) only pytree-node fields are kept
    — static fields like ``apply_fn``/``tx`` hold closures that neither pickle nor
    belong in a checkpoint; they are rebuilt by the app's ``init`` at restore time.
    """
    import dataclasses

    from flax import serialization

    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        return {
            f.name: serialization.to_state_dict(getattr(obj, f.name))
            for f in dataclasses.fields(obj)
            if f.metadata.get("pytree_node", True)
        }
    return serialization.to_state_dict(obj)


def restore_state(target: Any, state: Any) -> Any:
    """Inverse of :func:`extract_state`: restore data into ``target``'s structure."""
    import dataclasses

    from flax import serialization

    if dataclasses.is_dataclass(target) and not isinstance(target, type):
        updates = {
            f.name: serialization.from_state_dict(getattr(target, f.name), state[f.name])
            for f in dataclasses.fields(target)
            if f.metadata.get("pytree_node", True) and f.name in state
        }
        if hasattr(target, "replace"):
            return target.replace(**updates)
        return dataclasses.replace(target, **updates)
    return serialization.from_state_dict(target, state)


def save_pytree(tree: Any, file: FileLike, hyperparameters: Optional[dict] = None) -> FileLike:
    """Serialize a pytree (+hyperparameters) to a file or file-like object.

    Stored as a flax *state dict* of host arrays rather than a pickled object: pytree
    containers like ``TrainState`` carry unpicklable static fields (optax transform
    closures, bound apply_fns); the state dict is pure data and restores into a
    structural template rebuilt by the app's ``init`` (see ``default_load``).
    """
    payload = {
        _FORMAT_KEY: "pytree",
        "model_obj": pytree_to_host(extract_state(tree)),
        "hyperparameters": hyperparameters,
    }
    joblib.dump(payload, file)
    return file


def load_pytree(file: FileLike, target: Any = None) -> Any:
    """Load a pytree state dict; restores into ``target``'s structure when given."""
    payload = joblib.load(file)
    state = payload["model_obj"]
    if target is not None:
        return restore_state(target, state)
    return state


def default_save(
    model_obj: Any,
    hyperparameters: Optional[dict],
    file: FileLike,
    *args,
    model_type: Optional[type] = None,
    **kwargs,
) -> Any:
    """Framework-aware default saver (``model.py:1432-1480`` parity + pytree support)."""
    if is_sklearn_model(model_obj):
        joblib.dump({_FORMAT_KEY: "sklearn", "model_obj": model_obj, "hyperparameters": hyperparameters}, file)
        return file
    if is_pytorch_model(type(model_obj)):
        import torch

        torch.save({"model_obj": model_obj.state_dict(), "hyperparameters": hyperparameters}, file, *args, **kwargs)
        return file
    if is_keras_model(type(model_obj)):
        model_obj.save(file, *args, **kwargs)
        return file
    if _is_jax_pytree(model_obj):
        return save_pytree(model_obj, file, hyperparameters)
    raise NotImplementedError(
        f"Default saver not defined for type {type(model_obj)}. Use the Model.saver decorator to define one."
    )


def default_load(
    file: FileLike,
    *args,
    model_type: Optional[type] = None,
    init_fn: Optional[Callable[[dict], Any]] = None,
    **kwargs,
) -> Any:
    """Framework-aware default loader (``model.py:1482-1519`` parity + pytree support)."""
    if model_type is not None and is_pytorch_model(model_type):
        import torch

        payload = torch.load(file, *args, **kwargs)
        hyperparameters = payload.get("hyperparameters") or {}
        if init_fn is not None:
            model = init_fn(hyperparameters)
        else:
            model = model_type(**hyperparameters)
        model.load_state_dict(payload["model_obj"])
        return model
    if model_type is not None and is_keras_model(model_type):
        import keras  # standalone keras 3; also provided by tensorflow installs

        return keras.models.load_model(file)

    # joblib formats (sklearn, pytree) self-describe via the embedded format tag
    payload = joblib.load(file)
    if isinstance(payload, dict) and payload.get(_FORMAT_KEY) == "pytree":
        state = payload["model_obj"]
        if init_fn is not None:
            target = init_fn(payload.get("hyperparameters") or {})
            return restore_state(target, state)
        return state
    if isinstance(payload, dict) and _FORMAT_KEY in payload:
        return payload["model_obj"]
    if isinstance(payload, dict) and "model_obj" in payload:
        return payload["model_obj"]
    return payload


class Checkpointer:
    """Step-level checkpointing for long-running trainers (orbax-backed).

    Usage::

        ckpt = Checkpointer(dir, max_to_keep=3)
        start_step = ckpt.latest_step() or 0
        state = ckpt.restore(state) if start_step else state
        for step in range(start_step, n_steps):
            state = train_step(state, batch)
            ckpt.save(step, state)   # async; overlaps with compute
        ckpt.close()

    On multi-host meshes orbax writes shards per host; on preemption (SIGTERM) the
    executor calls :meth:`flush` so the latest async save completes before exit.
    """

    def __init__(self, directory: Union[str, os.PathLike], max_to_keep: int = 3, save_interval_steps: int = 1):
        import orbax.checkpoint as ocp

        self.directory = Path(directory).absolute()
        self.directory.mkdir(parents=True, exist_ok=True)
        self._manager = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep,
                save_interval_steps=save_interval_steps,
                enable_async_checkpointing=True,
            ),
        )

    def latest_step(self) -> Optional[int]:
        return self._manager.latest_step()

    def save(self, step: int, state: Any) -> bool:
        import orbax.checkpoint as ocp

        return self._manager.save(step, args=ocp.args.StandardSave(state))

    def restore(self, target: Any, step: Optional[int] = None) -> Any:
        """Restore into the structure (and shardings) of ``target``."""
        import orbax.checkpoint as ocp

        step = self._manager.latest_step() if step is None else step
        if step is None:
            raise FileNotFoundError(f"No checkpoint found under {self.directory}")
        abstract = jax.tree_util.tree_map(ocp.utils.to_shape_dtype_struct, target)
        return self._manager.restore(step, args=ocp.args.StandardRestore(abstract))

    def flush(self) -> None:
        """Block until pending async saves land (preemption-safe shutdown)."""
        self._manager.wait_until_finished()

    def close(self) -> None:
        self.flush()
        self._manager.close()


def install_preemption_handler(checkpointer: Checkpointer) -> None:
    """Flush checkpoints on SIGTERM — TPU VM preemption notice handling (SURVEY.md §5)."""
    import signal

    previous = signal.getsignal(signal.SIGTERM)

    def _handler(signum, frame):
        logger.warning("SIGTERM received: flushing checkpoints before exit.")
        checkpointer.flush()
        if callable(previous):
            previous(signum, frame)
        else:
            raise SystemExit(143)

    signal.signal(signal.SIGTERM, _handler)
