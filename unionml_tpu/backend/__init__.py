"""Execution backend: the in-framework replacement for Flyte admin + propeller.

Reference parity: the remote surface the reference gets from ``FlyteRemote``
(``unionml/model.py:967-981``, ``unionml/remote.py``) — app deployment, workflow
execution with versioned lineage, artifact queries, schedule activation. The TPU-native
backend is a filesystem-rooted job store + executor:

- **Job specs carry TPU pod-slice resources** (accelerator/topology/host_count from
  :class:`unionml_tpu.defaults.Resources`) — the "no GPU in the task spec" north star.
- **Workers rehydrate apps** exactly like the reference's task resolver
  (``unionml/task_resolver.py:16-31``): the job record stores
  ``(module, variable, workflow name)``; the worker imports the module and rebuilds the
  workflow (see :mod:`unionml_tpu.backend.worker`).
- **Lineage**: every execution directory holds inputs/outputs/metadata; model versions
  are successful train-execution ids, newest first — the same query semantics as
  ``unionml/remote.py:200-330``.
- **Schedules** are driven by :class:`Scheduler`, an in-process cron loop using
  :func:`unionml_tpu.schedule.next_fire_time`.

A ``TPUPodBackend`` targeting real TPU VM fleets over SSH/GCE APIs can implement the
same :class:`ExecutionBackend` protocol; the local backend doubles as the test sandbox
(the analogue of the reference's dockerized Flyte demo cluster,
``tests/integration/test_flyte_remote.py:36-60``).
"""

import datetime
import json
import os
import pickle
import subprocess
import sys
import threading
import time
import uuid

import numpy as np
from dataclasses import asdict, dataclass, is_dataclass
from pathlib import Path
from typing import Any, Dict, List, Optional

from unionml_tpu._logging import logger
from unionml_tpu.defaults import Resources
from unionml_tpu.exceptions import BackendError
from unionml_tpu.schedule import Schedule, next_fire_time

_STATUS_QUEUED = "QUEUED"
_STATUS_RUNNING = "RUNNING"
_STATUS_SUCCEEDED = "SUCCEEDED"
_STATUS_FAILED = "FAILED"


def default_backend_root() -> Path:
    return Path(os.getenv("UNIONML_TPU_HOME", Path.home() / ".unionml-tpu")) / "backend"


@dataclass
class JobSpec:
    """Serializable description of one workflow execution request.

    The resource block requests TPU pod-slice shape — accelerator type, chip topology,
    and host count — never a GPU device class.
    """

    app_module: str
    app_variable: str
    module_file: Optional[str]
    workflow_name: str
    app_version: str
    resources: Dict[str, Any]

    def to_json(self) -> Dict[str, Any]:
        return asdict(self)


class Execution:
    """Handle to a (possibly running) workflow execution."""

    def __init__(self, execution_id: str, directory: Path, backend: "LocalBackend"):
        self.id = execution_id
        self.directory = directory
        self._backend = backend
        self._outputs: Optional[Dict[str, Any]] = None

    @property
    def metadata(self) -> Dict[str, Any]:
        with (self.directory / "meta.json").open() as f:
            return json.load(f)

    @property
    def status(self) -> str:
        status_file = self.directory / "status"
        return status_file.read_text().strip() if status_file.exists() else _STATUS_QUEUED

    @property
    def is_done(self) -> bool:
        return self.status in (_STATUS_SUCCEEDED, _STATUS_FAILED)

    @property
    def error(self) -> Optional[str]:
        err = self.directory / "error.txt"
        return err.read_text() if err.exists() else None

    @property
    def outputs(self) -> Dict[str, Any]:
        if self._outputs is None:
            if self.status != _STATUS_SUCCEEDED:
                raise BackendError(f"Execution {self.id} has no outputs (status={self.status}): {self.error}")
            with (self.directory / "outputs.pkl").open("rb") as f:
                self._outputs = pickle.load(f)
        return self._outputs

    def __repr__(self) -> str:
        return f"Execution(id={self.id!r}, status={self.status!r})"


class LocalBackend:
    """Filesystem-rooted execution backend running jobs in worker subprocesses.

    ``in_process=True`` skips the subprocess boundary (fast unit-test path);
    the default forks a worker that re-imports the app module — the same process
    boundary a remote TPU VM worker crosses.
    """

    def __init__(
        self,
        root: Optional[Path] = None,
        project: Optional[str] = None,
        domain: Optional[str] = None,
        in_process: bool = False,
        retries: int = 0,
    ):
        """
        :param retries: job-level retry budget — a failed/crashed worker is respawned
            up to this many times before the execution is reported FAILED (the
            failure-recovery obligation from SURVEY.md §5; the reference delegates
            retries to Flyte).
        """
        self.root = Path(root) if root is not None else default_backend_root()
        self.default_project = project or "default-project"
        self.default_domain = domain or "development"
        self.in_process = in_process
        self.retries = retries
        self._workers: Dict[str, subprocess.Popen] = {}
        self._owned: set = set()  # executions this client started (retry eligibility)
        self._base.mkdir(parents=True, exist_ok=True)

    # ---------------------------------------------------------------- layout

    @property
    def _base(self) -> Path:
        return self.root / self.default_project / self.default_domain

    @property
    def _executions_dir(self) -> Path:
        return self._base / "executions"

    @property
    def _apps_dir(self) -> Path:
        return self._base / "apps"

    @property
    def _schedules_dir(self) -> Path:
        return self._base / "schedules"

    # ---------------------------------------------------------------- deployment

    def create_project(self, project: Optional[str] = None) -> None:
        """``unionml/remote.py:38-43`` analogue."""
        if project:
            self.default_project = project
        self._base.mkdir(parents=True, exist_ok=True)

    def deploy_workflow(
        self,
        model: Any,
        workflow_name: str,
        app_version: str,
        patch: bool = False,
    ) -> None:
        """Register a workflow version: record the app's rehydration address + resources."""
        resources = model.resources or Resources()
        spec = JobSpec(
            app_module=model.instantiated_in or "__unknown__",
            app_variable=model.find_lhs(),
            module_file=model._module_file,
            workflow_name=workflow_name,
            app_version=app_version,
            resources=asdict(resources),
        )
        target = self._apps_dir / app_version
        target.mkdir(parents=True, exist_ok=True)
        with (target / f"{workflow_name}.json").open("w") as f:
            json.dump({**spec.to_json(), "patch": patch, "deployed_at": _now_iso()}, f, indent=2)
        logger.info("Deployed workflow %s at version %s", workflow_name, app_version)

    def list_app_versions(self) -> List[str]:
        if not self._apps_dir.exists():
            return []
        versions = [(p.stat().st_mtime, p.name) for p in self._apps_dir.iterdir() if p.is_dir()]
        return [name for _, name in sorted(versions, reverse=True)]

    def fetch_workflow_spec(self, workflow_name: str, app_version: Optional[str] = None) -> Dict[str, Any]:
        versions = [app_version] if app_version else self.list_app_versions()
        for version in versions:
            candidate = self._apps_dir / version / f"{workflow_name}.json"
            if candidate.exists():
                with candidate.open() as f:
                    return json.load(f)
        raise BackendError(
            f"Workflow {workflow_name!r} not deployed"
            + (f" at version {app_version!r}" if app_version else " at any version")
        )

    # ---------------------------------------------------------------- execution

    def execute(
        self,
        model: Any,
        workflow_name: str,
        inputs: Dict[str, Any],
        app_version: Optional[str] = None,
        schedule_name: Optional[str] = None,
    ) -> Execution:
        """Submit one workflow execution; returns immediately with a handle."""
        try:
            spec_json = self.fetch_workflow_spec(workflow_name, app_version)
        except BackendError:
            # undeployed local runs still execute (the reference requires deploy first;
            # we degrade gracefully using the in-memory model's address)
            spec_json = {
                "app_module": model.instantiated_in or "__unknown__",
                "app_variable": model.find_lhs(),
                "module_file": model._module_file,
                "workflow_name": workflow_name,
                "app_version": app_version or "dev",
                "resources": asdict(model.resources or Resources()),
            }

        execution_id = "{}-{}-{}".format(
            workflow_name.replace(".", "-"),
            datetime.datetime.now().strftime("%Y%m%d%H%M%S"),
            uuid.uuid4().hex[:6],
        )
        exec_dir = self._executions_dir / execution_id
        exec_dir.mkdir(parents=True, exist_ok=True)

        with (exec_dir / "inputs.pkl").open("wb") as f:
            pickle.dump(_plain_inputs(inputs), f)
        meta = {
            "execution_id": execution_id,
            "workflow_name": spec_json["workflow_name"],
            "app_version": spec_json.get("app_version"),
            "app_module": spec_json["app_module"],
            "app_variable": spec_json["app_variable"],
            "module_file": spec_json.get("module_file"),
            "resources": spec_json.get("resources", {}),
            "schedule_name": schedule_name,
            "created_at": _now_iso(),
        }
        with (exec_dir / "meta.json").open("w") as f:
            json.dump(meta, f, indent=2)
        (exec_dir / "status").write_text(_STATUS_QUEUED)

        execution = Execution(execution_id, exec_dir, self)
        self._owned.add(execution_id)
        if self.in_process:
            if int((meta.get("resources") or {}).get("host_count", 1) or 1) > 1:
                raise BackendError(
                    "host_count > 1 requires worker subprocesses; in_process backends "
                    "cannot run multi-host jobs."
                )
            self._run_in_process(execution, model)
        else:
            self._spawn_worker(execution)
        return execution

    def _run_in_process(self, execution: Execution, model: Any) -> None:
        from unionml_tpu.backend.worker import run_workflow_for_model

        for attempt in range(1, self.retries + 2):
            (execution.directory / "attempts").write_text(str(attempt))
            (execution.directory / "status").write_text(_STATUS_RUNNING)
            try:
                with (execution.directory / "inputs.pkl").open("rb") as f:
                    inputs = pickle.load(f)
                outputs = run_workflow_for_model(model, execution.metadata["workflow_name"], inputs)
                with (execution.directory / "outputs.pkl").open("wb") as f:
                    pickle.dump(outputs, f)
                (execution.directory / "status").write_text(_STATUS_SUCCEEDED)
                return
            except Exception as exc:
                (execution.directory / "error.txt").write_text(repr(exc))
                (execution.directory / "status").write_text(_STATUS_FAILED)
                if attempt <= self.retries:
                    logger.warning(
                        "In-process execution %s failed (attempt %d/%d): retrying. Error: %r",
                        execution.id,
                        attempt,
                        self.retries + 1,
                        exc,
                    )
                else:
                    logger.exception("In-process execution %s failed", execution.id)

    def _spawn_worker(self, execution: Execution) -> None:
        """Fork the worker entrypoint(s) — the process/machine boundary (§3.2 call stack).

        Jobs whose resource spec declares ``host_count > 1`` spawn one worker per host
        with ``jax.distributed`` coordination env (the local stand-in for a multi-host
        TPU slice, where each host runs the same entrypoint); host 0 owns outputs and
        status.
        """
        host_count = int((execution.metadata.get("resources") or {}).get("host_count", 1) or 1)
        if host_count <= 1:
            with (execution.directory / "worker.log").open("w") as log_file:
                process = subprocess.Popen(
                    [sys.executable, "-m", "unionml_tpu.backend.worker", str(execution.directory)],
                    stdout=log_file,
                    stderr=subprocess.STDOUT,
                    cwd=os.getcwd(),
                )
            # keep the handles: poll() reaps children (no zombies) and detects crashes
            self._workers[execution.id] = [process]
            (execution.directory / "pid").write_text(str(process.pid))
            return

        from unionml_tpu.utils import pick_free_port

        coordinator = f"127.0.0.1:{pick_free_port()}"
        fleet = []
        for host in range(host_count):
            env = {
                **os.environ,
                "JAX_COORDINATOR_ADDRESS": coordinator,
                "JAX_NUM_PROCESSES": str(host_count),
                "JAX_PROCESS_ID": str(host),
            }
            with (execution.directory / f"worker-{host}.log").open("w") as log_file:
                process = subprocess.Popen(
                    [sys.executable, "-m", "unionml_tpu.backend.worker", str(execution.directory)],
                    stdout=log_file,
                    stderr=subprocess.STDOUT,
                    cwd=os.getcwd(),
                    env=env,
                )
            fleet.append(process)
        self._workers[execution.id] = fleet
        (execution.directory / "pid").write_text(str(fleet[0].pid))

    def _terminate_workers(self, execution_id: str, timeout: float = 5.0) -> None:
        """Kill every worker of an execution (before retries; on fleet failure)."""
        for process in self._workers.pop(execution_id, []):
            if process.poll() is None:
                process.terminate()
                try:
                    process.wait(timeout=timeout)
                except subprocess.TimeoutExpired:
                    process.kill()
                    process.wait()

    def _reap_dead_worker(self, execution: Execution) -> None:
        """Failure detection: mark an execution FAILED if its worker died without a status.

        A worker OOM-killed or segfaulted (plausible under XLA memory pressure) never
        writes SUCCEEDED/FAILED; without this check ``wait`` would spin forever. Own
        children are poll()ed (which also reaps the zombie); foreign pids (another
        client waiting on the same store) are checked via /proc, treating zombie state
        as dead.
        """
        fleet = self._workers.get(execution.id)
        if fleet is not None:
            if all(process.poll() is None for process in fleet):
                return
            if any(process.poll() is None for process in fleet):
                # part of a multi-host fleet died: the survivors are stuck in
                # collectives — bring the whole job down so FAILED is deterministic
                logger.warning("Execution %s: a worker died; terminating the fleet.", execution.id)
                self._terminate_workers(execution.id)
            else:
                self._workers.pop(execution.id, None)  # all exited: drop the handles
            dead = True
        else:
            pid_file = execution.directory / "pid"
            if not pid_file.exists():
                return
            try:
                pid = int(pid_file.read_text().strip())
            except ValueError:
                return
            dead = _pid_dead_or_zombie(pid)
        if dead and not execution.is_done:
            (execution.directory / "error.txt").write_text(
                "Worker process exited without reporting a status (killed or crashed)."
            )
            (execution.directory / "status").write_text(_STATUS_FAILED)

    def _attempts(self, execution: Execution) -> int:
        attempts_file = execution.directory / "attempts"
        return int(attempts_file.read_text()) if attempts_file.exists() else 1

    def _maybe_retry(self, execution: Execution) -> bool:
        """Respawn a failed worker while the retry budget lasts. True when retried.

        Only executions started by THIS client are eligible: ``wait`` on a historical
        FAILED execution is a status query and must never re-run the job.
        """
        if execution.id not in self._owned:
            return False
        attempts = self._attempts(execution)
        if attempts > self.retries:
            return False
        logger.warning(
            "Execution %s failed (attempt %d/%d): retrying. Error: %s",
            execution.id,
            attempts,
            self.retries + 1,
            execution.error,
        )
        self._terminate_workers(execution.id)  # no stale fleet racing the respawn
        (execution.directory / "attempts").write_text(str(attempts + 1))
        (execution.directory / "error.txt").unlink(missing_ok=True)
        (execution.directory / "status").write_text(_STATUS_QUEUED)
        execution._outputs = None
        self._spawn_worker(execution)
        return True

    def wait(self, execution: Execution, timeout: Optional[float] = None, poll_interval: float = 0.2) -> Execution:
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            while not execution.is_done:
                self._reap_dead_worker(execution)
                if execution.is_done:
                    break
                if deadline is not None and time.monotonic() > deadline:
                    raise BackendError(f"Timed out waiting for execution {execution.id}")
                time.sleep(poll_interval)
            if execution.status == _STATUS_FAILED and not self.in_process and self._maybe_retry(execution):
                continue
            break
        if execution.status == _STATUS_FAILED:
            raise BackendError(f"Execution {execution.id} failed: {execution.error}")
        return execution

    # ---------------------------------------------------------------- lineage queries

    def get_execution(self, execution_id: str) -> Execution:
        exec_dir = self._executions_dir / execution_id
        if not exec_dir.exists():
            raise BackendError(f"Execution {execution_id!r} not found")
        return Execution(execution_id, exec_dir, self)

    def list_executions(
        self,
        workflow_name: Optional[str] = None,
        app_version: Optional[str] = None,
        schedule_name: Optional[str] = None,
        only_successful: bool = True,
        limit: int = 10,
    ) -> List[Execution]:
        """Executions newest-first with the reference's filter semantics (``remote.py:200-269``)."""
        if not self._executions_dir.exists():
            return []
        candidates = sorted(self._executions_dir.iterdir(), key=lambda p: p.stat().st_mtime, reverse=True)
        results: List[Execution] = []
        for exec_dir in candidates:
            if len(results) >= limit:
                break
            execution = Execution(exec_dir.name, exec_dir, self)
            try:
                meta = execution.metadata
            except (OSError, json.JSONDecodeError):
                continue
            if workflow_name and meta.get("workflow_name") != workflow_name:
                continue
            if app_version and meta.get("app_version") != app_version:
                continue
            if schedule_name and meta.get("schedule_name") != schedule_name:
                continue
            if only_successful and execution.status != _STATUS_SUCCEEDED:
                continue
            results.append(execution)
        return results

    # ---------------------------------------------------------------- schedules

    def deploy_schedule(self, model: Any, schedule: Schedule, app_version: str) -> None:
        schedule.validate()
        self._schedules_dir.mkdir(parents=True, exist_ok=True)
        workflow_name = f"{model.name}.{'train' if schedule.workflow_kind == 'train' else 'predict'}"
        record = {
            "name": schedule.name,
            "workflow_name": workflow_name,
            "app_version": app_version,
            "expression": schedule.expression,
            "offset": schedule.offset,
            "fixed_rate_seconds": schedule.fixed_rate.total_seconds() if schedule.fixed_rate else None,
            "time_arg": schedule.time_arg,
            "active": False,
            "deployed_at": _now_iso(),
        }
        with (self._schedules_dir / f"{schedule.name}.json").open("w") as f:
            json.dump(record, f, indent=2)
        with (self._schedules_dir / f"{schedule.name}.inputs.pkl").open("wb") as f:
            pickle.dump(_plain_inputs(schedule.inputs or {}), f)

    def _set_schedule_active(self, name: str, active: bool) -> None:
        path = self._schedules_dir / f"{name}.json"
        if not path.exists():
            raise BackendError(f"Schedule {name!r} is not deployed")
        with path.open() as f:
            record = json.load(f)
        record["active"] = active
        with path.open("w") as f:
            json.dump(record, f, indent=2)

    def activate_schedule(self, model: Any, schedule: Schedule, app_version: Optional[str] = None) -> None:
        self._set_schedule_active(schedule.name, True)

    def deactivate_schedule(self, model: Any, schedule: Schedule, app_version: Optional[str] = None) -> None:
        self._set_schedule_active(schedule.name, False)

    def list_schedules(self) -> List[Dict[str, Any]]:
        if not self._schedules_dir.exists():
            return []
        records = []
        for path in sorted(self._schedules_dir.glob("*.json")):
            with path.open() as f:
                records.append(json.load(f))
        return records

    def list_scheduled_runs(self, schedule_name: str, app_version: Optional[str] = None, limit: int = 5):
        """``unionml/remote.py:333-350`` analogue: executions tagged with the schedule name."""
        return self.list_executions(
            schedule_name=schedule_name, app_version=app_version, only_successful=False, limit=limit
        )


class Scheduler:
    """In-process cron loop firing active schedules against a backend.

    The reference delegates this to Flyte's scheduler; here ``unionml-tpu scheduler run``
    (CLI) or ``Scheduler.start()`` runs it. Each fire creates a normal execution tagged
    with the schedule name so lineage queries work identically.
    """

    def __init__(self, backend: LocalBackend, poll_interval: float = 10.0):
        self.backend = backend
        self.poll_interval = poll_interval
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._next_fire: Dict[str, datetime.datetime] = {}

    def tick(self, now: Optional[datetime.datetime] = None) -> List[Execution]:
        """Evaluate all active schedules once; fire those that are due. Returns fired executions."""
        now = now or datetime.datetime.now()
        fired: List[Execution] = []
        for record in self.backend.list_schedules():
            if not record.get("active"):
                self._next_fire.pop(record["name"], None)  # graftlint: disable=data-race -- tick() is driven either synchronously (CLI/tests) or by the single _loop thread, never both; start() hands the schedule state to the loop
                continue
            name = record["name"]
            schedule = Schedule(
                type="trainer" if record["workflow_name"].endswith(".train") else "predictor",
                name=name,
                expression=record.get("expression"),
                offset=record.get("offset"),
                fixed_rate=(
                    datetime.timedelta(seconds=record["fixed_rate_seconds"])
                    if record.get("fixed_rate_seconds")
                    else None
                ),
                time_arg=record.get("time_arg"),
            )
            if name not in self._next_fire:
                self._next_fire[name] = next_fire_time(schedule, now)
                continue
            if now >= self._next_fire[name]:
                fired.append(self._fire(record, schedule, now))
                self._next_fire[name] = next_fire_time(schedule, now)
        return fired

    def _fire(self, record: Dict[str, Any], schedule: Schedule, now: datetime.datetime) -> Execution:
        with (self.backend._schedules_dir / f"{record['name']}.inputs.pkl").open("rb") as f:
            inputs = pickle.load(f)
        if schedule.time_arg:
            inputs[schedule.time_arg] = now
        spec = self.backend.fetch_workflow_spec(record["workflow_name"], record.get("app_version"))
        from unionml_tpu.tracker import load_tracked_instance

        model = load_tracked_instance(spec["app_module"], spec["app_variable"], spec.get("module_file"))
        logger.info("Schedule %s firing %s", record["name"], record["workflow_name"])
        return self.backend.execute(
            model,
            record["workflow_name"],
            inputs=inputs,
            app_version=record.get("app_version"),
            schedule_name=record["name"],
        )

    def start(self) -> None:
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.tick()
            except Exception:
                logger.exception("Scheduler tick failed")
            self._stop.wait(self.poll_interval)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)


def backend_from_config(
    target: Optional[str] = None,
    config_file: Optional[str] = None,
    project: Optional[str] = None,
    domain: Optional[str] = None,
) -> LocalBackend:
    """Build a backend client from a target string / YAML config file.

    Config layering parity with ``Config.auto(config_file=...)`` (``model.py:972-974``):
    explicit args > config file > environment > defaults.
    """
    root: Optional[Path] = None
    in_process = False
    if config_file:
        import yaml

        with open(config_file) as f:
            config = yaml.safe_load(f) or {}
        backend_cfg = config.get("backend", config)
        root = Path(backend_cfg["root"]) if "root" in backend_cfg else None
        project = project or backend_cfg.get("project")
        domain = domain or backend_cfg.get("domain")
        in_process = bool(backend_cfg.get("in_process", False))
    if target:
        if target.startswith("tpu-pod://"):
            from unionml_tpu.backend.tpu_pod import TPUPodBackend, parse_pod_target

            transport, options = parse_pod_target(target)
            return TPUPodBackend(
                store_url=options["store"],
                transport=transport,
                project=project or options.get("project"),
                domain=domain or options.get("domain"),
                retries=int(options.get("retries", "0")),
            )
        if target.startswith("local://"):
            root = Path(target[len("local://") :]) if len(target) > len("local://") else None
        elif target not in ("local", "sandbox"):
            raise BackendError(
                f"Unknown backend target {target!r}; expected 'local', 'sandbox', "
                f"'local://<path>', or 'tpu-pod://<hosts>?store=<url>'"
            )
    return LocalBackend(root=root, project=project, domain=domain, in_process=in_process)


def _pid_dead_or_zombie(pid: int) -> bool:
    """True when ``pid`` no longer runs (gone, or a zombie awaiting reaping)."""
    if os.path.isdir("/proc"):
        try:
            with open(f"/proc/{pid}/stat") as f:
                # field 3 (after the parenthesized comm, which may contain spaces)
                state = f.read().rsplit(")", 1)[1].split()[0]
            return state == "Z"
        except (FileNotFoundError, ProcessLookupError, IndexError):
            return True
        except OSError:  # pragma: no cover - unreadable entry: assume alive
            return False
    # no procfs (macOS/BSD): signal-0 probe — cannot see zombies, but those only
    # arise for our own children, which are handled via Popen.poll()
    try:
        os.kill(pid, 0)
        return False
    except ProcessLookupError:
        return True
    except PermissionError:  # pragma: no cover - alive, owned elsewhere
        return False


_STATE_MARKER = "__unionml_state_dict__"


def wire_encode_value(value: Any, hyperparameters: Any = None) -> Any:
    """Encode one value for cross-process transport.

    Three tiers (the type-engine replacement — SURVEY.md §7 "hard parts"):

    1. synthesized kwargs dataclasses -> plain dicts (their types don't exist in a
       fresh process);
    2. picklable values pass through;
    3. unpicklable pytrees (e.g. flax ``TrainState`` whose optax transform holds
       closures) -> flax state dict of host arrays + the hyperparameters needed to
       rebuild the structural template via the app's ``init`` on the other side.
    """
    if is_dataclass(value) and not isinstance(value, type) and hasattr(type(value), "from_dict"):
        # synthesized kwargs/hyperparameter dataclasses: plain-dict wire format
        return asdict(value)

    def state_encode():
        from unionml_tpu._logging import logger
        from unionml_tpu.checkpoint import extract_state, pytree_to_host

        hp = asdict(hyperparameters) if is_dataclass(hyperparameters) else hyperparameters
        if hp is None:
            logger.warning(
                "Encoding a non-picklable model object without hyperparameters; the "
                "receiving side rebuilds its structure via init() defaults."
            )
        return {_STATE_MARKER: pytree_to_host(extract_state(value)), "hyperparameters": hp}

    # flax struct dataclasses (TrainState etc.) always carry unpicklable static fields:
    # skip the (expensive, always-failing) pickle probe
    if is_dataclass(value) and not isinstance(value, type) and hasattr(value, "replace"):
        return state_encode()
    # scalars / arrays / strings are trivially picklable: skip the probe entirely
    if value is None or isinstance(value, (bool, int, float, str, bytes, np.ndarray, np.generic)):
        return value
    try:
        pickle.dumps(value)
        return value
    except Exception:  # graftlint: disable=swallowed-exception -- a picklability PROBE: any failure routes the value to state_encode(), which is the handling
        return state_encode()


def wire_decode_value(value: Any, model: Any) -> Any:
    """Rebuild a state-dict-encoded model object using the app's init slot."""
    if isinstance(value, dict) and _STATE_MARKER in value:
        from unionml_tpu.checkpoint import restore_state

        target = model._init_model_object(value.get("hyperparameters") or {})
        return restore_state(target, value[_STATE_MARKER])
    return value


def _plain_inputs(inputs: Dict[str, Any], hyperparameters: Any = None) -> Dict[str, Any]:
    """Encode every entry of an inputs/outputs mapping for transport."""
    hp = hyperparameters if hyperparameters is not None else inputs.get("hyperparameters")
    return {key: wire_encode_value(value, hp) for key, value in inputs.items()}


def _now_iso() -> str:
    return datetime.datetime.now().isoformat(timespec="seconds")
