"""Artifact store: a Path-like view over fsspec filesystems (local / GCS / memory).

The reference moves inter-task data and model artifacts through Flyte's blob store
(S3/minio — ``tests/integration/test_flyte_remote.py`` CI wiring sets minio creds).
The TPU-native equivalent is GCS: pod workers and the client share one bucket for job
records, inputs, outputs, and packaged app source. :class:`StorePath` exposes the small
pathlib surface the backend uses (join, mkdir, open, read/write_text, exists, iterdir)
over any fsspec URL, so the same backend code runs against:

- ``file:///...``  — local filesystem (tests, single-machine)
- ``gs://bucket/prefix`` — Google Cloud Storage via gcsfs (real TPU pod fleets)
- ``memory://...`` — in-process fake (unit tests; NOT visible across processes)

A ``StorePath`` stringifies back to its URL, so it can cross a process boundary as a
CLI argument and be reconstructed with :func:`store_path` on the other side (the pod
worker does exactly this).
"""

import io
import posixpath
from typing import Any, Iterator, List, Optional, Tuple

import fsspec


class _StoreStat:
    __slots__ = ("st_mtime", "st_size")

    def __init__(self, st_mtime: float, st_size: int):
        self.st_mtime = st_mtime
        self.st_size = st_size


class StorePath:
    """Minimal pathlib-compatible wrapper over an fsspec filesystem.

    Implements exactly the operations the execution backend performs on its root:
    ``/`` joining, ``name``, ``mkdir``, ``exists``, ``is_dir``, ``iterdir``, ``open``,
    ``read_text``/``write_text``, ``stat().st_mtime``, and ``unlink``.
    """

    def __init__(self, fs: fsspec.AbstractFileSystem, path: str, protocol: str):
        self._fs = fs
        self._path = path.rstrip("/") or "/"
        self._protocol = protocol

    # ---------------------------------------------------------------- identity

    @property
    def name(self) -> str:
        return posixpath.basename(self._path)

    @property
    def url(self) -> str:
        return f"{self._protocol}://{self._path.lstrip('/') if self._protocol != 'file' else self._path}"

    def __str__(self) -> str:
        return self.url

    def __repr__(self) -> str:
        return f"StorePath({self.url!r})"

    def __truediv__(self, other: str) -> "StorePath":
        return StorePath(self._fs, posixpath.join(self._path, str(other)), self._protocol)

    def __eq__(self, other: Any) -> bool:
        return isinstance(other, StorePath) and other.url == self.url

    def __lt__(self, other: Any):  # sorted() over listings
        if not isinstance(other, StorePath):
            return NotImplemented
        return self.url < other.url

    def __hash__(self) -> int:
        return hash(self.url)

    # ---------------------------------------------------------------- fs ops

    def mkdir(self, parents: bool = False, exist_ok: bool = False) -> None:
        # object stores have no real directories; makedirs is a no-op marker there,
        # which is exactly the semantics the backend needs
        try:
            self._fs.makedirs(self._path, exist_ok=exist_ok or parents)
        except FileExistsError:
            if not exist_ok:
                raise

    def exists(self) -> bool:
        return bool(self._fs.exists(self._path))

    def is_dir(self) -> bool:
        try:
            return bool(self._fs.isdir(self._path))
        except Exception:  # graftlint: disable=swallowed-exception -- fsspec backends raise wildly varied errors for missing paths; "not a dir" is the correct total answer
            return False

    def iterdir(self) -> Iterator["StorePath"]:
        if not self.exists():
            return
        for entry in self._fs.ls(self._path, detail=False):
            entry_path = entry if isinstance(entry, str) else entry["name"]
            entry_path = entry_path.rstrip("/")
            if entry_path and entry_path != self._path:
                yield StorePath(self._fs, entry_path, self._protocol)

    def glob(self, pattern: str) -> Iterator["StorePath"]:
        """Non-recursive glob over direct children (the backend's ``*.json`` case)."""
        import fnmatch

        for child in self.iterdir():
            # fnmatchcase: platform-independent, matching pathlib.Path.glob semantics
            if fnmatch.fnmatchcase(child.name, pattern):
                yield child

    def open(self, mode: str = "r"):
        if "r" in mode and not self._fs.exists(self._path):
            raise FileNotFoundError(self._path)
        return self._fs.open(self._path, mode)

    def read_text(self) -> str:
        with self.open("r") as f:
            data = f.read()
        return data.decode() if isinstance(data, bytes) else data

    def write_text(self, text: str) -> int:
        parent = posixpath.dirname(self._path)
        if parent:
            self._fs.makedirs(parent, exist_ok=True)
        with self._fs.open(self._path, "w") as f:
            f.write(text)
        return len(text)

    def read_bytes(self) -> bytes:
        with self.open("rb") as f:
            return f.read()

    def write_bytes(self, data: bytes) -> int:
        parent = posixpath.dirname(self._path)
        if parent:
            self._fs.makedirs(parent, exist_ok=True)
        with self._fs.open(self._path, "wb") as f:
            f.write(data)
        return len(data)

    def unlink(self, missing_ok: bool = False) -> None:
        try:
            self._fs.rm(self._path)
        except FileNotFoundError:
            if not missing_ok:
                raise

    def stat(self) -> _StoreStat:
        info = self._fs.info(self._path)
        mtime = info.get("mtime") or info.get("LastModified") or info.get("created") or 0
        if hasattr(mtime, "timestamp"):
            mtime = mtime.timestamp()
        return _StoreStat(float(mtime or 0), int(info.get("size") or 0))


def store_path(url: str) -> StorePath:
    """Build a :class:`StorePath` from an fsspec URL (``file://``, ``gs://``, ...).

    Bare filesystem paths (no ``://``) are accepted and absolutized.
    """
    import os

    if "://" not in url:
        return StorePath(fsspec.filesystem("file"), os.path.abspath(url), "file")
    protocol, _, rest = url.partition("://")
    if not rest:
        raise ValueError(f"Store URL must look like '<protocol>://<path>', got {url!r}")
    if protocol == "file":
        rest = os.path.abspath(rest)
    return StorePath(fsspec.filesystem(protocol), rest, protocol)
