"""Pod worker entrypoint: store-backed execution on a TPU VM host.

The pod-fleet analogue of :mod:`unionml_tpu.backend.worker` (which receives a local
execution directory): this entrypoint receives the execution's STORE URL, pulls the
packaged app source from the store, installs it on ``sys.path``, and then runs the
standard worker body against the store-backed execution "directory" — every status,
error, and output write lands in the shared store where the client (and the other
hosts) can see it.

Usage (launched by :class:`unionml_tpu.backend.tpu_pod.TPUPodBackend` via transport)::

    python -m unionml_tpu.backend.pod_worker <execution-url> [--source <zip-url>]

Multi-host jobs receive ``JAX_COORDINATOR_ADDRESS`` / ``JAX_NUM_PROCESSES`` /
``JAX_PROCESS_ID`` in the environment; ``worker.run_execution`` joins the
``jax.distributed`` mesh before any computation (reference boundary:
``unionml/task_resolver.py:16-31`` running inside the remote container).
"""

import argparse
import io
import json
import sys
import tempfile
import zipfile
from pathlib import Path
from typing import Optional


def install_source(source_url: str) -> Optional[str]:
    """Download + extract the app source zip; returns the local module file path."""
    from unionml_tpu.backend.store import store_path

    source = store_path(source_url)
    if not source.exists():
        return None
    scratch = Path(tempfile.mkdtemp(prefix="unionml-app-src-"))
    with zipfile.ZipFile(io.BytesIO(source.read_bytes())) as zf:
        zf.extractall(scratch)
    sys.path.insert(0, str(scratch))
    manifest = scratch / "__unionml_source__.json"
    if manifest.exists():
        rel = json.loads(manifest.read_text()).get("module_file")
        if rel and (scratch / rel).exists():
            return str(scratch / rel)
    return None


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("execution_url")
    parser.add_argument("--source", default=None)
    args = parser.parse_args()

    from unionml_tpu.backend.store import store_path
    from unionml_tpu.backend.worker import run_execution

    module_file_override = install_source(args.source) if args.source else None
    execution_dir = store_path(args.execution_url)
    raise SystemExit(run_execution(execution_dir, module_file_override=module_file_override))


if __name__ == "__main__":
    main()
