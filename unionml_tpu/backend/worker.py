"""Worker entrypoint: rehydrate the app module and execute a workflow.

This is the process/machine boundary of the backend — the analogue of the reference's
task resolver running inside a remote container (``unionml/task_resolver.py:16-31``):
the worker receives an execution directory containing ``meta.json`` with the app's
``(module, variable)`` address, re-imports the module (which re-runs the ``Dataset``/
``Model`` decorators), rebuilds the named workflow, and executes it.

On a multi-host TPU slice every host runs this same entrypoint; host 0 writes outputs.
``jax.distributed`` initialization happens here (before any jax computation) when the
job's resource spec declares ``host_count > 1``.
"""

import json
import pickle
import sys
from pathlib import Path
from typing import Any, Dict


def _resolve_workflow(model: Any, workflow_name: str):
    """Map a workflow name back to its factory on the rehydrated model object."""
    factories = {
        model.train_workflow_name: model.train_workflow,
        model.predict_workflow_name: model.predict_workflow,
        model.predict_from_features_workflow_name: model.predict_from_features_workflow,
    }
    try:
        return factories[workflow_name]()
    except KeyError:
        raise ValueError(
            f"Workflow {workflow_name!r} is not one of {sorted(factories)} for model {model.name!r}"
        ) from None


def _coerce_inputs(workflow, inputs: Dict[str, Any]) -> Dict[str, Any]:
    """Rebuild typed kwargs dataclasses from the plain-dict wire format."""
    coerced = {}
    for name, annotation in workflow.input_types.items():
        value = inputs.get(name)
        if (
            isinstance(value, dict)
            and isinstance(annotation, type)
            and hasattr(annotation, "from_dict")
        ):
            coerced[name] = annotation.from_dict(value)
        elif name in inputs:
            coerced[name] = value
    return coerced


def run_workflow_for_model(model: Any, workflow_name: str, inputs: Dict[str, Any]) -> Dict[str, Any]:
    """Execute a named workflow and map positional results to named outputs.

    Inputs are wire-decoded (state-dict-encoded model objects rebuilt via the app's
    init) and outputs wire-encoded back — see ``unionml_tpu.backend.wire_encode_value``.
    """
    from unionml_tpu.backend import _plain_inputs, wire_decode_value

    workflow = _resolve_workflow(model, workflow_name)
    inputs = {key: wire_decode_value(value, model) for key, value in inputs.items()}
    result = workflow(**_coerce_inputs(workflow, inputs))
    names = workflow.output_names
    if len(names) == 1:
        return _plain_inputs({names[0]: result})
    return _plain_inputs(dict(zip(names, result)))


def run_execution(execution_dir: Path, module_file_override: str = None) -> int:
    """Run one execution from its (local or store-backed) directory.

    ``module_file_override``: local path of the app module when the recorded
    ``module_file`` belongs to another machine (pod workers extract the shipped
    source zip and pass its location — see ``unionml_tpu.backend.pod_worker``).
    """
    from unionml_tpu._logging import logger
    from unionml_tpu.tracker import load_tracked_instance

    with (execution_dir / "meta.json").open() as f:
        raw = f.read()
    meta = json.loads(raw.decode() if isinstance(raw, bytes) else raw)
    if module_file_override:
        meta["module_file"] = module_file_override
    (execution_dir / "status").write_text("RUNNING")

    host_index = 0
    try:
        primary = True
        resources = meta.get("resources") or {}
        if (resources.get("host_count") or 1) > 1:
            import jax

            from unionml_tpu.parallel.distributed import initialize_distributed

            # strict: a silent single-process fallback would run N uncoordinated
            # copies of the job, each believing it is primary
            initialize_distributed(strict=True)
            primary = jax.process_index() == 0
            host_index = jax.process_index()

        model = load_tracked_instance(meta["app_module"], meta["app_variable"], meta.get("module_file"))
        with (execution_dir / "inputs.pkl").open("rb") as f:
            inputs = pickle.load(f)
        outputs = run_workflow_for_model(model, meta["workflow_name"], inputs)
        # every host runs the SPMD body; only host 0 owns outputs and terminal status
        if primary:
            with (execution_dir / "outputs.pkl").open("wb") as f:
                pickle.dump(outputs, f)
            (execution_dir / "status").write_text("SUCCEEDED")
        return 0
    except Exception as exc:  # record failure for the waiting client
        logger.exception("Worker failed for execution %s", meta.get("execution_id"))
        (execution_dir / f"error-host{host_index}.txt").write_text(repr(exc))
        status_file = execution_dir / "status"
        # never demote a completed job: host 0 may have already written SUCCEEDED
        # before a secondary host failed post-hoc
        if not (status_file.exists() and status_file.read_text().strip() == "SUCCEEDED"):
            (execution_dir / "error.txt").write_text(repr(exc))
            status_file.write_text("FAILED")
        return 1


def main() -> None:
    if len(sys.argv) != 2:
        print("usage: python -m unionml_tpu.backend.worker <execution_dir>", file=sys.stderr)
        raise SystemExit(2)
    raise SystemExit(run_execution(Path(sys.argv[1])))


if __name__ == "__main__":
    main()
