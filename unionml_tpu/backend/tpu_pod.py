"""TPU pod fleet backend: the real remote-execution target.

Reference parity: the reference deploys by building/pushing a docker image and
registering workflows against a running Flyte admin (``unionml/remote.py:71-161``),
then executes in remote containers. The TPU-native deployment story has no image
build — TPU VMs come with the framework installed (the ``Dockerfile`` at the repo
root is the pod image) — so "deploy" means:

1. package the APP source (the user's module) into the artifact store
   (:mod:`unionml_tpu.backend.store` — GCS for real fleets), and
2. record the workflow spec + TPU resources in the same store.

"Execute" writes the job record to the store and launches one
:mod:`unionml_tpu.backend.pod_worker` per host through a :class:`Transport`:

- :class:`SSHTransport` — real TPU VM fleets (``gcloud compute tpus tpu-vm ssh``
  style; plain ``ssh`` here). Workers pull the job + source from the store, join one
  ``jax.distributed`` mesh (coordinator = host 0), run the workflow SPMD, and host 0
  pushes outputs/status back to the store.
- :class:`LocalShellTransport` — the loopback stand-in: identical command, local
  subprocesses. This is what the backend-contract tests run against, faking exactly
  (and only) the machine boundary.

All lineage/schedule/retry semantics are inherited from
:class:`~unionml_tpu.backend.LocalBackend` — the records simply live in the store,
which :class:`~unionml_tpu.backend.store.StorePath` makes path-compatible.
"""

import io
import json
import os
import posixpath
import shlex
import subprocess
import sys
import tempfile
import zipfile
from pathlib import Path
from typing import Any, Dict, List, Optional, Sequence, Tuple

from unionml_tpu._logging import logger
from unionml_tpu.backend import Execution, LocalBackend
from unionml_tpu.backend.store import StorePath, store_path
from unionml_tpu.exceptions import BackendError


class LocalShellTransport:
    """Loopback transport: each "host" is a local subprocess.

    The command line, env plumbing, and store round-trip are byte-identical to the
    SSH path — only the machine boundary is faked (VERDICT round-1 next-step #4).
    """

    def __init__(self, host_count: int = 1, scratch: Optional[str] = None):
        self.hosts = [f"loopback-{i}" for i in range(host_count)]
        self.python = sys.executable  # workers run on this machine
        self.coordinator_port: Optional[int] = None  # pick a free local port per job
        self._scratch = scratch or tempfile.mkdtemp(prefix="unionml-pod-")

    def start(self, host_index: int, args: Sequence[str], env: Dict[str, str], log_name: str):
        log_path = Path(self._scratch) / log_name
        log_path.parent.mkdir(parents=True, exist_ok=True)
        with log_path.open("w") as log_file:
            process = subprocess.Popen(
                list(args),
                stdout=log_file,
                stderr=subprocess.STDOUT,
                env={**os.environ, **env},
                cwd=self._scratch,
            )
        return process

    def poll(self, handle) -> Optional[int]:
        return handle.poll()

    def terminate(self, handle, timeout: float = 5.0) -> None:
        if handle.poll() is None:
            handle.terminate()
            try:
                handle.wait(timeout=timeout)
            except subprocess.TimeoutExpired:
                handle.kill()
                handle.wait()


class SSHTransport:
    """SSH transport to a TPU VM fleet (one address per host).

    Commands launch detached under ``nohup``; liveness is a ``kill -0`` probe. The
    remote machines must have the framework installed and store credentials available
    (standard TPU VM + GCS service-account setup).
    """

    def __init__(
        self,
        hosts: Sequence[str],
        ssh_options: Sequence[str] = ("-o", "BatchMode=yes"),
        python: str = "python3",
        coordinator_port: int = 8476,
    ):
        """
        :param python: interpreter path ON THE REMOTE HOSTS (the client's
            ``sys.executable`` is meaningless there).
        :param coordinator_port: fixed ``jax.distributed`` coordinator port on host 0
            — client-side free-port probing says nothing about the remote machine.
        """
        if not hosts:
            raise BackendError("SSHTransport requires at least one host address")
        self.hosts = list(hosts)
        self.ssh_options = list(ssh_options)
        self.python = python
        self.coordinator_port: Optional[int] = coordinator_port

    def _ssh(self, host: str, remote_command: str) -> subprocess.CompletedProcess:
        return subprocess.run(
            ["ssh", *self.ssh_options, host, remote_command],
            capture_output=True,
            text=True,
            timeout=120,
        )

    def start(self, host_index: int, args: Sequence[str], env: Dict[str, str], log_name: str):
        host = self.hosts[host_index]
        env_prefix = " ".join(f"{k}={shlex.quote(v)}" for k, v in env.items())
        command = " ".join(shlex.quote(a) for a in args)
        remote = f"{env_prefix} nohup {command} > /tmp/{shlex.quote(log_name)} 2>&1 & echo $!"
        result = self._ssh(host, remote)
        if result.returncode != 0:
            raise BackendError(f"ssh launch on {host} failed: {result.stderr.strip()}")
        return (host, int(result.stdout.strip().splitlines()[-1]))

    def poll(self, handle) -> Optional[int]:
        host, pid = handle
        try:
            result = self._ssh(host, f"kill -0 {pid} 2>/dev/null && echo RUNNING || echo DEAD")
        except (subprocess.TimeoutExpired, OSError) as exc:
            logger.warning("ssh poll to %s failed (%s); treating worker as alive.", host, exc)
            return None
        if result.returncode != 0:
            # transient ssh/network failure is NOT evidence of worker death: a
            # false 'dead' here would tear down a healthy multi-hour fleet.
            # Terminal truth comes from the status file in the store.
            logger.warning(
                "ssh poll to %s returned rc=%d (%s); treating worker as alive.",
                host,
                result.returncode,
                result.stderr.strip(),
            )
            return None
        if "RUNNING" in result.stdout:
            return None
        return 0  # exited; terminal status comes from the store, not the exit code

    def terminate(self, handle, timeout: float = 5.0) -> None:
        host, pid = handle
        self._ssh(host, f"kill {pid} 2>/dev/null; sleep 1; kill -9 {pid} 2>/dev/null; true")


class TPUPodBackend(LocalBackend):
    """Execution backend targeting a TPU VM fleet through a transport + artifact store.

    Implements the full :class:`LocalBackend` protocol (deploy / execute / wait /
    lineage / schedules / retries); state lives in the fsspec store so the client and
    every pod host share one view.
    """

    def __init__(
        self,
        store_url: str,
        transport: Any = None,
        project: Optional[str] = None,
        domain: Optional[str] = None,
        retries: int = 0,
    ):
        self.store_url = store_url
        self.transport = transport or LocalShellTransport()
        self.root = store_path(store_url)
        self.default_project = project or "default-project"
        self.default_domain = domain or "development"
        self.in_process = False
        self.retries = retries
        self._workers: Dict[str, List[Any]] = {}
        self._owned: set = set()
        self._base.mkdir(parents=True, exist_ok=True)

    # ---------------------------------------------------------------- source packaging

    def _source_zip(self, app_version: str) -> StorePath:
        return self._apps_dir / app_version / "source.zip"

    def package_source(self, model: Any, app_version: str) -> Optional[StorePath]:
        """Zip the app's source (module file, or its whole package) into the store.

        The analogue of the reference's fast/"patch" registration zip upload
        (``unionml/remote.py:137-152``): only APP code ships — the framework itself
        is part of the pod image.
        """
        module_file = getattr(model, "_module_file", None)
        if not module_file or not os.path.exists(module_file):
            return None
        module_path = Path(module_file).resolve()
        buffer = io.BytesIO()
        with zipfile.ZipFile(buffer, "w", zipfile.ZIP_DEFLATED) as zf:
            if (module_path.parent / "__init__.py").exists():
                # packaged app: ship the whole top-level package so relative imports
                # survive; base = the directory containing the topmost package
                top = module_path.parent
                while (top.parent / "__init__.py").exists():
                    top = top.parent
                base = top.parent
                for path in sorted(top.rglob("*.py")):
                    zf.write(path, path.relative_to(base))
                rel_module = str(module_path.relative_to(base))
            else:
                zf.write(module_path, module_path.name)
                rel_module = module_path.name
            zf.writestr("__unionml_source__.json", json.dumps({"module_file": rel_module}))
        target = self._source_zip(app_version)
        target.write_bytes(buffer.getvalue())
        logger.info("Packaged app source for version %s (%d bytes)", app_version, buffer.tell())
        return target

    def deploy_workflow(self, model: Any, workflow_name: str, app_version: str, patch: bool = False) -> None:
        super().deploy_workflow(model, workflow_name, app_version, patch=patch)
        # ALWAYS repackage: re-deploying changed app code under the same version
        # (the reference's patch/fast-registration flow) must ship the new source,
        # never a stale zip
        self.package_source(model, app_version)

    def execute(self, model: Any, workflow_name: str, inputs: Dict[str, Any], app_version: Optional[str] = None, schedule_name: Optional[str] = None) -> Execution:
        # dev convenience parity with LocalBackend: undeployed runs package on the
        # fly — under the SAME version the execution's meta will record (the spec's
        # version when deployed, the "dev" fallback otherwise), so _spawn_worker
        # always finds the zip it looks up
        try:
            spec = self.fetch_workflow_spec(workflow_name, app_version)
            version = spec.get("app_version") or "dev"
        except BackendError:
            version = app_version or "dev"
        if not self._source_zip(version).exists():
            self.package_source(model, version)
        return super().execute(model, workflow_name, inputs, app_version=app_version, schedule_name=schedule_name)

    # ---------------------------------------------------------------- worker dispatch

    def _spawn_worker(self, execution: Execution) -> None:
        meta = execution.metadata
        resources = meta.get("resources") or {}
        host_count = int(resources.get("host_count", 1) or 1)
        if host_count > len(self.transport.hosts):
            raise BackendError(
                f"Job requests host_count={host_count} but the transport has "
                f"{len(self.transport.hosts)} host(s)"
            )
        version = meta.get("app_version") or "dev"
        source = self._source_zip(version)
        source_url = str(source) if source.exists() else ""

        coordinator = ""
        if host_count > 1:
            # host 0's address; loopback uses 127.0.0.1 + a locally-probed port,
            # SSH fleets use the transport's fixed coordinator port (a client-side
            # free-port probe says nothing about the remote machine)
            host0 = self.transport.hosts[0]
            address = "127.0.0.1" if host0.startswith("loopback") else host0.split("@")[-1]
            port = getattr(self.transport, "coordinator_port", None)
            if port is None:
                from unionml_tpu.utils import pick_free_port

                port = pick_free_port()
            coordinator = f"{address}:{port}"

        fleet = []
        for host in range(host_count):
            args = [
                getattr(self.transport, "python", sys.executable),
                "-m",
                "unionml_tpu.backend.pod_worker",
                str(execution.directory),
            ]
            if source_url:
                args += ["--source", source_url]
            env = {"UNIONML_POD_HOST_INDEX": str(host)}
            if coordinator:
                env.update(
                    JAX_COORDINATOR_ADDRESS=coordinator,
                    JAX_NUM_PROCESSES=str(host_count),
                    JAX_PROCESS_ID=str(host),
                )
            handle = self.transport.start(host, args, env, log_name=f"{execution.id}-host{host}.log")
            fleet.append(handle)
        self._workers[execution.id] = fleet
        # pod pids are per-remote-host; record the fleet for observability
        (execution.directory / "fleet.json").write_text(
            json.dumps({"hosts": self.transport.hosts[:host_count], "coordinator": coordinator})
        )

    def _terminate_workers(self, execution_id: str, timeout: float = 5.0) -> None:
        for handle in self._workers.pop(execution_id, []):
            self.transport.terminate(handle, timeout=timeout)

    def _reap_dead_worker(self, execution: Execution) -> None:
        fleet = self._workers.get(execution.id)
        if fleet is None:
            return  # not ours: status comes from the store alone
        polls = [self.transport.poll(handle) for handle in fleet]
        if all(p is None for p in polls):
            return
        if any(p is None for p in polls):
            logger.warning("Execution %s: a pod worker died; terminating the fleet.", execution.id)
            self._terminate_workers(execution.id)
        else:
            self._workers.pop(execution.id, None)
        if not execution.is_done:
            (execution.directory / "error.txt").write_text(
                "Pod worker exited without reporting a status (killed or crashed)."
            )
            (execution.directory / "status").write_text("FAILED")


def parse_pod_target(target: str) -> Tuple[Any, Dict[str, str]]:
    """Parse a ``tpu-pod://`` backend target.

    Forms::

        tpu-pod://local?store=file:///tmp/store&hosts=4   -> loopback transport
        tpu-pod://host1,host2?store=gs://bucket/prefix    -> SSH transport

    Returns ``(transport, options)`` where options includes the ``store`` URL.
    """
    from urllib.parse import parse_qs, urlsplit

    parts = urlsplit(target)
    if parts.scheme != "tpu-pod":
        raise BackendError(f"Not a tpu-pod target: {target!r}")
    options = {k: v[0] for k, v in parse_qs(parts.query).items()}
    if "store" not in options:
        raise BackendError("tpu-pod targets require a ?store=<fsspec-url> parameter")
    hosts = [h for h in (parts.netloc or "").split(",") if h]
    if hosts == ["local"] or not hosts:
        transport = LocalShellTransport(host_count=int(options.get("hosts", "1")))
    else:
        transport = SSHTransport(hosts)
    return transport, options
