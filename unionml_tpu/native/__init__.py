"""Native runtime components (C++), consumed via ctypes.

The shared library builds lazily on first use with the system toolchain (g++); when no
compiler is available the callers fall back to the pure-Python path, so the framework
never hard-depends on the native build.
"""

import ctypes
import os
import shutil
import subprocess
import threading
import time
from pathlib import Path
from typing import Any, Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from unionml_tpu._logging import logger

_SOURCES = (
    Path(__file__).parent / "prefetch.cpp",
    Path(__file__).parent / "pack.cpp",
)
_LIB_NAME = "libunionml_prefetch.so"
_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_build_failed = False

#: worker-side dtype conversions (mirrors the Conv enum in prefetch.cpp):
#: source dtype -> (code, destination numpy dtype)
_CONV_CODES = {
    "float64->float32": 1,
    "int64->int32": 2,
    "float32->bfloat16": 3,
}


def _build_dir() -> Path:
    return Path(os.getenv("UNIONML_TPU_HOME", Path.home() / ".unionml-tpu")) / "native"


def _compile(lib_path: Path) -> None:
    """Compile every native source into ``lib_path`` with the system toolchain."""
    lib_path.parent.mkdir(parents=True, exist_ok=True)
    subprocess.run(
        [
            "g++",
            "-O3",
            "-shared",
            "-fPIC",
            "-pthread",
            "-std=c++17",
            *[str(src) for src in _SOURCES],
            "-o",
            str(lib_path),
        ],
        check=True,
        capture_output=True,
    )
    logger.info("Built native prefetcher -> %s", lib_path)


def _build_and_load(lib_path: Path) -> ctypes.CDLL:
    """Compile (when stale/missing) and dlopen the native library.

    Raises ``subprocess.CalledProcessError`` / ``OSError`` on toolchain or
    loader failure — the caller decides the fallback policy.
    """
    newest_src = max(src.stat().st_mtime for src in _SOURCES)
    if not lib_path.exists() or lib_path.stat().st_mtime < newest_src:
        _compile(lib_path)
    return ctypes.CDLL(str(lib_path))


def _rebuild_and_load_fresh(lib_path: Path) -> ctypes.CDLL:
    """Replace a bad cached library and dlopen the REBUILT code in this process.

    The canonical path gets the fresh build (future processes load it normally),
    but glibc dedupes ``dlopen`` by pathname — reopening ``lib_path`` here would
    hand back the stale mapping we are replacing — so this process maps the
    healed build through a unique alias (unlinked immediately; the mapping
    outlives the name).
    """
    lib_path.unlink(missing_ok=True)
    _compile(lib_path)
    alias = lib_path.with_name(f"{lib_path.stem}.heal-{os.getpid()}-{time.monotonic_ns()}.so")
    try:
        shutil.copy2(lib_path, alias)
        return ctypes.CDLL(str(alias))
    finally:
        alias.unlink(missing_ok=True)


def load_native_library() -> Optional[ctypes.CDLL]:
    """Build (once) and load the native library; None when unavailable."""
    global _lib, _build_failed
    with _lock:
        if _lib is not None or _build_failed:
            return _lib
        lib_path = _build_dir() / _LIB_NAME
        try:
            # graftlint: disable=lock-order -- the lock intentionally serializes the ONE-TIME g++ build: concurrent first callers must wait for the compile rather than race it; every later call returns the cached handle without blocking
            lib = _build_and_load(lib_path)
        except (subprocess.CalledProcessError, OSError, FileNotFoundError) as exc:
            detail = getattr(exc, "stderr", b"")
            logger.warning(
                "Native prefetcher unavailable (%s %s); falling back to Python batching.",
                exc,
                detail.decode(errors="replace")[:500] if isinstance(detail, bytes) else detail,
            )
            _build_failed = True
            return None

        for attempt in (0, 1):
            try:
                _bind_symbols(lib)
                break
            except AttributeError as exc:
                # a stale cached library from an older package version can lack
                # newer symbols while carrying a fresher mtime than the sources
                # (e.g. a reinstalled wheel). Self-heal: delete the cache and
                # rebuild from the current sources ONCE before giving up.
                if attempt == 0:
                    logger.warning(
                        "Native library at %s is missing symbols (%s); rebuilding from source.",
                        lib_path,
                        exc,
                    )
                    try:
                        # graftlint: disable=lock-order -- same one-time-build serialization as above: the stale-cache self-heal rebuild must also complete before any caller proceeds
                        lib = _rebuild_and_load_fresh(lib_path)
                        continue
                    except (subprocess.CalledProcessError, OSError, FileNotFoundError) as build_exc:
                        logger.warning(
                            "Native rebuild failed (%s); falling back to Python.", build_exc
                        )
                else:
                    logger.warning(
                        "Rebuilt native library still missing symbols (%s); falling back to "
                        "Python. Delete %s to force another rebuild.",
                        exc,
                        lib_path,
                    )
                _build_failed = True
                return None
        _lib = lib
        return _lib


def _bind_symbols(lib: ctypes.CDLL) -> None:
    """Declare every C-ABI signature; AttributeError if any symbol is absent."""
    lib.upf_create.restype = ctypes.c_void_p
    lib.upf_create.argtypes = [
        ctypes.POINTER(ctypes.c_void_p),
        ctypes.POINTER(ctypes.c_long),
        ctypes.POINTER(ctypes.c_long),
        ctypes.POINTER(ctypes.c_long),
        ctypes.c_long,
        ctypes.c_long,
    ]
    lib.upf_start.argtypes = [
        ctypes.c_void_p,
        ctypes.POINTER(ctypes.c_long),
        ctypes.c_long,
        ctypes.c_long,
        ctypes.c_long,
        ctypes.c_long,
        ctypes.POINTER(ctypes.c_void_p),
    ]
    lib.upf_next.restype = ctypes.c_long
    lib.upf_next.argtypes = [ctypes.c_void_p]
    lib.upf_release.argtypes = [ctypes.c_void_p, ctypes.c_long]
    lib.upf_destroy.argtypes = [ctypes.c_void_p]
    lib.upk_pack.restype = ctypes.c_longlong
    lib.upk_pack.argtypes = [
        ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_int64),
        ctypes.c_longlong,
        ctypes.c_longlong,
        ctypes.c_int32,
        ctypes.c_longlong,
        ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_int32),
    ]
    lib.upk_count_rows.restype = ctypes.c_longlong
    lib.upk_count_rows.argtypes = [
        ctypes.POINTER(ctypes.c_int64),
        ctypes.c_longlong,
        ctypes.c_longlong,
        ctypes.c_longlong,
    ]


def pack_sequences_native(
    flat_tokens: np.ndarray,
    lengths: np.ndarray,
    seq_len: int,
    pad_id: int,
    max_segments_per_row: int,
) -> Optional[Dict[str, np.ndarray]]:
    """First-fit packing through the native library; None when it is unavailable.

    Inputs are pre-normalized by :func:`unionml_tpu.ops.packing.pack_sequences`
    (empties filtered, overlong sequences truncated, tokens concatenated); the
    wrapper re-checks that ``lengths`` sums to ``flat_tokens.size`` (the C side
    walks the buffer unchecked) and runs the two-pass protocol: count rows,
    allocate exact outputs, pack. Output arrays are byte-identical to the
    Python path's.
    """
    lib = load_native_library()
    if lib is None:
        return None
    flat_tokens = np.ascontiguousarray(flat_tokens, dtype=np.int32)
    lengths = np.ascontiguousarray(lengths, dtype=np.int64)
    if int(lengths.sum()) != flat_tokens.size:
        # the C side walks flat_tokens by the cumulative lengths with no bounds
        # check of its own; a short buffer would be an out-of-bounds READ in
        # upk_pack, so reject the call here and let the Python path (which
        # indexes safely) surface whatever is wrong with the inputs
        logger.warning(
            "Native packer input mismatch: lengths sum to %d but flat_tokens has %d "
            "tokens; using the Python path.",
            int(lengths.sum()),
            flat_tokens.size,
        )
        return None
    n_seqs = int(lengths.size)
    lengths_ptr = lengths.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))
    # two-pass protocol: count rows first, allocate EXACT outputs — a
    # worst-case (n_seqs, seq_len) x3 allocation is multi-GB at the corpus
    # scales this packer exists for. The count runs the identical first-fit
    # loop, so upk_pack fills exactly n_rows rows.
    n_rows = lib.upk_count_rows(lengths_ptr, n_seqs, seq_len, max_segments_per_row)
    if n_rows < 0:
        logger.warning("Native packer rejected inputs (rc=%d); using the Python path.", n_rows)
        return None
    input_ids = np.empty((n_rows, seq_len), dtype=np.int32)
    segment_ids = np.empty((n_rows, seq_len), dtype=np.int32)
    positions = np.empty((n_rows, seq_len), dtype=np.int32)
    as_i32 = lambda a: a.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))
    packed_rows = lib.upk_pack(
        as_i32(flat_tokens),
        lengths_ptr,
        n_seqs,
        seq_len,
        pad_id,
        max_segments_per_row,
        as_i32(input_ids),
        as_i32(segment_ids),
        as_i32(positions),
    )
    if packed_rows != n_rows:  # defensive: the two passes must agree exactly
        logger.warning(
            "Native packer row-count mismatch (%d vs %d); using the Python path.",
            packed_rows, n_rows,
        )
        return None
    return {
        "input_ids": input_ids,
        "segment_ids": segment_ids,
        "positions": positions,
    }


def native_available() -> bool:
    return load_native_library() is not None


def _bfloat16_dtype():
    import ml_dtypes

    return np.dtype(ml_dtypes.bfloat16)


def _resolve_conversion(array: np.ndarray, target: Optional[str]) -> Tuple[int, np.dtype]:
    """(conv code, destination dtype) for one source array."""
    if target is None:
        return 0, array.dtype
    target_dtype = _bfloat16_dtype() if target == "bfloat16" else np.dtype(target)
    if target_dtype == array.dtype:
        return 0, array.dtype  # no-op conversion request: plain gather
    key = f"{array.dtype.name}->{target}"
    code = _CONV_CODES.get(key)
    if code is None:
        raise ValueError(
            f"Unsupported native conversion {key!r}; supported: {sorted(_CONV_CODES)}"
        )
    return code, target_dtype


class PrefetchLoader:
    """Iterate dict batches gathered by the native threaded prefetcher.

    Wraps a mapping of name -> contiguous host array; each epoch yields dict batches
    in shuffled order with gathering overlapped against the consumer's compute.

    Round-2 hot-path upgrades (NEXT.md item 6):

    - Slot buffers are numpy arrays OWNED BY PYTHON; the C++ workers gather straight
      into them, so ``copy=False`` consumers hand the batch to ``jax.device_put``
      with zero additional host copies. The slot recycles only after the generator
      resumes — block on the transfer before advancing (``fit`` does).
    - ``convert={"name": "float32" | "int32" | "bfloat16"}`` runs the dtype
      conversion inside the worker threads (f64->f32, i64->i32, f32->bf16 with
      round-to-nearest-even) — the Python side never pays element-wise conversion.

    Falls back to pure-Python batching when the native library can't build.
    """

    def __init__(
        self,
        data: Dict[str, np.ndarray],
        batch_size: int,
        *,
        n_slots: int = 4,
        n_threads: int = 2,
        drop_remainder: bool = True,
        convert: Optional[Dict[str, str]] = None,
    ):
        self._keys = list(data)
        self._arrays = [np.ascontiguousarray(np.asarray(data[k])) for k in self._keys]
        n_rows = {a.shape[0] for a in self._arrays}
        if len(n_rows) != 1:
            raise ValueError(f"All arrays must share the leading dimension; got {n_rows}")
        self.n_rows = n_rows.pop()
        self.batch_size = batch_size
        self.n_slots = n_slots
        self.n_threads = n_threads
        self.drop_remainder = drop_remainder

        convert = convert or {}
        unknown = set(convert) - set(self._keys)
        if unknown:
            raise ValueError(f"convert refers to unknown arrays: {sorted(unknown)}")
        self._conv_codes: List[int] = []
        self._dst_dtypes: List[np.dtype] = []
        for key, array in zip(self._keys, self._arrays):
            code, dst = _resolve_conversion(array, convert.get(key))
            self._conv_codes.append(code)
            self._dst_dtypes.append(dst)

        self._lib = load_native_library()
        self._handle = None
        self._slot_arrays: List[List[np.ndarray]] = []
        self._slot_ptr_table = None
        if self._lib is not None:
            n = len(self._arrays)
            sources = (ctypes.c_void_p * n)(
                *[a.ctypes.data_as(ctypes.c_void_p).value for a in self._arrays]
            )
            row_bytes = (ctypes.c_long * n)(*[a.strides[0] for a in self._arrays])
            dst_row_bytes = (ctypes.c_long * n)(*self._dst_row_bytes())
            conv_codes = (ctypes.c_long * n)(*self._conv_codes)
            self._handle = self._lib.upf_create(
                sources, row_bytes, conv_codes, dst_row_bytes, n, self.n_rows
            )
            self._allocate_slots()

    def _dst_row_bytes(self) -> List[int]:
        out = []
        for array, dst in zip(self._arrays, self._dst_dtypes):
            row_elems = int(np.prod(array.shape[1:], dtype=np.int64)) if array.ndim > 1 else 1
            out.append(row_elems * dst.itemsize)
        return out

    def _allocate_slots(self) -> None:
        """Python-owned destination buffers: [n_slots][n_arrays] numpy arrays."""
        self._slot_arrays = []
        pointers = []
        for _ in range(self.n_slots):
            slot = []
            for array, dst in zip(self._arrays, self._dst_dtypes):
                buf = np.empty((self.batch_size,) + array.shape[1:], dtype=dst)
                slot.append(buf)
                pointers.append(buf.ctypes.data_as(ctypes.c_void_p).value)
            self._slot_arrays.append(slot)
        self._slot_ptr_table = (ctypes.c_void_p * len(pointers))(*pointers)

    @property
    def uses_native(self) -> bool:
        return self._handle is not None

    def _python_batch(self, idx: np.ndarray) -> Dict[str, np.ndarray]:
        out = {}
        for key, array, dst in zip(self._keys, self._arrays, self._dst_dtypes):
            gathered = array[idx]
            out[key] = gathered.astype(dst) if dst != array.dtype else gathered
        return out

    def epoch(
        self,
        rng: Optional[np.random.Generator] = None,
        copy: bool = True,
        defer_release: bool = False,
    ) -> Iterator[Any]:
        """Yield one epoch of dict batches in shuffled order.

        ``copy=True`` (default) yields loader-independent arrays: safe for any
        consumer, including fully-async device transfers. ``copy=False`` yields the
        python-owned slot arrays themselves — ZERO host copies after the worker
        gather — which recycle after the generator resumes: the consumer must finish
        reading (e.g. a ``hard_sync`` on the device transfer) inside the loop body.

        ``defer_release=True`` yields ``(views, release)`` pairs instead: the slot
        is recycled only when ``release()`` is called, so a consumer may hold a
        batch (e.g. an in-flight device transfer) while pulling the next one —
        the transfer-overlap lookahead ``fit()`` uses. Releases should happen in
        yield order; holding more than ``n_slots - 1`` unreleased batches stalls
        the gather workers.
        """
        indices = np.arange(self.n_rows, dtype=np.int64) if rng is None else rng.permutation(self.n_rows).astype(np.int64)
        # the native path only ever gathers FULL batches (its buffers are fixed-size);
        # a ragged tail is yielded via the python gather below, preserving true-batch
        # semantics with drop_remainder=False
        n_full = self.n_rows // self.batch_size
        remainder = self.n_rows - n_full * self.batch_size

        def emit(views, release=None):
            # python-gathered batches are fresh arrays: release is a no-op
            return (views, release or (lambda: None)) if defer_release else views

        def tail_batches():
            if not self.drop_remainder and remainder:
                yield emit(self._python_batch(indices[n_full * self.batch_size :]))
            elif n_full == 0:
                # degenerate tiny datasets always yield their one true batch
                yield emit(self._python_batch(indices))

        if self._handle is None or n_full == 0:
            for b in range(n_full):
                yield emit(self._python_batch(indices[b * self.batch_size : (b + 1) * self.batch_size]))
            yield from tail_batches()
            return

        n_batches = n_full
        indices_c = np.ascontiguousarray(indices[: n_batches * self.batch_size])
        self._lib.upf_start(
            self._handle,
            indices_c.ctypes.data_as(ctypes.POINTER(ctypes.c_long)),
            n_batches,
            self.batch_size,
            self.n_slots,
            self.n_threads,
            self._slot_ptr_table,
        )
        try:
            while True:
                batch = self._lib.upf_next(self._handle)
                if batch < 0:
                    break
                slot = self._slot_arrays[batch % self.n_slots]
                views = {
                    key: (np.array(buf) if copy else buf)
                    for key, buf in zip(self._keys, slot)
                }
                if defer_release:
                    released = [False]

                    def release(b=batch, flag=released):
                        if not flag[0] and self._handle is not None:
                            flag[0] = True
                            self._lib.upf_release(self._handle, b)

                    yield views, release
                else:
                    yield views
                    self._lib.upf_release(self._handle, batch)
            yield from tail_batches()
        finally:
            del indices_c

    def close(self) -> None:
        if self._handle is not None and self._lib is not None:
            self._lib.upf_destroy(self._handle)
            self._handle = None

    def __del__(self):  # pragma: no cover - best effort
        try:
            self.close()
        except Exception:  # best-effort-release shape: recognized by the lint
            pass
