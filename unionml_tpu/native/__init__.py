"""Native runtime components (C++), consumed via ctypes.

The shared library builds lazily on first use with the system toolchain (g++); when no
compiler is available the callers fall back to the pure-Python path, so the framework
never hard-depends on the native build.
"""

import ctypes
import os
import subprocess
import threading
from pathlib import Path
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from unionml_tpu._logging import logger

_SOURCE = Path(__file__).parent / "prefetch.cpp"
_LIB_NAME = "libunionml_prefetch.so"
_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_build_failed = False


def _build_dir() -> Path:
    return Path(os.getenv("UNIONML_TPU_HOME", Path.home() / ".unionml-tpu")) / "native"


def load_native_library() -> Optional[ctypes.CDLL]:
    """Build (once) and load the native library; None when unavailable."""
    global _lib, _build_failed
    with _lock:
        if _lib is not None or _build_failed:
            return _lib
        lib_path = _build_dir() / _LIB_NAME
        try:
            if not lib_path.exists() or lib_path.stat().st_mtime < _SOURCE.stat().st_mtime:
                lib_path.parent.mkdir(parents=True, exist_ok=True)
                subprocess.run(
                    [
                        "g++",
                        "-O3",
                        "-shared",
                        "-fPIC",
                        "-pthread",
                        "-std=c++17",
                        str(_SOURCE),
                        "-o",
                        str(lib_path),
                    ],
                    check=True,
                    capture_output=True,
                )
                logger.info("Built native prefetcher -> %s", lib_path)
            lib = ctypes.CDLL(str(lib_path))
        except (subprocess.CalledProcessError, OSError, FileNotFoundError) as exc:
            detail = getattr(exc, "stderr", b"")
            logger.warning(
                "Native prefetcher unavailable (%s %s); falling back to Python batching.",
                exc,
                detail.decode(errors="replace")[:500] if isinstance(detail, bytes) else detail,
            )
            _build_failed = True
            return None

        lib.upf_create.restype = ctypes.c_void_p
        lib.upf_create.argtypes = [
            ctypes.POINTER(ctypes.c_void_p),
            ctypes.POINTER(ctypes.c_long),
            ctypes.c_long,
            ctypes.c_long,
        ]
        lib.upf_start.argtypes = [
            ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_long),
            ctypes.c_long,
            ctypes.c_long,
            ctypes.c_long,
            ctypes.c_long,
        ]
        lib.upf_next.restype = ctypes.c_long
        lib.upf_next.argtypes = [ctypes.c_void_p, ctypes.POINTER(ctypes.c_void_p)]
        lib.upf_release.argtypes = [ctypes.c_void_p, ctypes.c_long]
        lib.upf_destroy.argtypes = [ctypes.c_void_p]
        _lib = lib
        return _lib


def native_available() -> bool:
    return load_native_library() is not None


class PrefetchLoader:
    """Iterate dict batches gathered by the native threaded prefetcher.

    Wraps a mapping of name -> contiguous host array; each epoch yields dict batches
    (numpy views copied into slot buffers) in shuffled order with gathering overlapped
    against the consumer's compute. Falls back to pure-Python batching when the native
    library can't build.
    """

    def __init__(
        self,
        data: Dict[str, np.ndarray],
        batch_size: int,
        *,
        n_slots: int = 4,
        n_threads: int = 2,
        drop_remainder: bool = True,
    ):
        self._keys = list(data)
        self._arrays = [np.ascontiguousarray(np.asarray(data[k])) for k in self._keys]
        n_rows = {a.shape[0] for a in self._arrays}
        if len(n_rows) != 1:
            raise ValueError(f"All arrays must share the leading dimension; got {n_rows}")
        self.n_rows = n_rows.pop()
        self.batch_size = batch_size
        self.n_slots = n_slots
        self.n_threads = n_threads
        self.drop_remainder = drop_remainder

        self._lib = load_native_library()
        self._handle = None
        if self._lib is not None:
            n = len(self._arrays)
            sources = (ctypes.c_void_p * n)(*[a.ctypes.data_as(ctypes.c_void_p).value for a in self._arrays])
            row_bytes = (ctypes.c_long * n)(*[a.strides[0] for a in self._arrays])
            self._handle = self._lib.upf_create(sources, row_bytes, n, self.n_rows)

    @property
    def uses_native(self) -> bool:
        return self._handle is not None

    def epoch(
        self, rng: Optional[np.random.Generator] = None, copy: bool = True
    ) -> Iterator[Dict[str, np.ndarray]]:
        """Yield one epoch of dict batches in shuffled order.

        ``copy=True`` (default) yields loader-independent arrays: safe for any
        consumer, including async device transfers — the threaded gather still
        overlaps; only a sequential memcpy remains on the consumer side.
        ``copy=False`` yields views into the slot ring that are overwritten after the
        generator resumes: only for consumers that fully read the data synchronously
        inside the loop body.
        """
        indices = np.arange(self.n_rows, dtype=np.int64) if rng is None else rng.permutation(self.n_rows).astype(np.int64)
        # the native path only ever gathers FULL batches (its buffers are fixed-size);
        # a ragged tail is yielded via the python gather below, preserving true-batch
        # semantics with drop_remainder=False
        n_full = self.n_rows // self.batch_size
        remainder = self.n_rows - n_full * self.batch_size

        def tail_batches():
            if not self.drop_remainder and remainder:
                idx = indices[n_full * self.batch_size :]
                yield {k: a[idx] for k, a in zip(self._keys, self._arrays)}
            elif n_full == 0:
                # degenerate tiny datasets always yield their one true batch
                yield {k: a[indices] for k, a in zip(self._keys, self._arrays)}

        if self._handle is None or n_full == 0:
            for b in range(n_full):
                idx = indices[b * self.batch_size : (b + 1) * self.batch_size]
                yield {k: a[idx] for k, a in zip(self._keys, self._arrays)}
            yield from tail_batches()
            return

        n_batches = n_full
        indices_c = np.ascontiguousarray(indices[: n_batches * self.batch_size])
        self._lib.upf_start(
            self._handle,
            indices_c.ctypes.data_as(ctypes.POINTER(ctypes.c_long)),
            n_batches,
            self.batch_size,
            self.n_slots,
            self.n_threads,
        )
        out_ptrs = (ctypes.c_void_p * len(self._arrays))()
        try:
            while True:
                batch = self._lib.upf_next(self._handle, out_ptrs)
                if batch < 0:
                    break
                views = {}
                for key, array, ptr in zip(self._keys, self._arrays, out_ptrs):
                    shape = (self.batch_size,) + array.shape[1:]
                    buf = (ctypes.c_uint8 * (self.batch_size * array.strides[0])).from_address(ptr)
                    view = np.frombuffer(buf, dtype=array.dtype).reshape(shape)
                    views[key] = np.array(view) if copy else view
                yield views
                self._lib.upf_release(self._handle, batch)
            yield from tail_batches()
        finally:
            del indices_c

    def close(self) -> None:
        if self._handle is not None and self._lib is not None:
            self._lib.upf_destroy(self._handle)
            self._handle = None

    def __del__(self):  # pragma: no cover - best effort
        try:
            self.close()
        except Exception:
            pass
