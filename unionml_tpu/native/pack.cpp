// Native sequence packer: the exact first-fit algorithm of
// unionml_tpu/ops/packing.py::pack_sequences, in C++.
//
// Packing is host-side input-pipeline work that runs per training job over the
// whole corpus; the Python loop is O(n_seqs * n_rows) with interpreter-speed
// constants, which at 10^5-10^6 sequences costs minutes before the first step
// reaches the chip. This implementation keeps byte-identical outputs (same
// first-fit placement in insertion order, same segment/position layout) and
// adds a per-length scan cursor: a row that once rejected length L stays
// rejected forever (free space only shrinks, segment counts only grow), so the
// scan for each length resumes where it last stopped — near-linear amortized
// for clustered length distributions, exact first-fit always. Parity is pinned
// by tests/unit/test_packing.py::test_native_packer_matches_python.
//
// C ABI (ctypes): caller pre-filters empty sequences, pre-truncates to seq_len,
// and concatenates tokens; allocation follows the TWO-PASS exact protocol —
// upk_count_rows first runs the identical first-fit placement loop to report
// the exact row count, the caller allocates exactly that many (rows, seq_len)
// output rows, then upk_pack fills them (and re-reports the row count, which
// the caller cross-checks). No worst-case allocation anywhere: (n_seqs,
// seq_len) x3 would be multi-GB at corpus scale.

#include <cstddef>
#include <cstdint>
#include <vector>

extern "C" {

// First-fit row count only (no writes): the Python wrapper calls this first
// and allocates EXACT outputs — a worst-case (n_seqs, seq_len) allocation
// would be multi-GB at the corpus scales this packer exists for. Runs the
// identical placement loop, so the subsequent upk_pack call fills exactly
// this many rows. Returns -1 on invalid arguments.
int64_t upk_count_rows(const int64_t* lengths,
                       int64_t n_seqs,
                       int64_t seq_len,
                       int64_t max_segments) {
  if (seq_len <= 0 || n_seqs < 0) return -1;
  struct Row {
    int64_t space;
    int64_t segments;
  };
  std::vector<Row> rows;
  std::vector<int64_t> scan_from(static_cast<size_t>(seq_len) + 1, 0);
  for (int64_t i = 0; i < n_seqs; ++i) {
    const int64_t len = lengths[i];
    if (len <= 0 || len > seq_len) return -1;
    int64_t placed = -1;
    int64_t r = scan_from[static_cast<size_t>(len)];
    for (; r < static_cast<int64_t>(rows.size()); ++r) {
      const Row& row = rows[static_cast<size_t>(r)];
      if (row.space >= len && (max_segments <= 0 || row.segments < max_segments)) {
        placed = r;
        break;
      }
    }
    scan_from[static_cast<size_t>(len)] = r;
    if (placed < 0) {
      rows.push_back(Row{seq_len, 0});
      placed = static_cast<int64_t>(rows.size()) - 1;
    }
    rows[static_cast<size_t>(placed)].space -= len;
    rows[static_cast<size_t>(placed)].segments += 1;
  }
  return rows.empty() ? 1 : static_cast<int64_t>(rows.size());
}

// Returns the number of rows written, or -1 on invalid arguments.
int64_t upk_pack(const int32_t* tokens,   // concatenated sequence tokens
                 const int64_t* lengths,  // per-sequence lengths, each in [1, seq_len]
                 int64_t n_seqs,
                 int64_t seq_len,
                 int32_t pad_id,
                 int64_t max_segments,    // 0 = unlimited
                 int32_t* input_ids,      // out: (max(n_seqs,1), seq_len)
                 int32_t* segment_ids,    // out: same shape
                 int32_t* positions) {    // out: same shape
  if (seq_len <= 0 || n_seqs < 0) return -1;

  struct Row {
    int64_t space;
    int64_t segments;
    int64_t offset;  // next free slot within the row
  };
  std::vector<Row> rows;
  rows.reserve(static_cast<size_t>(n_seqs));

  // scan_from[L] = first row index not yet REJECTED for length L. The reject
  // predicate (space < L, or segment cap reached) is monotone in time for a
  // fixed row, so resuming the scan here preserves exact first-fit placement.
  std::vector<int64_t> scan_from(static_cast<size_t>(seq_len) + 1, 0);

  const int32_t* cursor = tokens;
  for (int64_t i = 0; i < n_seqs; ++i) {
    const int64_t len = lengths[i];
    if (len <= 0 || len > seq_len) return -1;

    int64_t placed = -1;
    int64_t r = scan_from[static_cast<size_t>(len)];
    for (; r < static_cast<int64_t>(rows.size()); ++r) {
      const Row& row = rows[static_cast<size_t>(r)];
      if (row.space >= len && (max_segments <= 0 || row.segments < max_segments)) {
        placed = r;
        break;
      }
    }
    scan_from[static_cast<size_t>(len)] = r;  // rows before r are rejected for len, forever
    if (placed < 0) {
      rows.push_back(Row{seq_len, 0, 0});
      placed = static_cast<int64_t>(rows.size()) - 1;
    }

    Row& row = rows[static_cast<size_t>(placed)];
    int32_t* ids_out = input_ids + placed * seq_len + row.offset;
    int32_t* seg_out = segment_ids + placed * seq_len + row.offset;
    int32_t* pos_out = positions + placed * seq_len + row.offset;
    const int32_t segment = static_cast<int32_t>(row.segments + 1);
    for (int64_t t = 0; t < len; ++t) {
      ids_out[t] = cursor[t];
      seg_out[t] = segment;
      pos_out[t] = static_cast<int32_t>(t);
    }
    cursor += len;
    row.space -= len;
    row.segments += 1;
    row.offset += len;
  }

  // pad the tails of used rows (and the single all-padding row of an empty input)
  const int64_t n_rows = rows.empty() ? 1 : static_cast<int64_t>(rows.size());
  for (int64_t r = 0; r < n_rows; ++r) {
    const int64_t start =
        rows.empty() ? 0 : rows[static_cast<size_t>(r)].offset;
    for (int64_t t = start; t < seq_len; ++t) {
      input_ids[r * seq_len + t] = pad_id;
      segment_ids[r * seq_len + t] = 0;
      positions[r * seq_len + t] = 0;
    }
  }
  return n_rows;
}

}  // extern "C"
