// Native batch prefetcher: multi-threaded row gathering with double buffering.
//
// Role in the framework (SURVEY.md §7 "BERT wall-clock: the input pipeline will
// dominate unless async"): the Python training loop's per-batch work is a fancy
// gather — rows at shuffled indices copied into a contiguous batch buffer — followed
// by a host->device transfer. Doing the gather in C++ worker threads overlaps it with
// JAX dispatch and the previous step's device compute, keeping the accelerator fed.
//
// Model: N slots (ring buffer), each holding one batch's buffers for every source
// array. Worker threads claim batch indices in order, wait for their slot to free,
// gather rows, and mark the slot ready. The consumer (`upf_next`) takes batches in
// order and releases slots after the device transfer commits.
//
// Round-2 additions (NEXT.md item 6):
//  - slot buffers are owned by PYTHON (numpy arrays registered via `upf_set_buffers`),
//    so the consumer hands the gathered batch straight to jax.device_put with no
//    extra host copy; the slot is released only after the transfer commits.
//  - per-array dtype conversion runs INSIDE the worker threads during the gather:
//    float64->float32, int64->int32, and float32->bfloat16 (round-to-nearest-even),
//    so Python never pays element-wise conversion on the hot path.
//
// Build: g++ -O3 -shared -fPIC -pthread prefetch.cpp -o libunionml_prefetch.so
// (driven by unionml_tpu/native/__init__.py; pure C ABI, consumed via ctypes).

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <thread>
#include <vector>

namespace {

// conversion codes (mirrored in native/__init__.py)
enum Conv : long {
  kCopy = 0,      // raw memcpy
  kF64ToF32 = 1,  // float64 -> float32
  kI64ToI32 = 2,  // int64 -> int32
  kF32ToBf16 = 3, // float32 -> bfloat16 (round to nearest even)
};

inline uint16_t f32_to_bf16(float value) {
  uint32_t bits;
  std::memcpy(&bits, &value, sizeof(bits));
  // round-to-nearest-even on the dropped 16 bits; NaN stays NaN
  if ((bits & 0x7fffffffu) > 0x7f800000u) return (uint16_t)((bits >> 16) | 0x0040u);
  const uint32_t rounding = 0x7fffu + ((bits >> 16) & 1u);
  return (uint16_t)((bits + rounding) >> 16);
}

inline void convert_row(uint8_t* dst, const uint8_t* src, long src_bytes, long conv) {
  switch (conv) {
    case kCopy:
      std::memcpy(dst, src, (size_t)src_bytes);
      break;
    case kF64ToF32: {
      const long n = src_bytes / 8;
      const double* in = reinterpret_cast<const double*>(src);
      float* out = reinterpret_cast<float*>(dst);
      for (long i = 0; i < n; ++i) out[i] = (float)in[i];
      break;
    }
    case kI64ToI32: {
      const long n = src_bytes / 8;
      const int64_t* in = reinterpret_cast<const int64_t*>(src);
      int32_t* out = reinterpret_cast<int32_t*>(dst);
      for (long i = 0; i < n; ++i) out[i] = (int32_t)in[i];
      break;
    }
    case kF32ToBf16: {
      const long n = src_bytes / 4;
      const float* in = reinterpret_cast<const float*>(src);
      uint16_t* out = reinterpret_cast<uint16_t*>(dst);
      for (long i = 0; i < n; ++i) out[i] = f32_to_bf16(in[i]);
      break;
    }
  }
}

struct Slot {
  std::vector<uint8_t*> buffers;  // PYTHON-owned destination, one per source array
  long batch_idx = -1;            // which batch currently occupies the slot
  long next_fill = 0;             // the only batch allowed to fill next
  bool ready = false;
  bool in_use = false;
};

struct Prefetcher {
  std::vector<const uint8_t*> sources;
  std::vector<long> row_bytes;      // source row strides
  std::vector<long> conv;           // per-array conversion code
  std::vector<long> dst_row_bytes;  // destination row strides (post-conversion)
  long n_rows = 0;

  std::vector<long> indices;
  long n_batches = 0;
  long batch_size = 0;

  std::vector<Slot> slots;
  std::vector<std::thread> workers;
  std::atomic<long> next_claim{0};
  long next_deliver = 0;

  std::mutex mu;
  std::condition_variable cv_ready;   // consumer waits for ready slots
  std::condition_variable cv_free;    // workers wait for freed slots
  bool stopping = false;

  void gather(long batch) {
    Slot& slot = slots[batch % (long)slots.size()];
    {
      std::unique_lock<std::mutex> lock(mu);
      // fill strictly in per-slot order: a worker holding batch s+k*n_slots must not
      // occupy the slot before batch s+(k-1)*n_slots has been delivered + released,
      // or the in-order consumer deadlocks
      cv_free.wait(lock, [&] { return stopping || (!slot.in_use && slot.next_fill == batch); });
      if (stopping) return;
      slot.in_use = true;
      slot.batch_idx = batch;
      slot.next_fill = batch + (long)slots.size();
      slot.ready = false;
    }
    const long* batch_indices = indices.data() + batch * batch_size;
    for (size_t a = 0; a < sources.size(); ++a) {
      const long rb = row_bytes[a];
      const long drb = dst_row_bytes[a];
      const long cv = conv[a];
      uint8_t* dst = slot.buffers[a];
      const uint8_t* src = sources[a];
      for (long r = 0; r < batch_size; ++r) {
        convert_row(dst + r * drb, src + batch_indices[r] * rb, rb, cv);
      }
    }
    {
      std::lock_guard<std::mutex> lock(mu);
      slot.ready = true;
    }
    cv_ready.notify_all();
  }

  void worker_loop() {
    while (true) {
      long batch = next_claim.fetch_add(1);
      if (batch >= n_batches) return;
      gather(batch);
      {
        std::lock_guard<std::mutex> lock(mu);
        if (stopping) return;
      }
    }
  }

  void stop() {
    {
      std::lock_guard<std::mutex> lock(mu);
      stopping = true;
    }
    cv_free.notify_all();
    cv_ready.notify_all();
    for (auto& t : workers) {
      if (t.joinable()) t.join();
    }
    workers.clear();
  }
};

}  // namespace

extern "C" {

// conv_codes/dst_row_bytes describe the per-array worker-side conversion; pass
// kCopy + row_bytes[i] for raw gathering.
Prefetcher* upf_create(const void** sources, const long* row_bytes, const long* conv_codes,
                       const long* dst_row_bytes, long n_arrays, long n_rows) {
  auto* p = new Prefetcher();
  p->n_rows = n_rows;
  for (long i = 0; i < n_arrays; ++i) {
    p->sources.push_back(static_cast<const uint8_t*>(sources[i]));
    p->row_bytes.push_back(row_bytes[i]);
    p->conv.push_back(conv_codes[i]);
    p->dst_row_bytes.push_back(dst_row_bytes[i]);
  }
  return p;
}

// Begin an epoch. `indices` must stay valid until the epoch completes.
// `slot_buffers` is a row-major [n_slots][n_arrays] table of PYTHON-owned
// destination pointers (each sized batch_size * dst_row_bytes[a]); they must stay
// alive until upf_destroy or the next upf_start.
void upf_start(Prefetcher* p, const long* indices, long n_batches, long batch_size,
               long n_slots, long n_threads, void** slot_buffers) {
  p->stop();
  p->indices.assign(indices, indices + n_batches * batch_size);
  p->n_batches = n_batches;
  p->batch_size = batch_size;
  p->next_claim.store(0);
  p->next_deliver = 0;
  p->stopping = false;

  p->slots.assign((size_t)n_slots, Slot{});
  const size_t n_arrays = p->sources.size();
  for (long s = 0; s < n_slots; ++s) {
    Slot& slot = p->slots[(size_t)s];
    slot.next_fill = s;
    slot.buffers.resize(n_arrays);
    for (size_t a = 0; a < n_arrays; ++a) {
      slot.buffers[a] = static_cast<uint8_t*>(slot_buffers[s * n_arrays + a]);
    }
  }
  if (n_threads < 1) n_threads = 1;
  if (n_threads > n_slots) n_threads = n_slots;  // more would deadlock on slot waits
  for (long t = 0; t < n_threads; ++t) {
    p->workers.emplace_back([p] { p->worker_loop(); });
  }
}

// Blocks until the next in-order batch is ready. Returns the batch index (the
// consumer reads the python-owned slot buffers directly), or -1 when exhausted.
long upf_next(Prefetcher* p) {
  if (p->next_deliver >= p->n_batches) return -1;
  long batch = p->next_deliver++;
  Slot& slot = p->slots[batch % (long)p->slots.size()];
  std::unique_lock<std::mutex> lock(p->mu);
  p->cv_ready.wait(lock, [&] { return p->stopping || (slot.ready && slot.batch_idx == batch); });
  if (p->stopping) return -1;
  return batch;
}

// Release a delivered batch's slot so workers can refill it. Call only after the
// consumer no longer reads the slot buffers (e.g. the device transfer committed).
void upf_release(Prefetcher* p, long batch) {
  Slot& slot = p->slots[batch % (long)p->slots.size()];
  {
    std::lock_guard<std::mutex> lock(p->mu);
    slot.in_use = false;
    slot.ready = false;
    slot.batch_idx = -1;
  }
  p->cv_free.notify_all();
}

void upf_destroy(Prefetcher* p) {
  p->stop();
  delete p;
}

}  // extern "C"
