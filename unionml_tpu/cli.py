"""Command-line interface: ``unionml-tpu`` (click-based).

Reference parity: the typer app at ``unionml/cli.py:19-331`` — the same command set
(``init``, ``deploy``, ``activate-schedules``, ``deactivate-schedules``, ``train``,
``predict``, listings, ``fetch-model``, ``fetch-predictions``, ``serve``) plus a
``scheduler`` command running the in-framework cron loop (the reference delegates
firing to Flyte). ``serve`` hosts the native aiohttp app with the resident compiled
predictor instead of wrapping uvicorn; ``--model-path`` still lands in
``UNIONML_MODEL_PATH`` (``cli.py:285-320`` behavior).

Note: the reference's deactivate command calls ``remote_activate_schedules``
(``cli.py:124`` — an upstream bug); this implementation deactivates.
"""

import json
import os
import sys
from pathlib import Path
from typing import Optional

import click

from unionml_tpu._logging import logger


def _load_model(app: str):
    from unionml_tpu.remote import get_model

    return get_model(app)


def _parse_json_opt(value: Optional[str], flag: str) -> dict:
    if not value:
        return {}
    try:
        return json.loads(value)
    except json.JSONDecodeError as exc:
        raise click.BadParameter(f"{flag} must be valid JSON: {exc}") from exc


@click.group(name="unionml-tpu")
def app() -> None:
    """unionml-tpu: TPU-native model training, serving, and deployment."""


@app.command()
@click.argument("app_name")
@click.option(
    "--template",
    "-t",
    default="basic",
    show_default=True,
    help="Project template (see `unionml-tpu templates`).",
)
def init(app_name: str, template: str) -> None:
    """Initialize a unionml-tpu project from a template."""
    from unionml_tpu.templates import list_templates, render_template

    if template not in list_templates():
        raise click.BadParameter(f"unknown template {template!r}; available: {', '.join(list_templates())}")
    try:
        target = render_template(template, app_name, Path.cwd())
    except (ValueError, FileExistsError) as exc:
        raise click.ClickException(str(exc)) from exc
    click.echo(f"Created {target} from template {template!r}")


@app.command()
def templates() -> None:
    """List available project templates."""
    from unionml_tpu.templates import list_templates, template_description

    for name in list_templates():
        click.echo(f"{name:20s} {template_description(name)}")


@app.command()
@click.argument("app")
@click.option("--allow-uncommitted", is_flag=True, help="Deploy even with uncommitted changes.")
@click.option("--patch", is_flag=True, help="Code-only fast re-registration (no version bump of deps).")
@click.option("--schedule/--no-schedule", default=True, show_default=True, help="Deploy registered schedules.")
@click.option("--app-version", "-v", default=None, help="Explicit app version (default: git sha).")
def deploy(app: str, allow_uncommitted: bool, patch: bool, schedule: bool, app_version: Optional[str]) -> None:
    """Deploy a model app's workflows (and schedules) to the execution backend."""
    model = _load_model(app)
    version = model.remote_deploy(
        app_version=app_version, allow_uncommitted=allow_uncommitted, patch=patch, schedule=schedule
    )
    click.echo(f"Deployed app version {version}")


@app.command("activate-schedules")
@click.argument("app")
@click.option("--app-version", "-v", default=None)
@click.option("--name", "-n", "schedule_names", multiple=True, help="Schedule names (default: all).")
def activate_schedules(app: str, app_version: Optional[str], schedule_names) -> None:
    """Activate deployed schedules."""
    model = _load_model(app)
    model.remote_activate_schedules(app_version=app_version, schedule_names=list(schedule_names) or None)


@app.command("deactivate-schedules")
@click.argument("app")
@click.option("--app-version", "-v", default=None)
@click.option("--name", "-n", "schedule_names", multiple=True, help="Schedule names (default: all).")
def deactivate_schedules(app: str, app_version: Optional[str], schedule_names) -> None:
    """Deactivate deployed schedules."""
    model = _load_model(app)
    model.remote_deactivate_schedules(app_version=app_version, schedule_names=list(schedule_names) or None)


@app.command()
@click.argument("app")
@click.option("--inputs", "-i", default=None, help="JSON dict of training workflow inputs.")
@click.option("--app-version", "-v", default=None)
@click.option("--local", is_flag=True, help="Train locally in-process instead of on the backend.")
@click.option("--wait", "-w", is_flag=True, help="Wait for the remote execution to complete.")
@click.option("--profile-dir", default=None, help="Capture an xprof trace + stage timings into this directory (local mode).")
def train(
    app: str,
    inputs: Optional[str],
    app_version: Optional[str],
    local: bool,
    wait: bool,
    profile_dir: Optional[str],
) -> None:
    """Run a training job (remote by default, local with --local)."""
    model = _load_model(app)
    parsed = _parse_json_opt(inputs, "--inputs")
    if local:
        if profile_dir:
            from unionml_tpu.profiling import workflow_timings, xprof_trace

            with xprof_trace(profile_dir):
                _, metrics = model.train(**parsed)
            timings = workflow_timings(model.train_workflow())
            click.echo(json.dumps({"metrics": metrics, "stage_timings_s": timings}, default=str))
            return
        _, metrics = model.train(**parsed)
        click.echo(json.dumps({"metrics": metrics}, default=str))
        return
    result = model.remote_train(app_version=app_version, wait=wait, **parsed)
    if wait:
        click.echo(json.dumps({"metrics": result.metrics}, default=str))
    else:
        click.echo(f"Launched execution {result.id}")


@app.command()
@click.argument("app")
@click.option("--inputs", "-i", default=None, help="JSON dict of reader inputs.")
@click.option("--features", "-f", default=None, type=click.Path(exists=True, path_type=Path), help="JSON feature file.")
@click.option("--app-version", "-v", default=None)
@click.option("--model-version", "-m", default=None)
@click.option("--local", is_flag=True, help="Predict locally (requires a trained/loaded artifact or --model-path).")
@click.option("--model-path", default=None, type=click.Path(exists=True, path_type=Path), help="Local model file for --local.")
@click.option("--wait", "-w", is_flag=True)
def predict(
    app: str,
    inputs: Optional[str],
    features: Optional[Path],
    app_version: Optional[str],
    model_version: Optional[str],
    local: bool,
    model_path: Optional[Path],
    wait: bool,
) -> None:
    """Generate predictions from reader inputs or raw features."""
    model = _load_model(app)
    parsed_inputs = _parse_json_opt(inputs, "--inputs")
    feature_payload = None
    if features is not None:
        feature_payload = json.loads(Path(features).read_text())

    if local:
        if model_path is not None:
            model.load(model_path)
        predictions = model.predict(features=feature_payload, **parsed_inputs)
    else:
        result = model.remote_predict(
            app_version=app_version,
            model_version=model_version,
            wait=wait,
            features=feature_payload,
            **parsed_inputs,
        )
        if not wait:
            click.echo(f"Launched execution {result.id}")
            return
        predictions = result
    from unionml_tpu.serving import jsonable

    click.echo(json.dumps(jsonable(predictions), default=str))


@app.command("list-model-versions")
@click.argument("app")
@click.option("--app-version", "-v", default=None)
@click.option("--limit", default=10, show_default=True)
def list_model_versions(app: str, app_version: Optional[str], limit: int) -> None:
    """List model versions (training execution ids), newest first."""
    model = _load_model(app)
    for version in model.remote_list_model_versions(app_version=app_version, limit=limit):
        click.echo(version)


@app.command("list-prediction-ids")
@click.argument("app")
@click.option("--app-version", "-v", default=None)
@click.option("--limit", default=10, show_default=True)
def list_prediction_ids(app: str, app_version: Optional[str], limit: int) -> None:
    """List batch prediction ids, newest first."""
    model = _load_model(app)
    for pid in model.remote_list_prediction_ids(app_version=app_version, limit=limit):
        click.echo(pid)


@app.command("list-scheduled-training-runs")
@click.argument("app")
@click.argument("schedule_name")
@click.option("--app-version", "-v", default=None)
@click.option("--limit", default=5, show_default=True)
def list_scheduled_training_runs(app: str, schedule_name: str, app_version: Optional[str], limit: int) -> None:
    model = _load_model(app)
    for execution in model.remote_list_scheduled_training_runs(schedule_name, app_version=app_version, limit=limit):
        click.echo(f"{execution.id}\t{execution.status}")


@app.command("list-scheduled-prediction-runs")
@click.argument("app")
@click.argument("schedule_name")
@click.option("--app-version", "-v", default=None)
@click.option("--limit", default=5, show_default=True)
def list_scheduled_prediction_runs(app: str, schedule_name: str, app_version: Optional[str], limit: int) -> None:
    model = _load_model(app)
    for execution in model.remote_list_scheduled_prediction_runs(schedule_name, app_version=app_version, limit=limit):
        click.echo(f"{execution.id}\t{execution.status}")


@app.command("fetch-model")
@click.argument("app")
@click.option("--app-version", "-v", default=None)
@click.option("--model-version", "-m", default="latest", show_default=True)
@click.option("--output-file", "-o", required=True, type=click.Path(path_type=Path))
@click.option("--kwargs", default=None, help="JSON kwargs forwarded to model.save.")
def fetch_model(app: str, app_version: Optional[str], model_version: str, output_file: Path, kwargs: Optional[str]) -> None:
    """Fetch a trained model from backend lineage and save it locally."""
    from unionml_tpu.remote import get_model_artifact

    model = _load_model(app)
    model.artifact = get_model_artifact(model, app_version=app_version, model_version=model_version)
    model.save(output_file, **_parse_json_opt(kwargs, "--kwargs"))
    click.echo(f"Saved model to {output_file}")


@app.command("fetch-predictions")
@click.argument("app")
@click.option("--app-version", "-v", default=None)
@click.option("--prediction-id", "-p", default="latest", show_default=True)
@click.option("--output-file", "-o", required=True, type=click.Path(path_type=Path))
def fetch_predictions(app: str, app_version: Optional[str], prediction_id: str, output_file: Path) -> None:
    """Fetch batch predictions from backend lineage and write them as JSON."""
    model = _load_model(app)
    backend = model._remote
    if prediction_id == "latest":
        ids = model.remote_list_prediction_ids(app_version=app_version, limit=1)
        if not ids:
            raise click.ClickException("No predictions found.")
        prediction_id = ids[0]
    execution = backend.get_execution(prediction_id)
    predictions = model.remote_fetch_predictions(execution)
    Path(output_file).write_text(json.dumps(predictions, default=str))
    click.echo(f"Saved predictions to {output_file}")


@app.command()
@click.argument("app")
@click.option("--model-path", default=None, type=click.Path(exists=True, path_type=Path))
@click.option("--host", default="127.0.0.1", show_default=True)
@click.option("--port", default=8000, show_default=True)
@click.option("--remote", is_flag=True, help="Load the model from backend lineage instead of a file.")
@click.option("--app-version", "-v", default=None)
@click.option("--model-version", "-m", default="latest", show_default=True)
@click.option(
    "--replicas",
    default=1,
    show_default=True,
    help="Generation engine replicas behind the fleet router (requires the "
    "app to define a generator factory; >1 enables /generate session "
    "routing and failover).",
)
@click.option(
    "--telemetry/--no-telemetry",
    "telemetry",
    default=True,
    show_default=True,
    help="Per-request span tracing + the Prometheus /metrics, "
    "/trace/{request_id}, and /traces/recent endpoints on the generation "
    "path (off: the request path pays one host branch per hook and "
    "nothing else).",
)
@click.option(
    "--trace-journal",
    default=None,
    type=click.Path(path_type=Path),
    help="Append completed request traces to this JSONL file (schema v1; "
    "the replay-simulator input). Implies --telemetry.",
)
def serve(
    app: str,
    model_path: Optional[Path],
    host: str,
    port: int,
    remote: bool,
    app_version: Optional[str],
    model_version: str,
    replicas: int,
    telemetry: bool,
    trace_journal: Optional[Path],
) -> None:
    """Serve the model over HTTP with a resident compiled predictor."""
    if model_path is not None:
        os.environ["UNIONML_MODEL_PATH"] = str(model_path)
    if replicas < 1:
        raise click.BadParameter("--replicas must be >= 1")
    model = _load_model(app)
    from unionml_tpu.serving import run_app, serving_app

    serving_kwargs = {}
    if replicas > 1:
        serving_kwargs["generate_replicas"] = replicas
    if trace_journal is not None:
        telemetry = True
        serving_kwargs["generate_trace_journal"] = str(trace_journal)
    serving_kwargs["generate_telemetry"] = telemetry
    http_app = serving_app(
        model, remote=remote, app_version=app_version, model_version=model_version,
        **serving_kwargs,
    )
    logger.info("Serving %s on %s:%d (replicas=%d)", app, host, port, replicas)
    run_app(http_app, host=host, port=port)


@app.command()
@click.argument("app", required=False)
@click.option("--poll-interval", default=10.0, show_default=True, help="Seconds between schedule evaluations.")
def scheduler(app: Optional[str], poll_interval: float) -> None:
    """Run the schedule executor loop (fires active cron / fixed-rate jobs)."""
    from unionml_tpu.backend import Scheduler, backend_from_config

    backend = _load_model(app)._remote if app else backend_from_config()
    runner = Scheduler(backend, poll_interval=poll_interval)
    click.echo("Scheduler running; Ctrl-C to stop.")
    try:
        runner.start()
        runner._thread.join()
    except KeyboardInterrupt:
        runner.stop()


def main() -> None:
    app(prog_name="unionml-tpu")


if __name__ == "__main__":
    main()
