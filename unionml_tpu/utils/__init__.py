"""Shared helpers: framework sniffing, pytree/device-array utilities, dataclass synthesis.

Reference parity: ``unionml/utils.py:63-76`` (framework sniffers, ``module_is_installed``).
The stage-wrapping half of the reference's utils module lives in
:mod:`unionml_tpu.stage`. TPU-native additions: device-array conversion used by the
default Dataset pipeline and JSON-able dataclass synthesis replacing ``dataclasses_json``.
"""

import importlib
from dataclasses import _MISSING_TYPE, MISSING, asdict, field, fields, is_dataclass, make_dataclass
from inspect import Parameter, signature
from typing import Any, Callable, Dict, List, Mapping, Optional, Sequence, Tuple, Type

import jax
import numpy as np

_EMPTY = Parameter.empty


def is_pytorch_model(model_type: Optional[type]) -> bool:
    """True when ``model_type`` is a torch ``nn.Module`` subclass (``utils.py:63-64``)."""
    if model_type is None or not isinstance(model_type, type):
        return False
    return any(base.__module__.startswith("torch") for base in model_type.__mro__)


def is_keras_model(model_type: Optional[type]) -> bool:
    """True when ``model_type`` is a keras model subclass (``utils.py:67-68``)."""
    if model_type is None or not isinstance(model_type, type):
        return False
    return any(base.__module__.startswith(("keras", "tensorflow.python.keras")) for base in model_type.__mro__)


def is_flax_module(model_type: Optional[type]) -> bool:
    """True when ``model_type`` is a flax ``nn.Module`` subclass — a jax-native model family."""
    if model_type is None or not isinstance(model_type, type):
        return False
    return any(base.__module__.startswith("flax") for base in model_type.__mro__)


def is_sklearn_model(obj_or_type: Any) -> bool:
    try:
        import sklearn.base
    except ImportError:  # pragma: no cover
        return False
    if isinstance(obj_or_type, type):
        return issubclass(obj_or_type, sklearn.base.BaseEstimator)
    return isinstance(obj_or_type, sklearn.base.BaseEstimator)


def hard_sync(tree: Any) -> None:
    """Block until every array in ``tree`` has finished computing — via device-to-host
    fetches, not ``jax.block_until_ready``.

    On remote-TPU platforms (the axon plugin) ``block_until_ready`` returns before
    execution completes (observed 2026-07-29: a 10-step BERT timing loop "finished" in
    0.02s — TPU_PROBES.log), so anything that needs a real barrier (benchmark timing,
    zero-copy buffer recycling fences) must gate on a transfer instead. Fetching one
    element PER ADDRESSABLE SHARD forces every device's producing computation (and
    any pending host-to-device transfer it consumed) to complete — a whole-leaf
    fetch would sync only the device holding element 0 of a sharded array.
    """
    for leaf in jax.tree_util.tree_leaves(tree):
        shards = getattr(leaf, "addressable_shards", None)
        if shards:
            for shard in shards:
                if shard.data.size:
                    jax.device_get(shard.data.ravel()[0])
        elif hasattr(leaf, "ravel") and getattr(leaf, "size", 0):
            jax.device_get(leaf.ravel()[0])


def module_is_installed(module: str) -> bool:
    """``utils.py:71-76`` parity."""
    try:
        importlib.import_module(module)
        return True
    except ImportError:
        return False


def pick_free_port() -> int:
    """Reserve an ephemeral localhost port (bind-probe; small TOCTOU window applies)."""
    import socket

    with socket.socket() as probe:
        probe.bind(("127.0.0.1", 0))
        return probe.getsockname()[1]


def to_device_arrays(*arrays: Any, dtype: Any = None) -> Tuple[jax.Array, ...]:
    """Convert host data (pandas / numpy / lists) to device arrays.

    This is the host->device boundary of the default data pipeline: pandas objects go
    through ``.to_numpy()`` then ``jax.device_put``. On TPU, float64 numpy data is cast
    to float32 unless ``dtype`` says otherwise (x64 is disabled by default in jax).
    """
    import jax.numpy as jnp

    out = []
    for array in arrays:
        if isinstance(array, dict):
            # multi-input features (tokenized models): convert each value, keep the dict
            out.append({k: to_device_arrays(v, dtype=dtype)[0] for k, v in array.items()})
            continue
        if hasattr(array, "to_numpy"):
            array = array.to_numpy()
        array = np.asarray(array)
        if dtype is not None:
            array = array.astype(dtype)
        elif array.dtype == np.float64:
            array = array.astype(np.float32)
        out.append(jnp.asarray(array))
    return tuple(out)


def make_json_dataclass(name: str, field_specs: Sequence[Tuple], bases: Tuple[type, ...] = ()) -> Type:
    """``make_dataclass`` with ``to_dict``/``from_dict``/``to_json``/``from_json`` methods.

    Stands in for the reference's ``dataclasses_json`` decoration of synthesized kwargs
    dataclasses (``unionml/dataset.py:251``, ``model.py:201-203``) without the external
    dependency.
    """
    import json

    cls = make_dataclass(name, field_specs, bases=bases)

    def to_dict(self) -> Dict[str, Any]:
        return asdict(self)

    @classmethod
    def from_dict(cls_, data: Mapping[str, Any]):
        names = {f.name for f in fields(cls_)}
        return cls_(**{k: v for k, v in data.items() if k in names})

    def to_json(self) -> str:
        return json.dumps(asdict(self))

    @classmethod
    def from_json(cls_, raw: str):
        return cls_.from_dict(json.loads(raw))

    cls.to_dict = to_dict
    cls.from_dict = from_dict
    cls.to_json = to_json
    cls.from_json = from_json
    return cls


def kwargs_field_specs(
    fn: Callable,
    default_overrides: Optional[Mapping[str, Any]] = None,
    skip_first: int = 1,
) -> List[Tuple]:
    """Field specs for a kwargs dataclass synthesized from ``fn``'s trailing parameters.

    Mirrors the synthesis at ``unionml/dataset.py:240-280``: the first ``skip_first``
    parameters (the data argument) are dropped; defaults come from ``default_overrides``
    first, then the signature.
    """
    default_overrides = default_overrides or {}
    specs: List[Tuple] = []
    for index, param in enumerate(signature(fn).parameters.values()):
        if index < skip_first:
            continue
        default = default_overrides.get(param.name, param.default)
        annotation = param.annotation if param.annotation is not _EMPTY else Any
        if default is _EMPTY:
            specs.append((param.name, annotation))
        elif isinstance(default, (list, dict, set)):
            specs.append((param.name, annotation, field(default_factory=lambda d=default: d)))
        else:
            specs.append((param.name, annotation, field(default=default)))
    return specs
