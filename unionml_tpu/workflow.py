"""A minimal imperative workflow graph + in-process executor.

This replaces the flytekit ``Workflow`` the reference builds its train/predict graphs on
(``unionml/model.py:425-510``): the same imperative API — ``add_workflow_input``,
``add_entity``, ``add_workflow_output`` — wired to an in-repo topological executor
instead of Flyte's compiler. Stages run in dependency order; data flows as plain Python
objects / device arrays (no literal-type serialization on the local path).

The graph is also the unit the execution backend serializes for remote jobs: every node
references a stage by its tracked address, so a worker can rebuild the identical graph.
"""

from collections import OrderedDict
from typing import Any, Dict, List, NamedTuple, Optional

from unionml_tpu.exceptions import WorkflowError
from unionml_tpu.stage import Stage, _output_mapping


class Promise(NamedTuple):
    """A reference to a named output of a workflow node (or a workflow input)."""

    source: str  # node id, or "__inputs__"
    key: str


class Node:
    def __init__(self, node_id: str, stage: Stage, bindings: Dict[str, Any]):
        self.id = node_id
        self.stage = stage
        self.bindings = bindings  # arg name -> Promise | literal

    @property
    def outputs(self) -> Dict[str, Promise]:
        return {key: Promise(self.id, key) for key in _output_mapping(self.stage.output_annotation)}


class WorkflowInput(NamedTuple):
    name: str
    annotation: Any
    default: Any


_NO_DEFAULT = object()


class Workflow:
    """An imperative DAG of stages."""

    def __init__(self, name: str):
        self.name = name
        self._inputs: "OrderedDict[str, WorkflowInput]" = OrderedDict()
        self._nodes: "OrderedDict[str, Node]" = OrderedDict()
        self._outputs: "OrderedDict[str, Promise]" = OrderedDict()

    @property
    def inputs(self) -> Dict[str, Promise]:
        return {name: Promise("__inputs__", name) for name in self._inputs}

    @property
    def input_types(self) -> "OrderedDict[str, Any]":
        return OrderedDict((name, spec.annotation) for name, spec in self._inputs.items())

    @property
    def output_names(self) -> List[str]:
        return list(self._outputs)

    @property
    def nodes(self) -> List[Node]:
        return list(self._nodes.values())

    def add_workflow_input(self, name: str, annotation: Any, default: Any = _NO_DEFAULT) -> Promise:
        if name in self._inputs:
            raise WorkflowError(f"Workflow {self.name} already has an input named {name!r}")
        self._inputs[name] = WorkflowInput(name, annotation, default)
        return Promise("__inputs__", name)

    def add_entity(self, stage: Stage, **bindings: Any) -> Node:
        missing = [k for k in bindings if k not in stage.inputs]
        if missing:
            raise WorkflowError(f"Stage {stage.name} has no inputs named {missing}")
        node_id = f"n{len(self._nodes)}-{stage.name}"
        node = Node(node_id, stage, bindings)
        self._nodes[node_id] = node
        return node

    def add_workflow_output(self, name: str, promise: Promise) -> None:
        if not isinstance(promise, Promise):
            raise WorkflowError(f"Workflow output {name!r} must be bound to a Promise; got {promise!r}")
        self._outputs[name] = promise

    def execute(self, **inputs: Any) -> Any:
        """Run the graph in insertion (topological) order and return the declared outputs.

        Single output -> the bare value; multiple outputs -> NamedTuple-like tuple in
        declaration order (matching flytekit local-execution ergonomics the reference
        relies on at ``unionml/model.py:697-703``).
        """
        values: Dict[str, Dict[str, Any]] = {"__inputs__": {}}
        for name, spec in self._inputs.items():
            if name in inputs:
                values["__inputs__"][name] = inputs[name]
            elif spec.default is not _NO_DEFAULT:
                values["__inputs__"][name] = spec.default
            else:
                raise WorkflowError(f"Workflow {self.name} missing required input {name!r}")
        unknown = set(inputs) - set(self._inputs)
        if unknown:
            raise WorkflowError(f"Workflow {self.name} received unknown inputs: {sorted(unknown)}")

        for node in self._nodes.values():
            kwargs = {}
            for arg, binding in node.bindings.items():
                if isinstance(binding, Promise):
                    try:
                        kwargs[arg] = values[binding.source][binding.key]
                    except KeyError as exc:
                        raise WorkflowError(
                            f"Node {node.id} binding {arg!r} references unavailable value {binding}"
                        ) from exc
                else:
                    kwargs[arg] = binding
            result = node.stage(**kwargs)
            out_keys = list(_output_mapping(node.stage.output_annotation))
            if len(out_keys) == 1:
                values[node.id] = {out_keys[0]: result}
            else:
                values[node.id] = dict(zip(out_keys, result))

        resolved = [values[p.source][p.key] for p in self._outputs.values()]
        if len(resolved) == 1:
            return resolved[0]
        return tuple(resolved)

    __call__ = execute

    def __repr__(self) -> str:
        return f"Workflow(name={self.name!r}, inputs={list(self._inputs)}, nodes={len(self._nodes)})"
