"""Tracing & profiling: per-stage timing plus jax/XLA profiler capture.

Reference state: none — observability is delegated to the Flyte console (SURVEY.md §5).
Here the framework owns it: every :class:`~unionml_tpu.stage.Stage` records its last
wall-clock duration (surfaced via :func:`workflow_timings` and the CLI's
``train --profile-dir``), and this module adds xprof trace capture around any block
(viewable with TensorBoard/xprof) plus device-memory statistics.
"""

import contextlib
from typing import Any, Dict, Iterator, List, Optional

from unionml_tpu._logging import logger


@contextlib.contextmanager
def xprof_trace(log_dir: str, host_tracer_level: int = 2) -> Iterator[None]:
    """Capture a jax profiler trace (XLA ops, TPU activity) into ``log_dir``."""
    import jax

    logger.info("Starting profiler trace -> %s", log_dir)
    with jax.profiler.trace(log_dir, create_perfetto_link=False):
        yield
    logger.info("Profiler trace written to %s", log_dir)


@contextlib.contextmanager
def annotate(name: str) -> Iterator[None]:
    """Name a region in profiler traces (shows up in xprof timelines)."""
    import jax

    with jax.profiler.TraceAnnotation(name):
        yield


def workflow_timings(workflow: Any) -> Dict[str, Optional[float]]:
    """Last-run durations of every stage in a workflow (None = not yet run)."""
    return {node.stage.name: node.stage.last_duration for node in workflow.nodes}


def device_memory_stats() -> List[Dict[str, Any]]:
    """Per-device memory statistics (bytes in use / limit) where the backend reports them."""
    import jax

    stats = []
    for device in jax.devices():
        try:
            raw = device.memory_stats() or {}
        except Exception:  # backend without memory_stats: empty stats are the fallback
            raw = {}
        stats.append(
            {
                "device": str(device),
                "bytes_in_use": raw.get("bytes_in_use"),
                "bytes_limit": raw.get("bytes_limit"),
                "peak_bytes_in_use": raw.get("peak_bytes_in_use"),
            }
        )
    return stats
