"""Per-class serving SLOs: rolling-window attainment + multi-window burn rate.

PR 9 gave the serving tier raw telemetry (spans, counters, histograms); this
module turns it into *objectives*. Each priority class carries an
:class:`SLOObjective` — a TTFT bound and an attainment target — and one
:class:`SLOTracker` folds every completed request into:

- **Rolling-window attainment** per class: the fraction of requests inside
  their objective over each configured window (plus lifetime totals).
- **Multi-window burn rate** (the SRE error-budget pattern): how many times
  faster than sustainable the class is consuming its error budget, per
  window. An *alert* fires only when EVERY window burns above
  ``alert_burn`` — the short window proves the problem is current, the long
  window proves it is material, so a blip pages nobody and a slow leak
  still does.

The tracker is deliberately engine-free pure host code with an injectable
clock on every method (``now=``), so the SAME object scores the live
``/metrics`` + ``/stats`` surface (fed by :class:`~unionml_tpu.serving.
telemetry.Telemetry.end_trace`) and the fleet simulator's virtual-clock
replay/synthetic runs (``unionml_tpu.sim``) — one definition of "meeting
the SLO" everywhere, which is what makes the simulator's golden-replay
equality check meaningful.

Event accounting: a request is **good** when it completed ``ok`` within its
class's TTFT bound (classes with no bound count any ``ok`` as good);
``error``/``shed`` outcomes are bad; ``cancelled`` is excluded entirely
(a client hanging up is not a server SLO violation). TTFT is compared at
millisecond precision as journaled (3 decimals), so live scoring and
journal replay can never disagree on a boundary case.

Lock discipline: the tracker owns one LEAF lock and never calls out to any
other serving component; callers (telemetry, the HTTP stats route, the
simulator) read results after the lock is released.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Deque, Dict, List, Optional, Tuple

__all__ = [
    "DEFAULT_WINDOWS",
    "SLOConfig",
    "SLOObjective",
    "SLOTracker",
]

#: (name, seconds) rolling windows, shortest first — the classic fast/slow
#: pair: 5m catches a live incident, 1h proves it is spending real budget
DEFAULT_WINDOWS: Tuple[Tuple[str, float], ...] = (("5m", 300.0), ("1h", 3600.0))


@dataclass(frozen=True)
class SLOObjective:
    """One class's objective: TTFT bound (ms; ``None`` = success-only SLO)
    and the attainment target in ``(0, 1)`` — the error budget is
    ``1 - target``."""

    ttft_ms: Optional[float]
    target: float

    def __post_init__(self) -> None:
        if not (0.0 < self.target < 1.0):
            raise ValueError(f"target must be in (0, 1), got {self.target}")
        if self.ttft_ms is not None and self.ttft_ms <= 0:
            raise ValueError(f"ttft_ms must be > 0, got {self.ttft_ms}")


def _default_objectives() -> Dict[str, SLOObjective]:
    # mirrors the scheduler's PRIORITY_CLASSES; interactive is latency-bound,
    # batch only promises completion. Unknown classes fall back to standard.
    return {
        "interactive": SLOObjective(ttft_ms=250.0, target=0.99),
        "standard": SLOObjective(ttft_ms=1000.0, target=0.95),
        "batch": SLOObjective(ttft_ms=None, target=0.90),
    }


@dataclass(frozen=True)
class SLOConfig:
    """Objectives + windows + alerting threshold for one :class:`SLOTracker`.

    :param objectives: per-class :class:`SLOObjective`; classes absent here
        score against ``standard``.
    :param windows: rolling ``(name, seconds)`` windows, shortest first.
    :param alert_burn: burn-rate multiple above which a window counts toward
        the multi-window alert (the alert needs EVERY window above it).
    """

    objectives: Dict[str, SLOObjective] = field(default_factory=_default_objectives)
    windows: Tuple[Tuple[str, float], ...] = DEFAULT_WINDOWS
    alert_burn: float = 2.0

    def __post_init__(self) -> None:
        if not self.windows:
            raise ValueError("need at least one rolling window")
        if self.alert_burn <= 0:
            raise ValueError(f"alert_burn must be > 0, got {self.alert_burn}")
        if "standard" not in self.objectives:
            raise ValueError("objectives must cover the 'standard' fallback class")

    def objective_for(self, cls: str) -> SLOObjective:
        return self.objectives.get(cls, self.objectives["standard"])


class _Window:
    """One class's events inside one rolling window: a deque of
    ``(t, good)`` plus running counts, pruned on every touch so record and
    read are both amortized O(1)."""

    __slots__ = ("seconds", "events", "good", "total")

    def __init__(self, seconds: float) -> None:
        self.seconds = float(seconds)
        self.events: Deque[Tuple[float, bool]] = deque()
        self.good = 0
        self.total = 0

    def add(self, t: float, good: bool) -> None:
        self.events.append((t, good))
        self.total += 1
        if good:
            self.good += 1
        self.prune(t)

    def prune(self, now: float) -> None:
        horizon = now - self.seconds
        while self.events and self.events[0][0] < horizon:
            _, was_good = self.events.popleft()
            self.total -= 1
            if was_good:
                self.good -= 1

    def attainment(self) -> Optional[float]:
        return None if self.total == 0 else self.good / self.total


class _ClassState:
    """Lifetime totals + per-window state for one class."""

    __slots__ = ("good", "total", "windows")

    def __init__(self, windows: Tuple[Tuple[str, float], ...]) -> None:
        self.good = 0
        self.total = 0
        self.windows: Dict[str, _Window] = {name: _Window(s) for name, s in windows}


class SLOTracker:
    """Rolling SLO attainment + burn-rate scoring shared by the live serving
    surface and the fleet simulator.

    Thread-safe behind one leaf lock. Every method takes an optional ``now``
    (``time.monotonic`` when omitted) so a virtual-clock simulator and the
    live path run the identical arithmetic.
    """

    def __init__(self, config: Optional[SLOConfig] = None) -> None:
        self.config = config or SLOConfig()
        self._lock = threading.Lock()
        self._classes: Dict[str, _ClassState] = {}  # guarded-by: _lock

    # ------------------------------------------------------------------ intake

    def record(
        self,
        cls: str,
        status: str,
        ttft_ms: Optional[float] = None,
        *,
        now: Optional[float] = None,
    ) -> Optional[Dict[str, Any]]:
        """Fold one completed request in; returns the class's refreshed
        signal ``{"attainment": ..., "burn": {window: rate}}`` for the
        caller to mirror into gauges (outside this tracker's lock), or
        ``None`` when the outcome is excluded (``cancelled``)."""
        if status == "cancelled":
            return None
        now = time.monotonic() if now is None else now
        objective = self.config.objective_for(cls)
        good = status == "ok" and (
            objective.ttft_ms is None
            or (ttft_ms is not None and ttft_ms <= objective.ttft_ms)
        )
        budget = 1.0 - objective.target
        with self._lock:
            state = self._classes.get(cls)
            if state is None:
                state = self._classes[cls] = _ClassState(self.config.windows)
            state.total += 1
            if good:
                state.good += 1
            burn: Dict[str, float] = {}
            for name, window in state.windows.items():
                window.add(now, good)
                bad_frac = 1.0 - (window.good / window.total)
                burn[name] = round(bad_frac / budget, 4)
            attainment = state.windows[self.config.windows[-1][0]].attainment()
        return {"attainment": attainment, "burn": burn}

    # ----------------------------------------------------------------- readers

    def totals(self) -> Dict[str, Dict[str, int]]:
        """Lifetime ``{cls: {"good": n, "total": n}}`` — the golden-replay
        equality surface (window-free, so replay timing cannot perturb it)."""
        with self._lock:
            return {
                cls: {"good": s.good, "total": s.total}
                for cls, s in sorted(self._classes.items())
            }

    def report(self, now: Optional[float] = None) -> Dict[str, Any]:
        """The ``/stats`` → ``generation.slo`` block (same shape in the
        simulator's report): objectives, lifetime + per-window attainment,
        burn rates, and the multi-window alert per class."""
        now = time.monotonic() if now is None else now
        out: Dict[str, Any] = {
            "windows": {name: s for name, s in self.config.windows},
            "alert_burn": self.config.alert_burn,
            "per_class": {},
            "alerts": [],
        }
        with self._lock:
            for cls, state in sorted(self._classes.items()):
                objective = self.config.objective_for(cls)
                windows: Dict[str, Any] = {}
                burning: List[bool] = []
                for name, window in state.windows.items():
                    window.prune(now)
                    att = window.attainment()
                    if att is None:
                        burn = 0.0
                    else:
                        burn = round((1.0 - att) / (1.0 - objective.target), 4)
                    burning.append(burn >= self.config.alert_burn)
                    windows[name] = {
                        "total": window.total,
                        "good": window.good,
                        "attainment": None if att is None else round(att, 6),
                        "burn_rate": burn,
                    }
                alert = bool(burning) and all(burning)
                out["per_class"][cls] = {
                    "objective_ttft_ms": objective.ttft_ms,
                    "target": objective.target,
                    "total": state.total,
                    "good": state.good,
                    "attainment": (
                        None if state.total == 0 else round(state.good / state.total, 6)
                    ),
                    "windows": windows,
                    "alert": alert,
                }
                if alert:
                    out["alerts"].append(cls)
        return out
