"""Request coalescing: merge concurrent /predict requests into one compiled call.

The resident executable's cost is nearly flat across the batch bucket, so N concurrent
single-row requests served individually waste N-1 executions. The batcher queues
feature rows from concurrent requests, drains the queue up to ``max_batch`` rows
(waiting at most ``max_wait_ms`` for stragglers after the first arrival), runs ONE
predictor call, and fans results back out to the waiting requests.

Correctness contract: feature payloads must be row-lists (the `/predict
{"features": [...]}` shape) and the predictor must return one result per row; anything
else bypasses coalescing (the caller falls back to per-request prediction).
"""

import asyncio
from typing import Any, Callable, List, Optional, Sequence

from unionml_tpu._logging import logger


class RequestBatcher:
    """Coalesces concurrent row-list predictions into shared predictor calls."""

    def __init__(
        self,
        predict_rows: Callable[[List[Any]], Sequence[Any]],
        max_batch: int = 64,
        max_wait_ms: float = 2.0,
        adaptive: bool = True,
        preferred_multiple: Optional[int] = None,
    ):
        """
        ``adaptive=True`` keys the straggler wait on the observed arrival rate: when
        requests arrive sparsely (EMA inter-arrival gap above ``max_wait_ms``),
        waiting would add latency and coalesce nothing, so batches flush
        immediately; under bursts the full ``max_wait_ms`` window applies.

        ``preferred_multiple`` (mesh-sharded predictors: the data-axis shard
        count) grants one extra ``max_wait_ms`` straggler window when the drained
        row count is not a multiple — a shard-even batch pads less after
        bucketing — but never blocks a flush beyond that: correctness and the
        bounded-latency contract are unchanged.
        """
        self._predict_rows = predict_rows
        self.max_batch = max_batch
        self.max_wait_s = max_wait_ms / 1000.0
        self.adaptive = adaptive
        self.preferred_multiple = (
            int(preferred_multiple) if preferred_multiple and preferred_multiple > 1 else None
        )
        self._ema_gap_s: Optional[float] = None
        self._last_arrival: Optional[float] = None
        self._queue: Optional[asyncio.Queue] = None
        self._worker: Optional[asyncio.Task] = None
        self.stats = {"requests": 0, "rows": 0, "batches": 0}

    def _ensure_worker(self) -> None:
        if self._queue is None:
            self._queue = asyncio.Queue()
        if self._worker is None or self._worker.done():
            self._worker = asyncio.get_running_loop().create_task(self._run())

    async def submit(self, rows: List[Any]) -> List[Any]:
        """Queue one request's rows; resolves with that request's predictions."""
        self._ensure_worker()
        now = asyncio.get_running_loop().time()
        if self._last_arrival is not None:
            # clamp: one long idle period must not poison the EMA for the burst
            # that follows it (recovery would otherwise take dozens of requests)
            gap = min(now - self._last_arrival, 10 * self.max_wait_s)
            self._ema_gap_s = gap if self._ema_gap_s is None else 0.8 * self._ema_gap_s + 0.2 * gap
        self._last_arrival = now
        future = asyncio.get_running_loop().create_future()
        self.stats["requests"] += 1
        self.stats["rows"] += len(rows)
        await self._queue.put((rows, future))
        return await future

    @property
    def ema_gap_ms(self) -> Optional[float]:
        """Observed EMA inter-arrival gap (ms); None before any traffic."""
        return None if self._ema_gap_s is None else self._ema_gap_s * 1e3

    def _effective_wait_s(self) -> float:
        """The straggler window for this batch under the adaptive policy."""
        if not self.adaptive or self._ema_gap_s is None:
            return self.max_wait_s
        if self._ema_gap_s > self.max_wait_s:
            return 0.0  # sparse traffic: waiting only adds latency
        return self.max_wait_s

    async def _run(self) -> None:
        while True:
            rows, future = await self._queue.get()
            pending = [(rows, future)]
            total = len(rows)
            deadline = asyncio.get_running_loop().time() + self._effective_wait_s()
            topped_up = False
            while total < self.max_batch:
                timeout = deadline - asyncio.get_running_loop().time()
                if timeout <= 0:
                    # window spent (or adaptive zero-wait): still drain whatever is
                    # ALREADY queued — simultaneous arrivals must coalesce even when
                    # the straggler wait is zero
                    while total < self.max_batch:
                        try:
                            more_rows, more_future = self._queue.get_nowait()
                        except asyncio.QueueEmpty:
                            break
                        pending.append((more_rows, more_future))
                        total += len(more_rows)
                    if (
                        self.preferred_multiple
                        and not topped_up
                        and total % self.preferred_multiple != 0
                        and total < self.max_batch
                    ):
                        # mesh-sharded predictor: one extra window to reach a
                        # shard-even row count, then flush regardless
                        topped_up = True
                        deadline = asyncio.get_running_loop().time() + self.max_wait_s
                        continue
                    break
                try:
                    more_rows, more_future = await asyncio.wait_for(self._queue.get(), timeout)
                except asyncio.TimeoutError:
                    continue  # loop re-checks the deadline (and the top-up rule)
                pending.append((more_rows, more_future))
                total += len(more_rows)
                if (
                    self.preferred_multiple
                    and topped_up
                    and total % self.preferred_multiple == 0
                ):
                    break  # top-up reached a shard-even count: flush now
            await self._flush(pending)

    async def _flush(self, pending) -> None:
        self.stats["batches"] += 1
        all_rows: List[Any] = []
        for rows, _ in pending:
            all_rows.extend(rows)
        try:
            predictions = await asyncio.get_running_loop().run_in_executor(
                None, self._predict_rows, all_rows
            )
            predictions = _as_row_sequence(predictions, len(all_rows))
            offset = 0
            for rows, future in pending:
                if not future.done():
                    future.set_result(predictions[offset : offset + len(rows)])
                offset += len(rows)
        except Exception as exc:
            logger.exception("Coalesced prediction failed")
            for _, future in pending:
                if not future.done():
                    future.set_exception(exc)
        finally:
            # cancellation (close() mid-flush) is a BaseException: never strand waiters
            for _, future in pending:
                if not future.done():
                    future.set_exception(RuntimeError("batcher shut down mid-request"))

    def close(self) -> None:
        if self._worker is not None:
            self._worker.cancel()
            self._worker = None
        # fail any requests still queued: their handlers must not hang on shutdown
        if self._queue is not None:
            while not self._queue.empty():
                _, future = self._queue.get_nowait()
                if not future.done():
                    future.set_exception(RuntimeError("batcher shut down before dispatch"))


def _as_row_sequence(predictions: Any, n_rows: int) -> List[Any]:
    """Coerce predictor output to a per-row list, rejecting ambiguous shapes.

    A bare ``list()`` would iterate a mapping's KEYS or a DataFrame's COLUMNS — when
    either count coincides with the row count, requests would silently receive
    garbage; only explicit row-sequence types are accepted.
    """
    from collections.abc import Mapping

    if isinstance(predictions, Mapping):
        raise ValueError("coalescing requires a per-row sequence; predictor returned a mapping")
    if hasattr(predictions, "iloc"):  # pandas: rows as records
        rows = predictions.to_dict(orient="records") if hasattr(predictions, "to_dict") else None
        if rows is None or len(rows) != n_rows:
            raise ValueError("coalescing requires one result per row")
        return rows
    if hasattr(predictions, "shape"):  # numpy / jax: first axis is the row axis
        if predictions.ndim < 1 or predictions.shape[0] != n_rows:
            raise ValueError(
                f"predictor returned shape {getattr(predictions, 'shape', None)} for {n_rows} rows"
            )
        return list(predictions)
    if isinstance(predictions, (list, tuple)):
        if len(predictions) != n_rows:
            raise ValueError(
                f"predictor returned {len(predictions)} results for {n_rows} rows; "
                "coalescing requires one result per row"
            )
        return list(predictions)
    raise ValueError(f"coalescing cannot split predictor output of type {type(predictions)!r}")
