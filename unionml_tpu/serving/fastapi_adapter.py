"""FastAPI adapter: attach unionml-tpu serving endpoints to a user's FastAPI app.

Reference parity: ``unionml/fastapi.py:15-70`` — identical endpoint contract. Only
importable when ``fastapi`` is installed (optional dependency); the native aiohttp app
(:mod:`unionml_tpu.serving.app`) is the default serving surface.
"""

from http import HTTPStatus
from typing import Any, Dict, List, Optional, Union

from fastapi import Body, FastAPI, HTTPException
from fastapi.responses import HTMLResponse

from unionml_tpu._logging import logger
from unionml_tpu.serving.app import _INDEX_HTML, jsonable, load_model_artifact
from unionml_tpu.serving.resident import ResidentPredictor


def attach_fastapi(
    model: Any,
    app: FastAPI,
    remote: bool = False,
    app_version: Optional[str] = None,
    model_version: str = "latest",
    resident: bool = True,
    buckets: Optional[Any] = None,
    seq_buckets: Optional[Any] = None,
    example_features: Optional[Any] = None,
    mesh: Optional[Any] = None,
    param_specs: Optional[Any] = None,
    **unsupported: Any,
) -> FastAPI:
    from unionml_tpu.serving.resident import DEFAULT_BUCKETS

    if unsupported:
        # the aiohttp app supports more options (request coalescing); say so instead
        # of silently ignoring them on this path
        logger.warning(
            "attach_fastapi ignoring unsupported serving options: %s", sorted(unsupported)
        )

    predictor = (
        ResidentPredictor(
            model,
            buckets=buckets or DEFAULT_BUCKETS,
            seq_buckets=seq_buckets,
            example_features=example_features,
            # the mesh-sharded executor sits entirely below the endpoint
            # contract: /predict and /health behave identically above it
            mesh=mesh,
            param_specs=param_specs,
        )
        if resident
        else None
    )

    @app.on_event("startup")
    async def setup_model():
        load_model_artifact(model, remote=remote, app_version=app_version, model_version=model_version)
        if predictor is not None:
            # graftlint: disable=async-blocking -- startup hook: the warmup compile+hard_sync runs before the server accepts any traffic, so blocking the (idle) loop here is the point
            predictor.setup()

    @app.get("/", response_class=HTMLResponse)
    def root():
        return _INDEX_HTML

    # SYNC on purpose (graftlint async-blocking true positive, fixed): the
    # compiled predictor call and its device fetch block for milliseconds+,
    # which on an ``async def`` endpoint stalls the event loop for every
    # in-flight request. FastAPI runs sync endpoints in its threadpool — same
    # contract, no loop stall (the aiohttp app routes through run_in_executor
    # for the same reason).
    @app.post("/predict")
    def predict(
        inputs: Optional[Union[Dict[str, Any], None]] = Body(None),
        features: Optional[List[Any]] = Body(None),
    ):
        if inputs is None and features is None:
            raise HTTPException(status_code=500, detail="inputs or features must be supplied.")
        # empty {} means reader-defaults ONLY when no features came along (matches app.py)
        if inputs is not None and (inputs or features is None):
            result = predictor.predict(**inputs) if predictor is not None else model.predict(**inputs)
        else:
            # model.predict runs the feature pipeline itself; don't pre-process here
            result = (
                predictor.predict(features=features)
                if predictor is not None
                else model.predict(features=features)
            )
        return jsonable(result)

    @app.get("/health")
    async def health():
        if model.artifact is None:
            raise HTTPException(status_code=500, detail="Model artifact not found.")
        return {"message": HTTPStatus.OK.phrase, "status": HTTPStatus.OK.value}

    return app
