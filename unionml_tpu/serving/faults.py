"""Deterministic fault injection + the structured engine-failure taxonomy.

The serving core's failure handling used to be untestable: a device fault only
ever appeared as whatever exception a wedged runtime happened to raise, so the
recovery paths (engine rebuild, per-request quarantine, pool-exhaustion
fallback) shipped unexercised. This module makes every fault class the
supervisor must survive *injectable on a CPU mesh, deterministically*:

- :class:`FaultPlan` is a seeded, schedule-addressable fault script — "fail
  the 3rd step dispatch", "NaN slot 1's logits after dispatch 5", "raise on
  the 2nd prefill", "stall the 4th token fetch 300 ms", "exhaust the block
  pool on the 2nd admission", "fail the first 2 engine rebuilds". The engine
  (:class:`~unionml_tpu.serving.continuous.DecodeEngine`), batcher, and
  speculative facade consult the plan at each site behind a
  ``if self._faults is not None`` guard, so a plan-less engine pays ONE host
  branch per site and no device work — the hooks are zero-cost when disabled
  and add no host syncs to the hot path (graftlint holds that line).
- :class:`FaultError` is what an injected fault raises — a stand-in for the
  runtime's own device errors, taken through the SAME except paths real
  failures take (the handlers never special-case it).
- :class:`EngineFailure` is the structured error the serving stack reports
  UPWARD: every request that dies on an engine-side failure carries a
  machine-readable ``reason`` slug (and a retryability hint) instead of a
  stringified traceback, so the HTTP layer can map it to the unified error
  contract and clients can branch without parsing prose.

Determinism: schedules address global per-site counters (1-based), so the same
plan against the same request schedule injects at exactly the same operations;
``seed`` drives the optional Bernoulli storm rates (``step_failure_rate``) used
by ``bench_serving --chaos``, which are reproducible for a fixed seed + site
ordering. A plan is owned by ONE engine/facade (the worker thread that drives
it); counters are not cross-thread-safe by design.
"""

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

__all__ = ["EngineFailure", "FaultError", "FaultPlan"]


class FaultError(RuntimeError):
    """An injected device-side fault (see :class:`FaultPlan`).

    Raised at the injection site exactly where the runtime's own error would
    surface; the serving stack's failure handlers treat it like any other
    device exception (nothing downstream special-cases injection).
    """

    def __init__(self, message: str, *, site: str) -> None:
        super().__init__(message)
        #: which injection site fired (``step_dispatch``/``step_fetch``/...)
        self.site = site


class EngineFailure(RuntimeError):
    """A structured engine-side failure delivered to a request.

    ``reason`` is a machine-readable slug (``device_failure``,
    ``nan_logits``, ``request_unrecoverable``, ``engine_failed``,
    ``speculative_round_failed``, ...) the HTTP layer forwards in the unified
    error envelope; ``retryable`` states whether a client retry can plausibly
    succeed (it maps to 503-vs-500 at the route).
    """

    def __init__(self, message: str, *, reason: str, retryable: bool = True) -> None:
        super().__init__(message)
        self.reason = reason
        self.retryable = retryable


@dataclasses.dataclass
class FaultPlan:
    """A deterministic, schedule-addressable fault-injection script.

    Every index is **1-based** against a global per-site counter the engine
    advances as it runs (dispatches, fetches, prefills, admissions, rebuild
    attempts), so a plan addresses operations, not wall time:

    :param step_dispatch_failures: decode-step dispatch indexes that raise
        :class:`FaultError` *instead of* dispatching (device state intact, but
        the engine conservatively treats any step failure as poisoning).
    :param step_fetch_failures: token-fetch (burst) indexes that raise at the
        fused ``device_get`` — the deferred-error shape, where the step's
        donated outputs were already reassigned.
    :param prefill_failures: prefill-dispatch indexes that raise — the
        per-request-attributable admission failure.
    :param nan_logits: ``(step_dispatch_index, slot)`` pairs — after that
        dispatch, the slot's ``last_logits`` row is overwritten with NaN, so
        the NEXT step samples from poisoned logits and the engine's in-step
        finiteness flag trips (per-request quarantine, not batch failure).
    :param fetch_stalls: ``(fetch_index, stall_ms)`` pairs — sleep that long
        before the fetch, simulating a wedged device queue for the
        supervisor's fetch-stall watchdog.
    :param pool_exhausted_admits: admission (``admit_many`` call) indexes
        during which the prefix-cache block pool behaves fully referenced:
        no new block can be indexed, exercising the graceful cache-less
        fallback.
    :param rebuild_failures: fail this many engine rebuild attempts before
        letting one succeed (drives the supervisor's bounded-backoff loop).
    :param speculative_round_failures: speculative-generation round indexes
        that raise (the facade's structured-failure path).
    :param step_failure_rate: seeded Bernoulli dispatch-failure probability —
        the "chaos storm" mode ``bench_serving --chaos`` uses on top of the
        scheduled sites.
    :param seed: seeds the storm-rate RNG (scheduled sites need no RNG).
    """

    step_dispatch_failures: Sequence[int] = ()
    step_fetch_failures: Sequence[int] = ()
    prefill_failures: Sequence[int] = ()
    nan_logits: Sequence[Tuple[int, int]] = ()
    fetch_stalls: Sequence[Tuple[int, float]] = ()
    pool_exhausted_admits: Sequence[int] = ()
    rebuild_failures: int = 0
    speculative_round_failures: Sequence[int] = ()
    step_failure_rate: float = 0.0
    seed: int = 0

    def __post_init__(self) -> None:
        #: optional Telemetry mirror for injected-fault counters (attach with
        #: ``plan.telemetry = tel``); consulted behind ``is not None`` only
        self.telemetry = None
        self._rng = np.random.default_rng(self.seed)
        self._dispatches = 0
        self._fetches = 0
        self._prefills = 0
        self._admits = 0
        self._rebuilds = 0
        self._spec_rounds = 0
        self._admit_depth = 0
        self._nan_by_step: Dict[int, List[int]] = {}
        for step, slot in self.nan_logits:
            self._nan_by_step.setdefault(int(step), []).append(int(slot))
        self._stall_by_fetch = {int(i): float(ms) for i, ms in self.fetch_stalls}
        #: faults that FIRED, by site slug (the /stats "injected" block)
        self.injected: Dict[str, int] = {}
        #: faults the serving stack OBSERVED AND HANDLED (quarantines taken,
        #: exhausted allocations absorbed, ...) — recovery accounting writes
        #: here via :meth:`note_observed`
        self.observed: Dict[str, int] = {}

    # ------------------------------------------------------------ engine sites

    def _fire(self, site: str, message: str) -> None:
        self.injected[site] = self.injected.get(site, 0) + 1
        if self.telemetry is not None:
            self.telemetry.faults_injected_total.inc(1.0, site)
        raise FaultError(message, site=site)

    def check_step_dispatch(self) -> None:
        """Advance the dispatch counter; raise when this dispatch is scheduled
        to fail (or the storm rate fires)."""
        self._dispatches += 1
        if self._dispatches in set(self.step_dispatch_failures):
            self._fire("step_dispatch", f"injected step-dispatch failure #{self._dispatches}")
        if self.step_failure_rate > 0 and self._rng.random() < self.step_failure_rate:
            self._fire("step_dispatch", f"injected storm step failure #{self._dispatches}")

    def take_nan_slots(self) -> List[int]:
        """Slots whose ``last_logits`` the engine must poison after the
        dispatch just counted (empty almost always)."""
        slots = self._nan_by_step.pop(self._dispatches, [])
        if slots:
            self.injected["nan_logits"] = self.injected.get("nan_logits", 0) + len(slots)
            if self.telemetry is not None:
                self.telemetry.faults_injected_total.inc(float(len(slots)), "nan_logits")
        return slots

    def check_fetch(self) -> None:
        """Advance the fetch counter; raise when this fetch is scheduled to
        fail (the deferred-error shape)."""
        self._fetches += 1
        if self._fetches in set(self.step_fetch_failures):
            self._fire("step_fetch", f"injected token-fetch failure #{self._fetches}")

    def take_fetch_stall_ms(self) -> Optional[float]:
        """Stall (ms) scheduled for the fetch just counted, or ``None``."""
        ms = self._stall_by_fetch.pop(self._fetches, None)
        if ms is not None:
            self.injected["fetch_stall"] = self.injected.get("fetch_stall", 0) + 1
            if self.telemetry is not None:
                self.telemetry.faults_injected_total.inc(1.0, "fetch_stall")
        return ms

    def check_prefill(self) -> None:
        """Advance the prefill counter; raise when this prefill is scheduled
        to fail."""
        self._prefills += 1
        if self._prefills in set(self.prefill_failures):
            self._fire("prefill", f"injected prefill failure #{self._prefills}")

    def begin_admit(self) -> None:
        """Enter an ``admit_many`` call (advances the admission counter at the
        outermost entry; :meth:`pool_exhausted` is scoped to this window)."""
        if self._admit_depth == 0:
            self._admits += 1
            if self._admits in set(self.pool_exhausted_admits):
                self.injected["pool_exhausted"] = self.injected.get("pool_exhausted", 0) + 1
                if self.telemetry is not None:
                    self.telemetry.faults_injected_total.inc(1.0, "pool_exhausted")
        self._admit_depth += 1

    def end_admit(self) -> None:
        self._admit_depth = max(0, self._admit_depth - 1)

    def pool_exhausted(self) -> bool:
        """Whether the block pool must behave fully referenced right now (only
        inside an admission window this plan scheduled)."""
        return self._admit_depth > 0 and self._admits in set(self.pool_exhausted_admits)

    def check_rebuild(self) -> None:
        """Advance the rebuild counter; raise while scheduled rebuild failures
        remain (the supervisor's backoff loop consumes them one per attempt)."""
        self._rebuilds += 1
        if self._rebuilds <= int(self.rebuild_failures):
            self._fire("rebuild", f"injected rebuild failure #{self._rebuilds}")

    def check_speculative_round(self) -> None:
        """Advance the speculative-round counter; raise when scheduled."""
        self._spec_rounds += 1
        if self._spec_rounds in set(self.speculative_round_failures):
            self._fire(
                "speculative_round", f"injected speculative-round failure #{self._spec_rounds}"
            )

    # -------------------------------------------------------------- accounting

    def note_observed(self, kind: str) -> None:
        """Count one injected fault the serving stack handled (quarantine
        taken, exhausted allocation absorbed, stall survived, ...)."""
        self.observed[kind] = self.observed.get(kind, 0) + 1

    def stats(self) -> Dict[str, Dict[str, int]]:
        """The ``/stats`` → ``generation.robustness.faults`` block."""
        return {"injected": dict(self.injected), "observed": dict(self.observed)}
