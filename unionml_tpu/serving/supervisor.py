"""Supervised engine recovery: health state, watchdog, bounded-backoff rebuild.

A serving fleet (ROADMAP item 2) presupposes engines that fail *well*: a
device fault must cost the affected step, not the process; recoverable
requests must resume token-identically; and the failure must be *visible*
(``/healthz``) so a router can drain the replica instead of timing out
against it. :class:`EngineSupervisor` is that layer for one
:class:`~unionml_tpu.serving.continuous.DecodeEngine` behind a
:class:`~unionml_tpu.serving.continuous.ContinuousBatcher`:

- **Health state machine** — ``ok -> degraded -> rebuilding -> ok`` on a
  recovered fault, ``rebuilding -> failed`` when the bounded rebuild budget is
  exhausted. ``/healthz`` serves 503 while ``rebuilding``/``failed`` so load
  balancers stop routing here; ``degraded`` (watchdog trip, quarantine burst)
  still serves.
- **Watchdog** — the engine timestamps a heartbeat at every step dispatch and
  token-fetch completion; a background thread (or a synchronous
  :meth:`check` call in tests) trips when the engine is *busy* but the
  heartbeat goes stale past ``stall_timeout_s`` — the wedged-device-queue
  shape a blocked ``device_get`` produces, which no exception ever reports.
- **Bounded-exponential-backoff rebuild** — the batcher's recovery path runs
  :meth:`run_rebuild`, which retries ``engine.rebuild()`` up to
  ``max_rebuild_attempts`` times with ``backoff_s * 2^k`` (capped) sleeps
  between attempts; exhaustion transitions to ``failed`` and every pending
  request is failed with a structured
  :class:`~unionml_tpu.serving.faults.EngineFailure` instead of hanging.

The supervisor owns POLICY and OBSERVABILITY only: the engine performs the
actual salvage/rebuild (:meth:`DecodeEngine.take_salvage` /
:meth:`DecodeEngine.rebuild`), and the batcher moves the requests — see
``ContinuousBatcher._handle_engine_failure`` for the recovery sequence.
"""

import threading
import time
from typing import Any, Callable, Dict, List, Optional

from unionml_tpu._logging import logger
from unionml_tpu.serving.faults import EngineFailure

__all__ = ["EngineSupervisor", "HEALTH_STATES"]

#: the health state machine's states, in degrading order
HEALTH_STATES = ("ok", "degraded", "rebuilding", "failed")


class EngineSupervisor:
    """Health, watchdog, and rebuild policy for one supervised engine.

    :param stall_timeout_s: heartbeat staleness (while the engine is busy)
        that counts as a stall — trips the watchdog and degrades health.
    :param watchdog_interval_s: background watchdog poll period; ``0``
        disables the thread (tests drive :meth:`check` synchronously).
    :param max_rebuild_attempts: rebuild attempts per failure incident before
        the supervisor gives up and transitions to ``failed``.
    :param backoff_s: initial rebuild backoff; attempt ``k`` sleeps
        ``backoff_s * 2**(k-1)`` (capped at ``backoff_max_s``) before retrying.
    :param backoff_max_s: backoff cap.
    """

    def __init__(
        self,
        *,
        stall_timeout_s: float = 5.0,
        watchdog_interval_s: float = 0.5,
        max_rebuild_attempts: int = 3,
        backoff_s: float = 0.05,
        backoff_max_s: float = 2.0,
        time_fn: Callable[[], float] = time.monotonic,
        sleep_fn: Callable[[float], None] = time.sleep,
        telemetry: Optional[Any] = None,
    ) -> None:
        #: optional Telemetry; every record site runs OUTSIDE _lock (lock-leaf)
        self._telemetry = telemetry
        self.stall_timeout_s = float(stall_timeout_s)
        self.watchdog_interval_s = float(watchdog_interval_s)
        self.max_rebuild_attempts = max(1, int(max_rebuild_attempts))
        self.backoff_s = float(backoff_s)
        self.backoff_max_s = float(backoff_max_s)
        self._time = time_fn
        self._sleep = sleep_fn
        self._lock = threading.Lock()
        self._state = "ok"  # guarded-by: _lock
        self._last_fault: Optional[Dict[str, Any]] = None  # guarded-by: _lock
        self._stalled = False  # current stall episode flag — guarded-by: _lock
        # lifetime counters (the /stats robustness block) — guarded-by: _lock
        self.watchdog_trips = 0  # guarded-by: _lock
        self.failures = 0  # guarded-by: _lock
        self.rebuilds = 0  # guarded-by: _lock
        self.rebuild_attempts = 0  # guarded-by: _lock
        self.recovered_requests = 0  # guarded-by: _lock
        self.failed_requests = 0  # guarded-by: _lock
        #: wall time of the most recent failure->ok transition (ms); the
        #: chaos bench's headline number — guarded-by: _lock
        self.last_recovery_ms: Optional[float] = None  # guarded-by: _lock
        self._failure_at: Optional[float] = None  # guarded-by: _lock
        self._engine: Optional[Any] = None
        self._watchdog: Optional[threading.Thread] = None
        self._stop = threading.Event()
        # state-transition subscribers (a fleet router re-weighting replicas);
        # append-only before traffic starts, so reads need no lock
        self._subscribers: List[Callable[[str, str], None]] = []

    def subscribe(self, callback: Callable[[str, str], None]) -> None:  # fires-outside-lock
        """Register ``callback(old_state, new_state)``, fired on every health
        transition — OUTSIDE the supervisor lock, so a subscriber may read
        supervisor state (or take its own locks) without deadlock. Callbacks
        run on whichever thread drove the transition (worker/watchdog) and
        must be cheap and exception-safe; an exception is logged and dropped.
        Subscribe before attaching traffic: registration is not synchronized
        against concurrent transitions."""
        self._subscribers.append(callback)  # graftlint: disable=data-race -- documented contract (see docstring): append-only before traffic starts; _notify iterates a list() snapshot

    def _notify(self, old: str, new: str) -> None:
        # called OUTSIDE _lock by design (see subscribe) — a subscriber that
        # queries this supervisor or locks a router must not deadlock
        if old == new:
            return
        if self._telemetry is not None:
            self._telemetry.health_transitions_total.inc(1.0, new)
        for callback in list(self._subscribers):
            try:
                callback(old, new)
            except Exception:
                logger.exception("supervisor state subscriber failed (%s -> %s)", old, new)

    # ------------------------------------------------------------------ health

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    @property
    def healthy(self) -> bool:
        """Whether this engine should receive traffic (``ok``/``degraded``)."""
        return self.state in ("ok", "degraded")

    @property
    def last_fault(self) -> Optional[Dict[str, Any]]:
        """``{"reason", "detail", "age_s"}`` of the most recent fault, or None."""
        with self._lock:
            if self._last_fault is None:
                return None
            fault = dict(self._last_fault)
        fault["age_s"] = round(self._time() - fault.pop("at"), 3)
        return fault

    def _record_fault(self, reason: str, detail: str) -> None:
        # graftlint: disable=lock-discipline -- every caller already holds _lock (the helper exists to keep the fault-record shape in one place)
        self._last_fault = {"reason": reason, "detail": detail, "at": self._time()}

    @staticmethod
    def classify(exc: BaseException) -> str:
        """Machine-readable reason slug for an engine-side exception."""
        site = getattr(exc, "site", None)
        if site is not None:
            return f"injected_{site}"
        reason = getattr(exc, "reason", None)
        if reason is not None:
            return str(reason)
        return "device_failure"

    # ----------------------------------------------------------- failure flow

    def note_failure(self, exc: BaseException) -> None:
        """An engine failure was caught: record it and enter ``rebuilding``."""
        with self._lock:
            self.failures += 1
            self._failure_at = self._time()
            self._record_fault(self.classify(exc), str(exc))
            old = self._state
            if self._state != "failed":
                self._state = "rebuilding"
            new = self._state
        self._notify(old, new)
        if self._telemetry is not None:
            self._telemetry.engine_failures_total.inc(1.0, self.classify(exc))
        logger.warning("engine failure (%s): entering recovery", self.classify(exc))

    def run_rebuild(self, rebuild: Callable[[], None]) -> bool:
        """Drive ``rebuild()`` with bounded exponential backoff.

        Returns True on success (health -> ``ok``); False once
        ``max_rebuild_attempts`` attempts failed (health -> ``failed``: the
        engine is declared dead and the caller fails every pending request
        with a structured error).
        """
        for attempt in range(1, self.max_rebuild_attempts + 1):
            with self._lock:
                self.rebuild_attempts += 1
            try:
                rebuild()
            except Exception as exc:
                logger.warning(
                    "engine rebuild attempt %d/%d failed: %s",
                    attempt, self.max_rebuild_attempts, exc,
                )
                with self._lock:
                    self._record_fault(self.classify(exc), f"rebuild failed: {exc}")
                if attempt == self.max_rebuild_attempts:
                    break
                self._sleep(min(self.backoff_s * (2 ** (attempt - 1)), self.backoff_max_s))
                continue
            with self._lock:
                self.rebuilds += 1
                old = self._state
                self._state = "ok"
                self._note_recovery_time()
            self._notify(old, "ok")
            if self._telemetry is not None:
                self._telemetry.rebuilds_total.inc()
            logger.info("engine rebuilt (attempt %d/%d)", attempt, self.max_rebuild_attempts)
            return True
        with self._lock:
            old = self._state
            self._state = "failed"
        self._notify(old, "failed")
        logger.error(
            "engine rebuild exhausted %d attempts; supervisor state FAILED",
            self.max_rebuild_attempts,
        )
        return False

    def _note_recovery_time(self) -> None:
        if self._failure_at is not None:
            self.last_recovery_ms = (self._time() - self._failure_at) * 1e3  # graftlint: disable=lock-discipline -- every caller already holds _lock
            self._failure_at = None  # graftlint: disable=lock-discipline -- every caller already holds _lock

    def note_rebuilt(self) -> None:
        """The engine already rebuilt itself in place at fault time (the
        common case): count it and return to ``ok`` without a retry loop."""
        with self._lock:
            self.rebuilds += 1
            old = self._state
            if self._state == "rebuilding":
                self._state = "ok"
            new = self._state
            self._note_recovery_time()
        self._notify(old, new)
        if self._telemetry is not None:
            self._telemetry.rebuilds_total.inc()

    def note_recovered(self, n: int = 1) -> None:
        """Count requests checkpoint-resumed across a rebuild."""
        with self._lock:
            self.recovered_requests += int(n)

    def note_request_failed(self, n: int = 1) -> None:
        """Count requests an engine failure killed (structured, not hung)."""
        with self._lock:
            self.failed_requests += int(n)

    def unavailable_error(self) -> EngineFailure:
        """The structured error a request gets while the engine cannot serve."""
        state = self.state
        return EngineFailure(
            f"engine is {state}",
            reason="engine_failed" if state == "failed" else "engine_rebuilding",
            retryable=state != "failed",
        )

    # -------------------------------------------------------------- watchdog

    def attach(self, engine: Any) -> None:
        """Bind the supervised engine and start the watchdog thread (when
        ``watchdog_interval_s`` > 0). Called by the owning batcher."""
        self._engine = engine  # graftlint: disable=data-race -- attach() runs once at construction; Thread.start() below orders this write before every _watch read
        if self.watchdog_interval_s > 0 and self._watchdog is None:
            self._watchdog = threading.Thread(
                target=self._watch, name="engine-watchdog", daemon=True
            )
            self._watchdog.start()

    def _watch(self) -> None:
        while not self._stop.wait(self.watchdog_interval_s):
            try:
                self.check()
            except Exception:  # the watchdog must outlive any probe hiccup
                logger.exception("engine watchdog check failed")

    def check(self, now: Optional[float] = None) -> bool:
        """One watchdog evaluation (the thread's body; callable synchronously
        in tests). Trips — once per stall episode — when the engine is busy
        but its heartbeat is older than ``stall_timeout_s``; recovers
        ``degraded -> ok`` when the heartbeat freshens. Returns whether a
        stall is currently observed."""
        engine = self._engine
        if engine is None:
            return False
        now = self._time() if now is None else now
        heartbeat = getattr(engine, "last_heartbeat", None)
        busy = bool(getattr(engine, "busy", False))
        stalled = (
            busy and heartbeat is not None and (now - heartbeat) > self.stall_timeout_s
        )
        with self._lock:
            old = self._state
            if stalled and not self._stalled:
                self._stalled = True
                self.watchdog_trips += 1
                self._record_fault(
                    "watchdog_stall",
                    f"no engine heartbeat for {now - heartbeat:.3f}s while busy",
                )
                if self._state == "ok":
                    self._state = "degraded"
                logger.warning("engine watchdog tripped: heartbeat stale while busy")
            elif not stalled and self._stalled:
                self._stalled = False
                if self._state == "degraded":
                    self._state = "ok"
            new = self._state
        self._notify(old, new)
        return stalled

    def close(self) -> None:
        """Stop the watchdog thread (batcher close)."""
        self._stop.set()
        watchdog = self._watchdog
        if watchdog is not None and watchdog.is_alive():
            watchdog.join(timeout=2.0)

    # ------------------------------------------------------------------- stats

    def stats(self) -> Dict[str, Any]:
        """The ``/stats`` → ``generation.robustness`` supervisor counters."""
        with self._lock:
            return {
                "health": self._state,
                "failures": self.failures,
                "rebuilds": self.rebuilds,
                "rebuild_attempts": self.rebuild_attempts,
                "watchdog_trips": self.watchdog_trips,
                "recovered_requests": self.recovered_requests,
                "failed_requests": self.failed_requests,
                "last_recovery_ms": None
                if self.last_recovery_ms is None
                else round(self.last_recovery_ms, 3),
            }
