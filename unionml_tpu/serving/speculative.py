"""Speculative-decoding serving facade for the ``/generate`` route.

Wraps :func:`unionml_tpu.models.speculative.speculative_generate` behind the
same asyncio contract as :class:`~unionml_tpu.serving.continuous.ContinuousBatcher`
(``await generate(...)``, ``stream(...)``, ``close()``, an ``engine`` view for
``/stats``), so an app serves a draft+target pair by passing this as the
``generator``::

    build_aiohttp_app(model, generator=SpeculativeBatcher(
        target, target_vars, draft, draft_vars, gamma=4))

Speculation is a LATENCY play, not a throughput play: each request decodes
alone (the verify step is batch-1 — see ``models/speculative.py``), so requests
serialize on one worker thread. For concurrent-throughput serving use the
continuous-batching :class:`DecodeEngine` instead; measured on v5e, its decode
lookahead is the throughput lever (TPU_PROBES.log 2026-07-29: 104.6 -> 1343.5
tok/s at k=1 -> 32).
"""

import asyncio
import threading
import time
from types import SimpleNamespace
from typing import Any, List, Optional, Sequence

import jax
import numpy as np

from unionml_tpu._logging import logger

__all__ = ["SpeculativeBatcher"]


class SpeculativeBatcher:
    """Single-stream speculative generation behind the ContinuousBatcher contract.

    Requests route through the same SLO scheduler as the continuous engine
    (:mod:`unionml_tpu.serving.scheduler`): bounded queueing with structured
    shedding, priority-ordered turn-taking for the single decode stream, and
    deadline enforcement while queued — so ``GET /stats`` reports one uniform
    scheduler counter set whichever generator backs ``/generate``. (Preemption
    does not apply: the verify loop is batch-1 with no KV checkpoint to steal.)
    """

    def __init__(
        self,
        target: Any,
        target_variables: Any,
        draft: Any,
        draft_variables: Any,
        *,
        gamma: int = 4,
        max_len: Optional[int] = None,
        scheduler: Optional[Any] = None,
        faults: Optional[Any] = None,
    ) -> None:
        from unionml_tpu.serving.scheduler import SchedulerConfig, SLOScheduler

        self._target = target
        self._target_variables = target_variables
        self._draft = draft
        self._draft_variables = draft_variables
        self._gamma = int(gamma)
        #: deterministic fault injection (:class:`~unionml_tpu.serving.faults.
        #: FaultPlan`); None = production (one host branch per request)
        self._faults = faults
        #: requests that died in a speculative round (structured failures)
        self.round_failures = 0  # guarded-by: _lock
        self._max_len = int(max_len or target.config.max_position_embeddings)
        self._lock = threading.Lock()  # serializes device work across requests
        #: SLO admission control shared-shape with ContinuousBatcher (/stats)
        self.scheduler = (
            scheduler
            if isinstance(scheduler, SLOScheduler)
            else SLOScheduler(scheduler if isinstance(scheduler, SchedulerConfig) else None)
        )
        #: turn-taking for the single stream: executor threads wait here until
        #: the scheduler ranks their ticket first and no request is running
        self._turn = threading.Condition()
        self._current: Optional[Any] = None  # guarded-by: _turn
        self._closed = False
        # persistent evolving key (same contract as DecodeEngine): identical
        # sampled requests must NOT return identical completions unless the
        # client pins an explicit seed
        self._key = jax.random.PRNGKey(0)  # guarded-by: _lock
        # the /stats view; num_slots=1 states the single-stream design honestly.
        # bucket_for is the route's prefill-validation hook: speculation prefills
        # at the exact prompt length (no bucket ladder), so identity is correct.
        # requests_admitted / tokens_decoded / prefill_tokens_computed mirror the
        # continuous engine's generation counters, so the stats route reports the
        # same shape whichever generator is plugged in
        # guarded-by: _lock
        self.engine = SimpleNamespace(
            num_slots=1,
            num_active=0,
            max_len=self._max_len,
            bucket_for=lambda n: n,
            requests_admitted=0,
            tokens_decoded=0,
            prefill_tokens_computed=0,
        )

    # ------------------------------------------------------------------ request path

    def _validate(self, prompt_ids: Sequence[int], max_new_tokens: int, sampling: dict):
        if self._closed:
            raise RuntimeError("SpeculativeBatcher is closed")
        prompt = np.asarray(list(prompt_ids), dtype=np.int32)
        if prompt.ndim != 1 or prompt.size == 0:
            raise ValueError("prompt_ids must be a non-empty 1-D token list")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if prompt.size + max_new_tokens + self._gamma + 1 > self._max_len:
            raise ValueError(
                f"prompt ({prompt.size}) + max_new_tokens ({max_new_tokens}) + gamma slack "
                f"({self._gamma + 1}) exceeds max_len ({self._max_len})"
            )
        if sampling.get("top_k") or sampling.get("top_p") not in (None, 1.0):
            raise ValueError("speculative decoding supports temperature sampling only (no top_k/top_p)")
        temperature = float(sampling.get("temperature", 0.0) or 0.0)
        seed = sampling.get("seed")
        return prompt, temperature, seed

    def _await_turn(self, ticket) -> None:
        """Block until the scheduler ranks ``ticket`` first and the stream is
        free. Raises the ticket's shed error when a later, higher-class submit
        displaced it, and :class:`DeadlineExceededError` when its deadline
        passes while queued — the same structured rejections the continuous
        path surfaces."""
        from unionml_tpu.serving.scheduler import DeadlineExceededError

        with self._turn:
            while True:
                if self._closed:
                    self.scheduler.remove(ticket)
                    raise RuntimeError("SpeculativeBatcher is closed")
                if ticket.shed_exc is not None:  # displaced under a full queue
                    raise ticket.shed_exc
                if ticket.expired(time.monotonic()):
                    # removes this ticket (and any expired peers — their own
                    # waiting threads raise on their next poll) and counts the
                    # queued deadline misses
                    self.scheduler.take_expired()
                    raise DeadlineExceededError("deadline expired while queued")
                if self._current is None and self.scheduler.peek() is ticket:
                    if not self.scheduler.pop_ticket(ticket):
                        raise RuntimeError("ticket vanished from the scheduler queue")
                    self._current = ticket
                    return
                self._turn.wait(timeout=0.02)

    def _end_turn(self) -> None:
        with self._turn:
            self._current = None
            self._turn.notify_all()

    def _run(self, ticket, prompt: np.ndarray, max_new_tokens: int, temperature: float, seed) -> List[int]:
        self._await_turn(ticket)
        try:
            return self._run_current(prompt, max_new_tokens, temperature, seed)
        finally:
            self._end_turn()

    def _run_current(self, prompt: np.ndarray, max_new_tokens: int, temperature: float, seed) -> List[int]:
        from unionml_tpu.models.speculative import speculative_generate
        from unionml_tpu.serving.faults import EngineFailure

        with self._lock:
            if seed is not None:
                rng = jax.random.PRNGKey(int(seed))
            else:
                self._key, rng = jax.random.split(self._key)
            self.engine.num_active = 1
            self.engine.requests_admitted += 1
            try:
                if self._faults is not None:
                    self._faults.check_speculative_round()
                # graftlint: disable=lock-order -- _lock EXISTS to serialize device work across requests (single-stream design, see class docstring); blocking under it is the design, and _await_turn admits exactly one holder
                out = speculative_generate(
                    self._target,
                    self._target_variables,
                    self._draft,
                    self._draft_variables,
                    jax.device_put(prompt)[None, :],  # explicit: keeps the entry path transfer-guard-clean
                    max_new_tokens,
                    gamma=self._gamma,
                    temperature=temperature,
                    rng=rng,
                )
            except Exception as exc:
                # every round's device state is call-local (no persistent KV or
                # donated engine buffers), so a failure costs exactly this
                # request — structured, and the next request runs clean
                self.round_failures += 1
                logger.warning("speculative round failed: %s", exc)
                raise EngineFailure(
                    f"speculative round failed: {exc}", reason="speculative_round_failed"
                ) from exc
            finally:
                self.engine.num_active = 0
            tokens = [int(t) for t in np.asarray(out)[0, prompt.size :]]
            # counter updates stay under the lock: concurrent requests (each on
            # its own executor thread) race read-modify-write otherwise — the
            # lock-discipline lint finding that motivated this placement
            self.engine.prefill_tokens_computed += int(prompt.size)
            self.engine.tokens_decoded += len(tokens)
        return tokens

    async def generate(
        self,
        prompt_ids: Sequence[int],
        max_new_tokens: int,
        *,
        priority: Any = None,
        deadline_ms: Optional[float] = None,
        **sampling,
    ) -> List[int]:
        prompt, temperature, seed = self._validate(prompt_ids, max_new_tokens, sampling)
        # admission control BEFORE any device work: shed errors (queue full /
        # deadline infeasible) raise here, on the caller's side, exactly like
        # the continuous path
        ticket = self.scheduler.make_ticket(
            prompt, int(max_new_tokens), sampling, None,
            priority=priority, deadline_ms=deadline_ms,
        )
        displaced = self.scheduler.submit(ticket)
        if displaced is not None:
            with self._turn:  # wake the displaced ticket's waiting thread
                self._turn.notify_all()
        loop = asyncio.get_running_loop()
        return await loop.run_in_executor(
            None, self._run, ticket, prompt, max_new_tokens, temperature, seed
        )

    async def stream(
        self,
        prompt_ids: Sequence[int],
        max_new_tokens: int,
        *,
        priority: Any = None,
        deadline_ms: Optional[float] = None,
        **sampling,
    ):
        """Async iterator of tokens. Tokens arrive in one burst at completion:
        speculation verifies whole proposal rounds, so there is no per-token
        decode step to stream from (use the continuous engine for live streams)."""
        for token in await self.generate(
            prompt_ids, max_new_tokens, priority=priority, deadline_ms=deadline_ms, **sampling
        ):
            yield token

    def close(self) -> None:
        self._closed = True
        with self._turn:  # wake queued waiters so they fail promptly, not on poll
            self._turn.notify_all()
        logger.info("SpeculativeBatcher closed.")
