"""Speculative decoding for the production paged engine (and a legacy facade).

Two generations live here:

- :class:`SpeculativeEngine` — speculative decoding as a first-class MODE of
  the continuous-batching :class:`~unionml_tpu.serving.continuous.DecodeEngine`
  (ISSUE 16). Draft and target share ONE block-table/allocator/id space: the
  draft's K/V lives in a parallel set of pool leaves indexed by the same block
  ids, so prefix-cache splices, preempt-to-cache, salvage, and failover apply
  to speculative requests with zero new block accounting. Rounds (propose-γ +
  verify + accept/commit + adaptive-γ update) are ONE jitted program that
  dispatches ahead exactly like the PR-3 pipeline and pays one deferred fetch —
  zero steady-state host→device uploads. γ adapts per request from an
  acceptance EMA, decaying to 0 (≈ vanilla) on adversarial traffic.

- :class:`SpeculativeBatcher` — the legacy single-stream ``/generate`` facade
  over :func:`unionml_tpu.models.speculative.speculative_generate` (dense
  caches, fixed γ, batch-1 verify). Kept for apps that want the zero-setup
  latency play; everything throughput-shaped should use the engine mode.

Why the engine's rounds are EXACT (token-identical to vanilla decode, greedy
and fixed-seed sampled): every token selection — the round's bonus token, the
draft's proposals, and the target's per-position choices — goes through ONE
selection rule keyed by ``fold_in(slot_key, position)``. A proposal is
accepted iff it EQUALS the target's own selection at that position, so the
emitted stream is, position by position, exactly the sequence the target
alone would have selected; the draft merely prepays verification compute
(common random numbers make the draft agree often, which is where the
accepted-tokens-per-target-step > 1 comes from). The carried ``last_logits``
always follows the last FED token, and the commit writes exactly the emitted
tokens — so the pool trajectory matches vanilla decode byte-for-byte on fp32
pools (int8 pools ride the pinned divergence budget vs the PLAIN engine, and
are bitwise between spec-on and spec-off arms, which share this program).
"""

import asyncio
import threading
import time
from types import SimpleNamespace
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from unionml_tpu._logging import logger
from unionml_tpu.serving.continuous import DecodeEngine

__all__ = ["SpeculativeBatcher", "SpeculativeEngine"]


class SpeculativeEngine(DecodeEngine):
    """Continuous-batching decode engine with adaptive speculative rounds.

    A drop-in :class:`DecodeEngine` (paged mode required) that additionally
    holds a DRAFT model whose K/V rides the same block tables as the target's:
    ``self._draft_pool`` is a second set of pool leaves (draft shapes, same
    block ids), so allocation, splice, preempt, salvage, and failover stay
    oblivious to speculation. Requests opt in per admission via the sampling
    dict — ``{"speculative": True, "seed": ..., "gamma": ...}`` — which the
    SLO scheduler sets per class (interactive on, batch off).

    **Round program.** When any active slot is speculative (or samples — keyed
    selection needs the round program either way), :meth:`_dispatch_step`
    swaps the base burst for ONE jitted round: select the bonus token e0 from
    ``last_logits``; draft-propose up to ``gamma_max`` continuations (common
    keyed selection); verify the S = ``gamma_max``+1 chunk through the paged
    verify kernel (pool untouched — :func:`unionml_tpu.models.gpt.
    _paged_verify_chunk`); accept the longest prefix of proposals that equal
    the target's own selections; emit ``a+1`` tokens through the standard
    (tokens, masks, bads) burst contract with the vanilla retirement rule
    inlined per emission; commit exactly the emitted tokens
    (:func:`~unionml_tpu.models.gpt.paged_commit_chunk` — no γ block slack:
    draft overshoot lands in the scratch column); and update the per-slot
    acceptance EMA and γ device-side. The host replays the fetched masks to
    mirror the EMA/γ rule (retiring slots mis-estimate their last round,
    harmlessly — they re-arm at next admission).

    **Per-request γ=0 is sticky** until the slot re-arms: collapsed acceptance
    degrades a request to vanilla decode (1 emitted token per round, always ≥
    the baseline in accepted-tokens-per-target-step) rather than oscillating.

    **Key discipline.** Rounds never consume the engine's global PRNG key:
    sampled selection is (slot_key, position)-keyed, so token streams are
    independent of dispatch boundaries, pipelining, and sibling admissions.
    The base replay's ``_key_steps`` bookkeeping overcounts splits that round
    bursts never performed; this is harmless because no spec-engine sampled
    stream reads the global key (greedy streams never did).

    **Not supported:** ``top_k``/``top_p`` (engine-wide — any sampling slot
    routes every burst through the round program, whose keyed selection
    implements temperature only), dense (non-paged) mode, and speculation on
    chunked-prefill admissions (the request decodes vanilla instead).
    """

    def __init__(
        self,
        model: Any,
        variables: Any,
        draft: Any,
        draft_variables: Any,
        *,
        gamma_max: int = 4,
        gamma_init: int = 2,
        ema_beta: float = 0.25,
        ema_hi: float = 0.6,
        ema_lo: float = 0.3,
        **kwargs: Any,
    ) -> None:
        if not kwargs.get("paged", True):
            raise ValueError("SpeculativeEngine requires paged=True (the shared block pool)")
        kwargs["paged"] = True
        if int(gamma_max) < 1:
            raise ValueError(f"gamma_max must be >= 1, got {gamma_max}")
        if not 0 <= int(gamma_init) <= int(gamma_max):
            raise ValueError(f"gamma_init must be in [0, gamma_max], got {gamma_init}")
        if not 0.0 < float(ema_beta) <= 1.0:
            raise ValueError(f"ema_beta must be in (0, 1], got {ema_beta}")
        if not 0.0 <= float(ema_lo) < float(ema_hi) <= 1.0:
            raise ValueError(f"need 0 <= ema_lo < ema_hi <= 1, got lo={ema_lo} hi={ema_hi}")
        if draft.config.vocab_size != model.config.vocab_size:
            raise ValueError(
                f"draft vocab ({draft.config.vocab_size}) != target vocab "
                f"({model.config.vocab_size}): acceptance compares token ids"
            )
        eff_max_len = int(kwargs.get("max_len") or model.config.max_position_embeddings)
        if draft.config.max_position_embeddings < eff_max_len:
            raise ValueError(
                f"draft max_position_embeddings ({draft.config.max_position_embeddings}) "
                f"< engine max_len ({eff_max_len})"
            )
        # everything _init_device_state (called inside super().__init__) reads
        self._draft_model = draft
        self._draft_config = draft.config
        self._draft_cache_sharding = None
        self._gamma_max = int(gamma_max)
        self._gamma_init = int(gamma_init)
        self._ema_beta = float(ema_beta)
        self._ema_hi = float(ema_hi)
        self._ema_lo = float(ema_lo)

        super().__init__(model, variables, **kwargs)

        # draft params: replicated under a mesh (the draft is small by design;
        # its K/V pool is what scales, and that shards via kv_block_spec below)
        if self._mesh is not None:
            draft_variables = jax.device_put(draft_variables, self._replicated)
        self._draft_variables = draft_variables

        # re-derive the weight-dequant hook (an __init__ local in the base)
        if kwargs.get("quantize") == "int8":
            from unionml_tpu.ops.quant import dequantize_tree

            self._maybe_dequant = dequantize_tree
        else:
            self._maybe_dequant = lambda tree: tree

        #: compiled round programs keyed by the trace-time sampling switch
        self._round_fns: Dict[bool, Any] = {}
        #: per-request class labels (batcher-set) for the acceptance gauge
        self._slot_class: Dict[int, str] = {}
        # lifetime counters (survive rebuilds — they describe served traffic)
        self.spec_rounds = 0  #: round bursts replayed
        self.spec_slot_rounds = 0  #: (slot, round) pairs that ran with γ > 0
        self.spec_proposed = 0  #: draft tokens proposed by ran slot-rounds
        self.spec_accepted = 0  #: proposals accepted by verification
        self.spec_fallback_rounds = 0  #: speculative slots decoding with γ = 0
        self.spec_round_dispatches = 0
        self.draft_prefill_dispatches = 0
        self._spec_admissions = 0  # seeds derived-key arming deterministically

        def _spec_update(gamma, ema, t_prev, keys, slot, g0, e0, t0, key_row):
            """Point-update one slot's speculative device state at arming
            (same pipelining-safe discipline as ``_slot_update``)."""
            return (
                gamma.at[slot].set(g0),
                ema.at[slot].set(e0),
                t_prev.at[slot].set(t0),
                keys.at[slot].set(key_row),
            )

        self._spec_update_fn = jax.jit(_spec_update, donate_argnums=(0, 1, 2, 3))

        def _constrain_draft(tree):
            if self._draft_cache_sharding is None:
                return tree
            return jax.tree_util.tree_map(
                lambda leaf: jax.lax.with_sharding_constraint(leaf, self._draft_cache_sharding),
                tree,
            )

        self._constrain_draft = _constrain_draft

        def _draft_chunk(d_variables, chunk_ids, d_pool, tables, slot, position):
            """Draft full-prompt prefill straight into the slot's SHARED table
            row (the draft twin of ``_paged_chunk``; logits discarded — rounds
            recompute the draft state they need from the committed stream).
            Bucket padding past the prompt writes zeros the round feeds
            overwrite before any attention reads them (feed contiguity)."""
            row = jax.lax.dynamic_slice_in_dim(tables, slot, 1, axis=0)
            cache = {"table": row, **d_pool}
            _, new_cache = draft.apply(d_variables, chunk_ids, cache=cache, position=position)
            return _constrain_draft(
                {name: leaf for name, leaf in new_cache.items() if name != "table"}
            )

        self._draft_chunk_fn = jax.jit(_draft_chunk, donate_argnums=(2,))

    # ------------------------------------------------------------------ round program

    def _make_round(self, sampling: bool):
        """Compile the fused speculative round (see the class docstring for the
        structure). ``sampling`` is the same trace-time switch as the base step
        family: the greedy program is pure argmax everywhere."""
        model, draft = self._model, self._draft_model
        maybe_dequant = self._maybe_dequant
        constrain_draft = self._constrain_draft
        max_len, eos = self.max_len, self.eos_token_id
        S = self._gamma_max + 1
        gamma_max = self._gamma_max
        beta, hi, lo = self._ema_beta, self._ema_hi, self._ema_lo
        cache_sharding = self._cache_sharding

        def constrain(tree):
            if cache_sharding is None:
                return tree
            return jax.tree_util.tree_map(
                lambda leaf: jax.lax.with_sharding_constraint(leaf, cache_sharding), tree
            )

        def _round(
            variables, d_variables, pool, d_pool, tables,
            last_logits, lens, active, remaining, gamma, ema, t_prev, slot_keys, temp,
        ):
            from unionml_tpu.models.gpt import paged_commit_chunk

            variables = maybe_dequant(variables)
            # graftlint: disable=retrace -- trace-time reads, exactly like the base paged programs: a pool re-layout changes leaf/table shapes and forces the retrace that re-reads them
            sentinel = (self._table_width - 1) * self._prefix_block_size

            def select(logits, positions):
                """THE selection rule (bonus, proposals, and verification all
                use it): greedy argmax, or a per-(slot, position) keyed
                categorical at the slot's temperature — so the same position
                always draws the same token regardless of which program (or
                which round boundary) evaluates it."""
                greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                if not sampling:
                    return greedy
                keys = jax.vmap(jax.random.fold_in)(slot_keys, positions.astype(jnp.uint32))
                scaled = logits.astype(jnp.float32) / jnp.maximum(temp, 1e-6)[:, None]
                drawn = jax.vmap(jax.random.categorical)(keys, scaled)
                return jnp.where(temp > 0, drawn.astype(jnp.int32), greedy)

            drafting = active & (gamma > 0)
            e0 = select(last_logits, lens)

            # ---- draft: heal + propose (correctness-free: affects only α) ----
            # heal position lens-1: the previous round's LAST accepted proposal
            # was selected but never fed to the draft, so re-append the last
            # committed token (idempotent when already present — same value,
            # same block, and int8 re-quantization of an identical row is a
            # fixed point of the monotone-scale append)
            dcache = {"table": tables, **d_pool}
            heal_pos = jnp.where(drafting, jnp.maximum(lens - 1, 0), sentinel)
            _, dcache = draft.apply(d_variables, t_prev[:, None], cache=dcache, position=heal_pos)
            dlog, dcache = draft.apply(
                d_variables, e0[:, None], cache=dcache,
                position=jnp.where(drafting, lens, sentinel),
            )
            cur = dlog[:, -1, :]
            props = []
            for j in range(1, S):
                d_j = select(cur, lens + j)
                props.append(d_j)
                if j < S - 1:
                    dlog, dcache = draft.apply(
                        d_variables, d_j[:, None], cache=dcache,
                        position=jnp.where(drafting, lens + j, sentinel),
                    )
                    cur = dlog[:, -1, :]
            new_d_pool = constrain_draft(
                {name: leaf for name, leaf in dcache.items() if name != "table"}
            )

            # ---- verify: one S-token target pass, pool untouched ----
            chunk = jnp.concatenate([e0[:, None]] + [p[:, None] for p in props], axis=1)
            cache = {"table": tables, **pool}
            vlogits, vcache = model.apply(
                variables, chunk, cache=cache,
                position=jnp.where(active, lens, sentinel),
            )

            # ---- accept: longest prefix of proposals matching the target ----
            sel = jnp.stack(
                [select(vlogits[:, j, :], lens + 1 + j) for j in range(S - 1)], axis=1
            )  # target's own choice for position lens+1+j
            ok = (
                (chunk[:, 1:] == sel)
                & (jnp.arange(1, S)[None, :] <= gamma[:, None])
                & active[:, None]
            )
            acc = jnp.cumprod(ok.astype(jnp.int32), axis=1)
            a = acc.sum(axis=1)
            plan = a + 1  # bonus token always emits

            # ---- emit: a+1 tokens under the vanilla retirement rule ----
            act, rem, cur_lens = active, remaining, lens
            toks_rows, mask_rows, bad_rows = [], [], []
            for j in range(S):
                tok = chunk[:, j]
                em = act & (j < plan)
                src = last_logits if j == 0 else vlogits[:, j - 1, :]
                bad_rows.append(~jnp.all(jnp.isfinite(src), axis=-1))
                toks_rows.append(tok)
                mask_rows.append(em)
                new_rem = jnp.where(em, rem - 1, rem)
                new_l = jnp.where(em, jnp.minimum(cur_lens + 1, max_len - 1), cur_lens)
                finished = (new_rem <= 0) | (new_l >= max_len - 1)
                if eos is not None:
                    finished = finished | (tok == eos)
                act = act & ~(em & finished)
                rem, cur_lens = new_rem, new_l
            masks = jnp.stack(mask_rows, axis=0)  # (S, n): the burst contract
            m = masks.astype(jnp.int32).sum(axis=0)  # tokens fed+emitted per row

            # ---- commit exactly the emitted tokens into the target pool ----
            new_pool = {}
            for name in pool:
                layer = {k: v for k, v in vcache[name].items() if k not in ("ck", "cv")}
                new_pool[name] = paged_commit_chunk(
                    layer, tables, lens, m, vcache[name]["ck"], vcache[name]["cv"]
                )
            new_pool = constrain(new_pool)

            # ---- carry: last_logits follows the last fed token ----
            last_idx = jnp.clip(m - 1, 0, S - 1)
            fed = jnp.take_along_axis(vlogits, last_idx[:, None, None], axis=1)[:, 0, :]
            new_last_logits = jnp.where((m > 0)[:, None], fed, last_logits)
            new_t_prev = jnp.where(
                m > 0, jnp.take_along_axis(chunk, last_idx[:, None], axis=1)[:, 0], t_prev
            )

            # ---- adaptive γ from the acceptance EMA (γ=0 is sticky) ----
            alpha = a.astype(jnp.float32) / jnp.maximum(gamma, 1).astype(jnp.float32)
            new_ema = jnp.where(drafting, (1.0 - beta) * ema + beta * alpha, ema)
            bump = (new_ema >= hi).astype(jnp.int32) - (new_ema < lo).astype(jnp.int32)
            new_gamma = jnp.where(drafting, jnp.clip(gamma + bump, 0, gamma_max), gamma)

            return (
                new_pool, new_d_pool, new_last_logits, cur_lens, act, rem,
                new_gamma, new_ema, new_t_prev,
                jnp.stack(toks_rows, axis=0), masks, jnp.stack(bad_rows, axis=0),
            )

        # donate the KV pools, the sampling logits, and the spec carries the
        # round replaces; tables/keys/temp ride as plain inputs (admission-only
        # point updates, same discipline as the base step family)
        return jax.jit(_round, donate_argnums=(2, 3, 5, 9, 10, 11))

    # ------------------------------------------------------------------ device state

    def _init_device_state(self) -> None:
        super()._init_device_state()
        from unionml_tpu.models.gpt import init_block_pool

        if self._mesh is not None and self._draft_cache_sharding is None:
            from jax.sharding import NamedSharding, PartitionSpec

            from unionml_tpu.models.gpt import kv_cache_spec
            from unionml_tpu.parallel.mesh import TENSOR_AXIS

            spec = kv_cache_spec(self._draft_config, tuple(self._mesh.axis_names))
            tensor_size = (
                int(self._mesh.shape[TENSOR_AXIS])
                if TENSOR_AXIS in self._mesh.axis_names
                else 1
            )
            if self._draft_config.num_heads % max(tensor_size, 1) != 0:
                spec = PartitionSpec()  # draft heads don't divide: replicate
            self._draft_cache_sharding = NamedSharding(self._mesh, spec)
        # the draft pool mirrors the target pool block-for-block (same ids,
        # same tables, draft leaf shapes); every draft layer quantizes under
        # kv_quantize — the draft is correctness-free, so no skip list
        d_pool = init_block_pool(
            self._draft_config, self.pool_blocks, self._prefix_block_size,
            kv_quantize=self.kv_quantize,
        )
        gamma = jnp.zeros((self.num_slots,), jnp.int32)
        ema = jnp.ones((self.num_slots,), jnp.float32)
        t_prev = jnp.zeros((self.num_slots,), jnp.int32)
        keys = jnp.zeros((self.num_slots, 2), jnp.uint32)
        if self._mesh is not None:
            d_pool = jax.device_put(d_pool, self._draft_cache_sharding)
            gamma = jax.device_put(gamma, self._replicated)
            ema = jax.device_put(ema, self._replicated)
            t_prev = jax.device_put(t_prev, self._replicated)
            keys = jax.device_put(keys, self._replicated)
        self._draft_pool = d_pool
        self._gamma_dev, self._ema_dev = gamma, ema
        self._tprev_dev, self._keys_dev = t_prev, keys
        # host mirrors of the device EMA/γ rule (replayed from fetched masks)
        self._slot_gamma = np.zeros(self.num_slots, dtype=np.int32)
        self._slot_ema = np.ones(self.num_slots, dtype=np.float32)
        self._slot_spec = np.zeros(self.num_slots, dtype=bool)
        #: id(masks) of in-flight ROUND bursts (vs base bursts) for replay
        self._round_bursts: Dict[int, bool] = {}

    # ------------------------------------------------------------------ admission

    def validate_request(
        self,
        prompt_ids: Sequence[int],
        max_new_tokens: int,
        *,
        speculative: Optional[bool] = None,
        seed: Optional[int] = None,
        gamma: Optional[int] = None,
        **sampling: Any,
    ) -> Tuple[np.ndarray, int, float, int, float]:
        """Base validation plus the speculative-mode restrictions; the spec
        keys (``speculative``/``seed``/``gamma``) are accepted and ignored so
        batcher-side validation can pass the full sampling dict through.

        Note the engine needs NO γ slack in max_len or the block pool: the
        verify pass never writes the pool, the commit writes only emitted
        tokens, and draft overshoot lands in the scratch column — so a request
        admissible to the vanilla engine is admissible here (contrast the
        legacy facade, whose dense working window reserves ``gamma + 1``)."""
        self._reject_unsupported_sampling(sampling)
        return super().validate_request(prompt_ids, max_new_tokens, **sampling)

    @staticmethod
    def _reject_unsupported_sampling(sampling: Dict[str, Any]) -> None:
        if sampling.get("top_k") or sampling.get("top_p") not in (None, 1.0):
            # engine-wide, not per-request: one sampling sibling routes EVERY
            # burst through the round program, whose keyed selection implements
            # temperature only
            raise ValueError(
                "speculative engine supports temperature sampling only (no top_k/top_p)"
            )

    def admit_many(self, requests: Sequence[Tuple]) -> List[int]:
        """Admit requests, peeling the speculative controls from each sampling
        dict BEFORE the base admission (its 5-tuple normalization stays
        untouched), then ARM each admitted slot: point-update its γ/EMA/key
        device rows and run the draft's full-prompt prefill through the shared
        table row. Arming re-runs the WHOLE prompt on the draft even when the
        target admission was a prefix-cache hit — that is the draft-side
        splice: shared spliced blocks get their draft leaves (re)written with
        identical content (idempotent), which also self-heals prefixes donated
        by non-speculative requests that never wrote draft KV."""
        peeled, spec_args = [], []
        for req in requests:
            sampling = dict(req[2]) if len(req) > 2 and req[2] else {}
            spec = bool(sampling.pop("speculative", False))
            seed = sampling.pop("seed", None)
            gamma = sampling.pop("gamma", None)
            self._reject_unsupported_sampling(sampling)
            peeled.append((req[0], req[1], sampling))
            spec_args.append((spec, seed, gamma))
        slots = super().admit_many(peeled)
        try:
            for slot, req, (spec, seed, gamma) in zip(slots, peeled, spec_args):
                prompt = np.asarray(req[0], dtype=np.int32).reshape(-1)
                self._arm_slot(slot, prompt, spec, seed, gamma)
        except Exception:
            # arming dispatches donate spec device state: a failure here is a
            # device failure (the base admission already committed the slots)
            self._on_failure()
            raise
        return slots

    def _arm_slot(
        self, slot: int, prompt: np.ndarray, spec: bool, seed: Optional[int], gamma: Optional[int]
    ) -> None:
        armed = spec and slot not in self._partials
        bucket = None
        if armed:
            try:
                bucket = self.bucket_for(int(prompt.size))
            except ValueError:
                armed = False  # admissible only via prefix/chunk paths: decode vanilla
        g0 = 0
        if armed:
            g0 = self._gamma_init if gamma is None else max(0, min(int(gamma), self._gamma_max))
        self._slot_spec[slot] = armed
        self._slot_gamma[slot] = g0
        self._slot_ema[slot] = 1.0
        if seed is None:
            # deterministic derived key: identical admission sequences (e.g.
            # the two arms of an A/B bench) draw identical per-slot keys
            seed = self._seed * 1_000_003 + self._spec_admissions
        self._spec_admissions += 1
        key_row = np.array(
            [(int(seed) >> 32) & 0xFFFFFFFF, int(seed) & 0xFFFFFFFF], dtype=np.uint32
        )
        scalars = jax.device_put(
            (np.int32(slot), np.int32(g0), np.float32(1.0), np.int32(prompt[-1]), key_row)
        )
        try:
            (self._gamma_dev, self._ema_dev, self._tprev_dev, self._keys_dev) = (
                self._spec_update_fn(
                    self._gamma_dev, self._ema_dev, self._tprev_dev, self._keys_dev, *scalars
                )
            )
        except Exception:
            self._device_poisoned = True
            raise
        if armed:
            self._draft_prefill(slot, prompt, bucket)

    # transfers: kv-block (draft leaves ride the slot's existing block grant)
    def _draft_prefill(self, slot: int, prompt: np.ndarray, bucket: int) -> None:
        """Write the full prompt's draft K/V through ``slot``'s table row
        (bucket-padded, one dispatch). The draft pool is DONATED: a dispatch
        death poisons the device state like any paged chunk failure."""
        ids = np.zeros((1, bucket), dtype=np.int32)
        ids[0, : prompt.size] = prompt
        try:
            self._draft_pool = self._draft_chunk_fn(
                self._draft_variables, jax.device_put(ids), self._draft_pool,
                self._tables, *jax.device_put((np.int32(slot), np.int32(0))),
            )
        except Exception:
            self._device_poisoned = True
            raise
        self.draft_prefill_dispatches += 1
        if self._telemetry is not None:
            self._note_span(slot, "draft_prefill", tokens=int(prompt.size), bucket=int(bucket))

    def note_request_class(self, slot: int, cls: Optional[str]) -> None:
        """Label ``slot``'s occupant with its SLO class (batcher-set) so the
        acceptance gauge can report per class."""
        if cls is not None:
            self._slot_class[slot] = str(cls)

    # ------------------------------------------------------------------ dispatch/replay

    def _dispatch_step(self, lookahead: int) -> Tuple[Any, Any, Any, int]:
        """Route to the round program whenever any active slot speculates or
        samples; otherwise the base (all-greedy) burst — whose argmax emissions
        are exactly the round program's greedy selection, so the stream is
        dispatch-kind-independent. A round ignores ``lookahead``: it already
        fuses up to S = ``gamma_max``+1 emissions into one dispatch."""
        run_round = bool((self._active & (self._slot_spec | (self._slot_temp > 0))).any())
        if not run_round:
            return super()._dispatch_step(lookahead)
        sampling = bool((self._slot_temp[self._active] > 0).any())
        fn = self._round_fns.get(sampling)
        if fn is None:
            fn = self._round_fns[sampling] = self._make_round(sampling)
        if self._faults is not None:
            self._faults.check_step_dispatch()
        # graftlint: disable=use-after-donate -- _make_round donates argnums (2, 3, 5, 9, 10, 11): both pools, last_logits, and the spec carries; tables/keys/temp are plain inputs
        (
            self._pool,
            self._draft_pool,
            self._last_logits,
            self._lens,
            self._active_dev,
            self._remaining_dev,
            self._gamma_dev,
            self._ema_dev,
            self._tprev_dev,
            tokens,
            masks,
            bads,
        ) = fn(
            self._variables, self._draft_variables, self._pool, self._draft_pool,
            self._tables, self._last_logits, self._lens, self._active_dev,
            self._remaining_dev, self._gamma_dev, self._ema_dev, self._tprev_dev,
            self._keys_dev, self._temp_dev,
        )
        self._round_bursts[id(masks)] = True
        self.spec_round_dispatches += 1
        return tokens, masks, bads, self._gamma_max + 1

    def _replay_burst(self, burst, skip=frozenset()):
        """Base replay plus, for round bursts, the host-side mirror of the
        device EMA/γ rule: each clean event per slot is one FED token, so
        ``a = fed - 1`` recovers the acceptance count (a slot that retired
        mid-round under-counts its LAST round only — its spec state dies with
        it). Also feeds the speculation counters, span, and gauges."""
        is_round = bool(self._round_bursts.pop(id(burst[1]), False))
        if not is_round:
            return super()._replay_burst(burst, skip)
        gammas_at_dispatch = self._slot_gamma.copy()
        spec_at_dispatch = self._slot_spec.copy()
        events = super()._replay_burst(burst, skip)
        fed: Dict[int, int] = {}
        for ev in events:
            if ev.error is None:
                fed[ev.slot] = fed.get(ev.slot, 0) + 1
        self.spec_rounds += 1
        telemetry = self._telemetry
        for slot, m in fed.items():
            if not spec_at_dispatch[slot]:
                continue
            g = int(gammas_at_dispatch[slot])
            if g <= 0:
                self.spec_fallback_rounds += 1
                continue
            a = max(0, min(m - 1, g))
            self.spec_slot_rounds += 1
            self.spec_proposed += g
            self.spec_accepted += a
            alpha = a / g
            ema = (1.0 - self._ema_beta) * float(self._slot_ema[slot]) + self._ema_beta * alpha
            self._slot_ema[slot] = ema
            bump = 1 if ema >= self._ema_hi else (-1 if ema < self._ema_lo else 0)
            self._slot_gamma[slot] = min(self._gamma_max, max(0, g + bump))
            if telemetry is not None:
                telemetry.spec_proposed_total.inc(float(g))
                telemetry.spec_accepted_total.inc(float(a))
                self._note_span(slot, "speculation", gamma=g, accepted=a, alpha=round(alpha, 4))
        if telemetry is not None:
            live = self._active & self._slot_spec
            by_class: Dict[str, List[float]] = {}
            for slot in np.flatnonzero(live):
                cls = self._slot_class.get(int(slot), "standard")
                by_class.setdefault(cls, []).append(float(self._slot_ema[int(slot)]))
            for cls, vals in by_class.items():
                telemetry.spec_acceptance.set(sum(vals) / len(vals), cls)
            if live.any():
                telemetry.spec_gamma.set(float(self._slot_gamma[live].mean()))
        return events

    def abort_all(self) -> None:
        super().abort_all()
        # in-flight round bursts were discarded with the pipeline; stale ids
        # must not collide with a future burst's id()
        self._round_bursts.clear()
        self._slot_spec[:] = False
        self._slot_gamma[:] = 0
        self._slot_class.clear()

    # ------------------------------------------------------------------ observability

    def kv_pool_stats(self) -> Dict[str, Any]:
        """Base pool accounting plus the draft leaves: the equal-byte A/B
        contract charges speculation for EVERY byte it keeps resident."""
        stats = super().kv_pool_stats()
        if stats and getattr(self, "_draft_pool", None) is not None:
            from unionml_tpu.models.gpt import kv_pool_bytes

            stored, full = kv_pool_bytes(self._draft_pool, self._draft_config.dtype)
            stats["kv_pool_bytes"] += stored
            stats["kv_pool_bytes_dense_equiv"] += full
            stats["draft_kv_pool_bytes"] = stored
        return stats

    def speculation_stats(self) -> Dict[str, Any]:
        """The ``generation.speculation`` block for ``GET /stats``.

        ``accepted_per_target_step`` counts EVERY armed slot-round as one
        target forward pass — including γ-decayed-to-0 fallback rounds, which
        emit exactly their bonus token — so the ratio is honest about
        adaptive degradation: vanilla decode is 1.0, and a collapsed-α
        workload converges to 1.0 rather than being dropped from the metric."""
        live = self._active & self._slot_spec
        ran = max(1, self.spec_slot_rounds + self.spec_fallback_rounds)
        return {
            "enabled_slots": int(live.sum()),
            "gamma_max": self._gamma_max,
            "rounds": self.spec_rounds,
            "round_dispatches": self.spec_round_dispatches,
            "proposed": self.spec_proposed,
            "accepted": self.spec_accepted,
            "fallback_rounds": self.spec_fallback_rounds,
            "acceptance_ema": (
                round(float(self._slot_ema[live].mean()), 4) if live.any() else None
            ),
            "gamma": round(float(self._slot_gamma[live].mean()), 4) if live.any() else None,
            "accepted_per_target_step": (
                round(
                    (self.spec_accepted + self.spec_slot_rounds + self.spec_fallback_rounds)
                    / ran,
                    4,
                )
                if self.spec_slot_rounds + self.spec_fallback_rounds
                else None
            ),
        }


class SpeculativeBatcher:
    """Single-stream speculative generation behind the ContinuousBatcher contract.

    Requests route through the same SLO scheduler as the continuous engine
    (:mod:`unionml_tpu.serving.scheduler`): bounded queueing with structured
    shedding, priority-ordered turn-taking for the single decode stream, and
    deadline enforcement while queued — so ``GET /stats`` reports one uniform
    scheduler counter set whichever generator backs ``/generate``. (Preemption
    does not apply: the verify loop is batch-1 with no KV checkpoint to steal.)
    """

    def __init__(
        self,
        target: Any,
        target_variables: Any,
        draft: Any,
        draft_variables: Any,
        *,
        gamma: int = 4,
        max_len: Optional[int] = None,
        scheduler: Optional[Any] = None,
        faults: Optional[Any] = None,
    ) -> None:
        from unionml_tpu.serving.scheduler import SchedulerConfig, SLOScheduler

        self._target = target
        self._target_variables = target_variables
        self._draft = draft
        self._draft_variables = draft_variables
        self._gamma = int(gamma)
        #: deterministic fault injection (:class:`~unionml_tpu.serving.faults.
        #: FaultPlan`); None = production (one host branch per request)
        self._faults = faults
        #: requests that died in a speculative round (structured failures)
        self.round_failures = 0  # guarded-by: _lock
        self._max_len = int(max_len or target.config.max_position_embeddings)
        self._lock = threading.Lock()  # serializes device work across requests
        #: SLO admission control shared-shape with ContinuousBatcher (/stats)
        self.scheduler = (
            scheduler
            if isinstance(scheduler, SLOScheduler)
            else SLOScheduler(scheduler if isinstance(scheduler, SchedulerConfig) else None)
        )
        #: turn-taking for the single stream: executor threads wait here until
        #: the scheduler ranks their ticket first and no request is running
        self._turn = threading.Condition()
        self._current: Optional[Any] = None  # guarded-by: _turn
        self._closed = False
        # persistent evolving key (same contract as DecodeEngine): identical
        # sampled requests must NOT return identical completions unless the
        # client pins an explicit seed
        self._key = jax.random.PRNGKey(0)  # guarded-by: _lock
        # the /stats view; num_slots=1 states the single-stream design honestly.
        # bucket_for is the route's prefill-validation hook: speculation prefills
        # at the exact prompt length (no bucket ladder), so identity is correct.
        # requests_admitted / tokens_decoded / prefill_tokens_computed mirror the
        # continuous engine's generation counters, so the stats route reports the
        # same shape whichever generator is plugged in
        # guarded-by: _lock
        self.engine = SimpleNamespace(
            num_slots=1,
            num_active=0,
            max_len=self._max_len,
            bucket_for=lambda n: n,
            requests_admitted=0,
            tokens_decoded=0,
            prefill_tokens_computed=0,
        )

    # ------------------------------------------------------------------ request path

    def _validate(self, prompt_ids: Sequence[int], max_new_tokens: int, sampling: dict):
        if self._closed:
            raise RuntimeError("SpeculativeBatcher is closed")
        prompt = np.asarray(list(prompt_ids), dtype=np.int32)
        if prompt.ndim != 1 or prompt.size == 0:
            raise ValueError("prompt_ids must be a non-empty 1-D token list")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if prompt.size + max_new_tokens + self._gamma + 1 > self._max_len:
            need = prompt.size + max_new_tokens
            # name the BINDING constraint: a request that already overflows
            # max_len on its own is not a γ problem, and saying "gamma slack"
            # there sends operators tuning the wrong knob
            detail = (
                "the request alone"
                if need > self._max_len
                else (
                    f"the draft working window (gamma={self._gamma} proposals + 1 bonus "
                    f"token may be in flight past the last emitted position)"
                )
            )
            raise ValueError(
                f"prompt ({prompt.size}) + max_new_tokens ({max_new_tokens}) exceeds "
                f"max_len ({self._max_len}) once the speculative round slack is "
                f"reserved: {detail} is the binding constraint; lower max_new_tokens "
                f"or gamma"
            )
        if sampling.get("top_k") or sampling.get("top_p") not in (None, 1.0):
            raise ValueError("speculative decoding supports temperature sampling only (no top_k/top_p)")
        temperature = float(sampling.get("temperature", 0.0) or 0.0)
        seed = sampling.get("seed")
        return prompt, temperature, seed

    def _await_turn(self, ticket) -> None:
        """Block until the scheduler ranks ``ticket`` first and the stream is
        free. Raises the ticket's shed error when a later, higher-class submit
        displaced it, and :class:`DeadlineExceededError` when its deadline
        passes while queued — the same structured rejections the continuous
        path surfaces."""
        from unionml_tpu.serving.scheduler import DeadlineExceededError

        with self._turn:
            while True:
                if self._closed:
                    self.scheduler.remove(ticket)
                    raise RuntimeError("SpeculativeBatcher is closed")
                if ticket.shed_exc is not None:  # displaced under a full queue
                    raise ticket.shed_exc
                if ticket.expired(time.monotonic()):
                    # removes this ticket (and any expired peers — their own
                    # waiting threads raise on their next poll) and counts the
                    # queued deadline misses
                    self.scheduler.take_expired()
                    raise DeadlineExceededError("deadline expired while queued")
                if self._current is None and self.scheduler.peek() is ticket:
                    if not self.scheduler.pop_ticket(ticket):
                        raise RuntimeError("ticket vanished from the scheduler queue")
                    self._current = ticket
                    return
                self._turn.wait(timeout=0.02)

    def _end_turn(self) -> None:
        with self._turn:
            self._current = None
            self._turn.notify_all()

    def _run(self, ticket, prompt: np.ndarray, max_new_tokens: int, temperature: float, seed) -> List[int]:
        self._await_turn(ticket)
        try:
            return self._run_current(prompt, max_new_tokens, temperature, seed)
        finally:
            self._end_turn()

    def _run_current(self, prompt: np.ndarray, max_new_tokens: int, temperature: float, seed) -> List[int]:
        from unionml_tpu.models.speculative import speculative_generate
        from unionml_tpu.serving.faults import EngineFailure

        with self._lock:
            if seed is not None:
                rng = jax.random.PRNGKey(int(seed))
            else:
                self._key, rng = jax.random.split(self._key)
            self.engine.num_active = 1
            self.engine.requests_admitted += 1
            try:
                if self._faults is not None:
                    self._faults.check_speculative_round()
                # graftlint: disable=lock-order -- _lock EXISTS to serialize device work across requests (single-stream design, see class docstring); blocking under it is the design, and _await_turn admits exactly one holder
                out = speculative_generate(
                    self._target,
                    self._target_variables,
                    self._draft,
                    self._draft_variables,
                    jax.device_put(prompt)[None, :],  # explicit: keeps the entry path transfer-guard-clean
                    max_new_tokens,
                    gamma=self._gamma,
                    temperature=temperature,
                    rng=rng,
                )
            except Exception as exc:
                # every round's device state is call-local (no persistent KV or
                # donated engine buffers), so a failure costs exactly this
                # request — structured, and the next request runs clean
                self.round_failures += 1
                logger.warning("speculative round failed: %s", exc)
                raise EngineFailure(
                    f"speculative round failed: {exc}", reason="speculative_round_failed"
                ) from exc
            finally:
                self.engine.num_active = 0
            tokens = [int(t) for t in np.asarray(out)[0, prompt.size :]]
            # counter updates stay under the lock: concurrent requests (each on
            # its own executor thread) race read-modify-write otherwise — the
            # lock-discipline lint finding that motivated this placement
            self.engine.prefill_tokens_computed += int(prompt.size)
            self.engine.tokens_decoded += len(tokens)
        return tokens

    async def generate(
        self,
        prompt_ids: Sequence[int],
        max_new_tokens: int,
        *,
        priority: Any = None,
        deadline_ms: Optional[float] = None,
        **sampling,
    ) -> List[int]:
        prompt, temperature, seed = self._validate(prompt_ids, max_new_tokens, sampling)
        # admission control BEFORE any device work: shed errors (queue full /
        # deadline infeasible) raise here, on the caller's side, exactly like
        # the continuous path
        ticket = self.scheduler.make_ticket(
            prompt, int(max_new_tokens), sampling, None,
            priority=priority, deadline_ms=deadline_ms,
        )
        displaced = self.scheduler.submit(ticket)
        if displaced is not None:
            with self._turn:  # wake the displaced ticket's waiting thread
                self._turn.notify_all()
        loop = asyncio.get_running_loop()
        return await loop.run_in_executor(
            None, self._run, ticket, prompt, max_new_tokens, temperature, seed
        )

    async def stream(
        self,
        prompt_ids: Sequence[int],
        max_new_tokens: int,
        *,
        priority: Any = None,
        deadline_ms: Optional[float] = None,
        **sampling,
    ):
        """Async iterator of tokens. Tokens arrive in one burst at completion:
        speculation verifies whole proposal rounds, so there is no per-token
        decode step to stream from (use the continuous engine for live streams)."""
        for token in await self.generate(
            prompt_ids, max_new_tokens, priority=priority, deadline_ms=deadline_ms, **sampling
        ):
            yield token

    def close(self) -> None:
        self._closed = True
        with self._turn:  # wake queued waiters so they fail promptly, not on poll
            self._turn.notify_all()
        logger.info("SpeculativeBatcher closed.")
