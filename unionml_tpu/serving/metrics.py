"""Shared metrics registry with Prometheus text exposition.

One process-wide :class:`MetricsRegistry` backs every serving module's
headline counters (routing decisions, sheds, rebuilds, cache hits,
tokens in/out) plus the latency histograms (TTFT, ITL, queue wait per
class, decode-burst fetch time). Modules keep their private
``stats()``-shaped counters — those are API surface pinned by tests —
and mirror the headline mutations into the registry at the same sites.

Lock discipline: the registry owns ONE lock and it is a LEAF — no
registry method calls out to user code or any other serving component,
so recording is safe from inside or outside any caller's critical
section (callers still record outside their own locks by convention,
keeping graftlint's lock-order rule trivially clean). No third-party
client library: the exposition renderer is ~40 lines of the stable
`Prometheus text format
<https://prometheus.io/docs/instrumenting/exposition_formats/>`_, which
keeps the container dependency-free.
"""

from __future__ import annotations

import math
import threading
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "log_buckets",
]

#: label-values key for the unlabelled child of a metric
_NO_LABELS: Tuple[str, ...] = ()


def log_buckets(start: float, factor: float, count: int) -> Tuple[float, ...]:
    """Geometric histogram bucket upper bounds: ``start * factor**i``.

    Log-spaced buckets give constant *relative* error across decades —
    the right shape for latencies, where 1 ms and 1 s both matter.
    ``+Inf`` is implicit (every histogram gets it).
    """
    if start <= 0 or factor <= 1 or count < 1:
        raise ValueError(f"log_buckets({start}, {factor}, {count}): need start>0, factor>1, count>=1")
    return tuple(start * factor**i for i in range(count))


def _format_value(v: float) -> str:
    """Render a sample value the way Prometheus expects (no exponent noise)."""
    if v == math.inf:
        return "+Inf"
    if float(v).is_integer() and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


def _escape_label(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _labels_suffix(names: Sequence[str], values: Sequence[str], extra: str = "") -> str:
    parts = [f'{n}="{_escape_label(str(v))}"' for n, v in zip(names, values)]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


class _Metric:
    """Base: a named family of label-keyed children. Registry-lock guarded."""

    kind = "untyped"

    def __init__(self, registry: "MetricsRegistry", name: str, help: str, labels: Sequence[str] = ()) -> None:
        self._registry = registry
        self.name = name
        self.help = help
        self.label_names = tuple(labels)
        #: label-values tuple -> child state; guarded-by: registry._lock
        self._children: Dict[Tuple[str, ...], object] = {}

    def _key(self, label_values: Sequence[str]) -> Tuple[str, ...]:
        if len(label_values) != len(self.label_names):
            raise ValueError(
                f"{self.name}: expected labels {self.label_names}, got {len(label_values)} values"
            )
        return tuple(str(v) for v in label_values)


class Counter(_Metric):
    """Monotonically increasing count (``_total`` naming convention)."""

    kind = "counter"

    def inc(self, amount: float = 1.0, *labels: str) -> None:
        with self._registry._lock:
            key = self._key(labels)
            self._children[key] = self._children.get(key, 0.0) + amount  # type: ignore[operator]

    def value(self, *labels: str) -> float:
        with self._registry._lock:
            return float(self._children.get(self._key(labels), 0.0))  # type: ignore[arg-type]

    def _render(self, out: List[str]) -> None:
        for key, v in sorted(self._children.items()):
            out.append(f"{self.name}{_labels_suffix(self.label_names, key)} {_format_value(float(v))}")  # type: ignore[arg-type]

    def _snapshot(self) -> object:
        if not self.label_names:
            return float(self._children.get(_NO_LABELS, 0.0))  # type: ignore[arg-type]
        return {",".join(k): float(v) for k, v in sorted(self._children.items())}  # type: ignore[arg-type]


class Gauge(_Metric):
    """Point-in-time value; supports ``set`` and ``inc``/``dec``."""

    kind = "gauge"

    def set(self, value: float, *labels: str) -> None:
        with self._registry._lock:
            self._children[self._key(labels)] = float(value)

    def inc(self, amount: float = 1.0, *labels: str) -> None:
        with self._registry._lock:
            key = self._key(labels)
            self._children[key] = self._children.get(key, 0.0) + amount  # type: ignore[operator]

    def dec(self, amount: float = 1.0, *labels: str) -> None:
        self.inc(-amount, *labels)

    def value(self, *labels: str) -> float:
        with self._registry._lock:
            return float(self._children.get(self._key(labels), 0.0))  # type: ignore[arg-type]

    _render = Counter._render
    _snapshot = Counter._snapshot


class _HistChild:
    __slots__ = ("counts", "total", "count")

    def __init__(self, nbuckets: int) -> None:
        self.counts = [0] * (nbuckets + 1)  # +1 for the implicit +Inf bucket
        self.total = 0.0
        self.count = 0


class Histogram(_Metric):
    """Cumulative histogram with fixed upper bounds (Prometheus semantics).

    ``observe`` is O(log buckets) via bisection; render emits the
    canonical ``_bucket{le=...}`` cumulative series plus ``_sum`` and
    ``_count``. Use :func:`log_buckets` for latency-shaped bounds.
    """

    kind = "histogram"

    def __init__(
        self,
        registry: "MetricsRegistry",
        name: str,
        help: str,
        buckets: Sequence[float],
        labels: Sequence[str] = (),
    ) -> None:
        super().__init__(registry, name, help, labels)
        bounds = tuple(sorted(float(b) for b in buckets))
        if not bounds:
            raise ValueError(f"{name}: histogram needs at least one bucket bound")
        if len(set(bounds)) != len(bounds):
            raise ValueError(f"{name}: duplicate bucket bounds")
        self.buckets = bounds

    def observe(self, value: float, *labels: str) -> None:
        v = float(value)
        with self._registry._lock:
            key = self._key(labels)
            child = self._children.get(key)
            if child is None:
                child = self._children[key] = _HistChild(len(self.buckets))
            # linear scan beats bisect for the ~20-bucket latency shapes here
            idx = len(self.buckets)
            for i, bound in enumerate(self.buckets):
                if v <= bound:
                    idx = i
                    break
            child.counts[idx] += 1  # type: ignore[union-attr]
            child.total += v  # type: ignore[union-attr]
            child.count += 1  # type: ignore[union-attr]

    def _render(self, out: List[str]) -> None:
        for key, child in sorted(self._children.items()):
            cum = 0
            for bound, n in zip(self.buckets, child.counts):  # type: ignore[union-attr]
                cum += n
                le = _labels_suffix(self.label_names, key, f'le="{_format_value(bound)}"')
                out.append(f"{self.name}_bucket{le} {cum}")
            cum += child.counts[-1]  # type: ignore[union-attr]
            le = _labels_suffix(self.label_names, key, 'le="+Inf"')
            out.append(f"{self.name}_bucket{le} {cum}")
            suffix = _labels_suffix(self.label_names, key)
            out.append(f"{self.name}_sum{suffix} {_format_value(child.total)}")  # type: ignore[union-attr]
            out.append(f"{self.name}_count{suffix} {cum}")

    def _snapshot(self) -> object:
        def one(child: _HistChild) -> dict:
            n = child.count
            return {
                "count": n,
                "sum": round(child.total, 3),
                "mean_ms": round(child.total / n, 3) if n else 0.0,
            }

        if not self.label_names:
            child = self._children.get(_NO_LABELS)
            return one(child) if child is not None else {"count": 0, "sum": 0.0, "mean_ms": 0.0}  # type: ignore[arg-type]
        return {",".join(k): one(c) for k, c in sorted(self._children.items())}  # type: ignore[arg-type]


class MetricsRegistry:
    """Create-once, record-many metric family registry.

    ``counter``/``gauge``/``histogram`` are idempotent on name (the
    existing family is returned, with a type check), so independent
    modules can declare the metrics they record without coordinating
    creation order. ``render()`` produces the Prometheus text
    exposition; ``snapshot()`` a JSON-friendly dict for ``/stats``.
    """

    def __init__(self) -> None:
        #: the one LEAF lock guarding all metric state (see module docstring)
        self._lock = threading.Lock()  # lock-leaf
        #: name -> metric family; guarded-by: _lock
        self._metrics: Dict[str, _Metric] = {}

    def _get_or_create(self, cls, name: str, help: str, labels: Sequence[str], **kw) -> _Metric:
        with self._lock:
            existing = self._metrics.get(name)
            if existing is not None:
                if type(existing) is not cls or existing.label_names != tuple(labels):
                    raise ValueError(
                        f"metric {name!r} already registered as {type(existing).__name__}"
                        f" with labels {existing.label_names}"
                    )
                return existing
            metric = cls(self, name, help, labels=labels, **kw)
            self._metrics[name] = metric
            return metric

    def counter(self, name: str, help: str, labels: Sequence[str] = ()) -> Counter:
        return self._get_or_create(Counter, name, help, labels)  # type: ignore[return-value]

    def gauge(self, name: str, help: str, labels: Sequence[str] = ()) -> Gauge:
        return self._get_or_create(Gauge, name, help, labels)  # type: ignore[return-value]

    def histogram(
        self, name: str, help: str, buckets: Sequence[float], labels: Sequence[str] = ()
    ) -> Histogram:
        return self._get_or_create(Histogram, name, help, labels, buckets=buckets)  # type: ignore[return-value]

    def render(self) -> str:
        """The `/metrics` payload: Prometheus text exposition format 0.0.4."""
        lines: List[str] = []
        with self._lock:
            for name in sorted(self._metrics):
                metric = self._metrics[name]
                lines.append(f"# HELP {name} {metric.help}")
                lines.append(f"# TYPE {name} {metric.kind}")
                metric._render(lines)
        return "\n".join(lines) + "\n"

    def snapshot(self) -> Dict[str, object]:
        """JSON-friendly view of every family (backs the `/stats` telemetry block)."""
        with self._lock:
            return {name: m._snapshot() for name, m in sorted(self._metrics.items())}
