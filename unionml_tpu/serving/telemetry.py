"""Per-request span tracing + replayable event journal for the serving tier.

Every request admitted with telemetry enabled gets a ``request_id``-keyed
:class:`Trace`: an ordered list of :class:`Span` records covering its whole
lifetime — admission, queue wait (per class), the routing decision, prefix
cache hit/restore, each prefill chunk, decode (per-burst timing piggybacked
on the engine's existing fused deferred fetches: ZERO new host↔device
syncs, pinned by the transfer-guard regression), preemption/resume,
quarantine, engine death, and failover adoption. Completed traces land in a
bounded ring journal (``/traces/recent``, ``/trace/{request_id}``) and
optionally a JSONL sink whose schema (v2, see ``docs/observability.md``)
is the replay input format for the fleet simulator (``unionml_tpu.sim``):
v2 stamps the session id and the admission-time block-pool arithmetic onto
every trace so replay needs no side channels.

Hook contract (the PR-7 FaultPlan pattern): every emitting module holds an
``Optional[Telemetry]`` and guards each record site with a single host
branch — ``if self._telemetry is not None`` — so disabled telemetry costs
one pointer compare. Recording sites are LOCK-LEAF: ``Telemetry`` methods
never call out to other serving components, and callers invoke them
OUTSIDE their own critical sections, keeping graftlint's lock-order rule
at 0 findings.

Headline latency/throughput aggregates mirror into the shared
:class:`~unionml_tpu.serving.metrics.MetricsRegistry` (rendered at
``/metrics``); modules' private ``stats()`` counters are unchanged API.
"""

from __future__ import annotations

import json
import threading
import time
import uuid
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Deque, Dict, List, Optional

from unionml_tpu.serving.metrics import MetricsRegistry, log_buckets
from unionml_tpu.serving.slo import SLOTracker

__all__ = [
    "JOURNAL_SCHEMA_VERSION",
    "Span",
    "Telemetry",
    "Trace",
]

#: bump when the journal JSONL schema changes shape (simulator replay input).
#: v2 (ISSUE 15): top-level ``session_id``; admission spans carry
#: ``block_demand`` + ``available_blocks``; admission/queue_wait spans carry
#: the session id. The sim's loader (``unionml_tpu.sim.journal``) still
#: accepts v1 with those fields defaulted.
JOURNAL_SCHEMA_VERSION = 2

#: latency bucket bounds, ms: 0.25 ms … ~16 s in ×2 steps (17 buckets)
_LATENCY_BUCKETS_MS = log_buckets(0.25, 2.0, 17)


def new_request_id() -> str:
    """A fresh 16-hex request id (also minted route-side in ``app.py``)."""
    return uuid.uuid4().hex[:16]


@dataclass
class Span:
    """One timed event inside a trace.

    ``t_ms`` is milliseconds since the trace started (monotonic clock);
    ``dur_ms`` is None for instantaneous markers. ``attrs`` carries
    kind-specific detail (see the span taxonomy in
    ``docs/observability.md``) and must stay JSON-serializable.
    """

    kind: str
    t_ms: float
    dur_ms: Optional[float] = None
    attrs: Dict[str, Any] = field(default_factory=dict)

    def to_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {"kind": self.kind, "t_ms": round(self.t_ms, 3)}
        if self.dur_ms is not None:
            out["dur_ms"] = round(self.dur_ms, 3)
        if self.attrs:
            out["attrs"] = self.attrs
        return out


@dataclass
class Trace:
    """A request's full timeline; lives in ``Telemetry`` under its lock."""

    request_id: str
    created_unix: float
    t0: float  # monotonic origin for every span's t_ms
    session_id: Optional[str] = None
    cls: str = "standard"
    status: str = "active"
    reason: Optional[str] = None
    tokens_in: int = 0
    tokens_out: int = 0
    first_token_t: Optional[float] = None
    last_token_t: Optional[float] = None
    decode_bursts: int = 0
    spans: List[Span] = field(default_factory=list)
    attrs: Dict[str, Any] = field(default_factory=dict)

    @property
    def ttft_ms(self) -> Optional[float]:
        if self.first_token_t is None:
            return None
        return (self.first_token_t - self.t0) * 1e3

    @property
    def itl_ms(self) -> Optional[float]:
        if self.first_token_t is None or self.last_token_t is None or self.tokens_out < 2:
            return None
        return (self.last_token_t - self.first_token_t) * 1e3 / (self.tokens_out - 1)

    def to_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {
            "v": JOURNAL_SCHEMA_VERSION,
            "request_id": self.request_id,
            "created_unix": round(self.created_unix, 6),
            "class": self.cls,
            "status": self.status,
            "tokens_in": self.tokens_in,
            "tokens_out": self.tokens_out,
            "decode_bursts": self.decode_bursts,
            "spans": [s.to_dict() for s in self.spans],
        }
        if self.session_id is not None:
            out["session_id"] = self.session_id
        if self.reason is not None:
            out["reason"] = self.reason
        ttft = self.ttft_ms
        if ttft is not None:
            out["ttft_ms"] = round(ttft, 3)
        itl = self.itl_ms
        if itl is not None:
            out["itl_ms"] = round(itl, 3)
        if self.attrs:
            out["attrs"] = self.attrs
        return out


class Telemetry:
    """Process-wide trace collector + metrics mirror for one serving stack.

    One instance is shared by the whole request path (app → fleet → router
    → batcher → engine → scheduler/supervisor/prefix-cache/faults), so a
    request keeps ONE trace across replica failover. All methods are
    thread-safe behind a single leaf lock and never raise on unknown
    request ids (a span for a request that was never traced, or already
    journaled, is dropped) — recording must never take down serving.

    :param registry: shared :class:`MetricsRegistry`; a fresh one is
        created when omitted.
    :param journal_size: completed traces kept in the in-memory ring
        (``/traces/recent``).
    :param journal_path: optional JSONL file appended one completed trace
        per line — the ROADMAP-8 simulator's replay input.
    :param max_spans: per-trace span cap; beyond it spans are dropped and
        counted in ``attrs["spans_dropped"]`` (bounds runaway requests).
    :param slo: shared :class:`~unionml_tpu.serving.slo.SLOTracker`; a fresh
        default-objective tracker is created when omitted, so every deployment
        shape gets the ``/metrics`` attainment/burn gauges and the
        ``generation.slo`` stats block for free.
    """

    def __init__(
        self,
        registry: Optional[MetricsRegistry] = None,
        *,
        journal_size: int = 256,
        journal_path: Optional[str] = None,
        max_spans: int = 512,
        slo: Optional[SLOTracker] = None,
    ) -> None:
        self.metrics = registry if registry is not None else MetricsRegistry()
        #: the SLO scoring shared with /stats and the fleet simulator —
        #: end_trace feeds it one event per completed request
        self.slo = slo if slo is not None else SLOTracker()
        self._max_spans = int(max_spans)
        #: guards _active/_ring/_completed; LEAF (never calls out — see module doc)
        self._lock = threading.Lock()  # lock-leaf
        self._active: Dict[str, Trace] = {}  # guarded-by: _lock
        self._ring: Deque[Trace] = deque(maxlen=int(journal_size))  # guarded-by: _lock
        self._completed = 0  # guarded-by: _lock
        self._dropped_spans = 0  # guarded-by: _lock
        self.journal_path = journal_path
        #: serializes JSONL appends only; LEAF, never held with _lock
        self._journal_lock = threading.Lock()  # lock-leaf

        m = self.metrics
        self.requests_total = m.counter(
            "unionml_requests_total", "Completed requests by outcome", ("outcome",)
        )
        self.sheds_total = m.counter(
            "unionml_sheds_total", "Requests shed by structured reason", ("reason",)
        )
        self.tokens_in_total = m.counter("unionml_tokens_in_total", "Prompt tokens accepted")
        self.tokens_out_total = m.counter("unionml_tokens_out_total", "Tokens decoded and delivered")
        self.prefill_tokens_total = m.counter(
            "unionml_prefill_tokens_total", "Tokens run through prefill (incl. restored-suffix recompute)"
        )
        self.ttft_ms = m.histogram(
            "unionml_ttft_ms", "Time to first token, ms", _LATENCY_BUCKETS_MS, ("cls",)
        )
        self.itl_ms = m.histogram(
            "unionml_itl_ms", "Mean inter-token latency per request, ms", _LATENCY_BUCKETS_MS, ("cls",)
        )
        self.queue_wait_ms = m.histogram(
            "unionml_queue_wait_ms", "Scheduler queue wait, ms", _LATENCY_BUCKETS_MS, ("cls",)
        )
        self.decode_fetch_ms = m.histogram(
            "unionml_decode_fetch_ms",
            "Host-blocked time per fused decode-burst fetch, ms",
            _LATENCY_BUCKETS_MS,
        )
        self.route_decisions_total = m.counter(
            "unionml_route_decisions_total", "Fleet routing decisions by type", ("decision",)
        )
        self.prefix_lookups_total = m.counter(
            "unionml_prefix_lookups_total", "Prefix-cache lookups"
        )
        self.prefix_hits_total = m.counter(
            "unionml_prefix_hits_total", "Prefix-cache lookups that matched at least one block"
        )
        self.prefix_hit_tokens_total = m.counter(
            "unionml_prefix_hit_tokens_total", "Prompt tokens served from the prefix cache"
        )
        self.preemptions_total = m.counter(
            "unionml_preemptions_total", "Requests preempted to the prefix cache"
        )
        self.resumes_total = m.counter(
            "unionml_resumes_total", "Preempted/salvaged requests re-admitted"
        )
        self.quarantines_total = m.counter(
            "unionml_quarantines_total", "Slots quarantined (NaN logits)"
        )
        self.engine_failures_total = m.counter(
            "unionml_engine_failures_total", "Engine-wide failures by classified reason", ("reason",)
        )
        self.rebuilds_total = m.counter(
            "unionml_rebuilds_total", "Successful in-place engine rebuilds"
        )
        self.health_transitions_total = m.counter(
            "unionml_health_transitions_total", "Supervisor health-state transitions", ("to",)
        )
        self.failover_adoptions_total = m.counter(
            "unionml_failover_adoptions_total", "Orphaned tickets adopted by a surviving replica"
        )
        self.faults_injected_total = m.counter(
            "unionml_faults_injected_total", "Faults injected by the active FaultPlan", ("site",)
        )
        # paged KV pool occupancy (ISSUE 13): every block is owned by exactly
        # one of free list / live slot / radix index, so these three gauges
        # plus pinned (a subset of cached) give capacity headroom at a glance
        self.pool_free_blocks = m.gauge(
            "unionml_kv_pool_free_blocks", "Paged KV pool blocks on the free list"
        )
        self.pool_live_blocks = m.gauge(
            "unionml_kv_pool_live_blocks", "Paged KV pool blocks owned by live decode slots"
        )
        self.pool_cached_blocks = m.gauge(
            "unionml_kv_pool_cached_blocks", "Paged KV pool blocks held by the radix prefix index"
        )
        self.pool_pinned_blocks = m.gauge(
            "unionml_kv_pool_pinned_blocks", "Paged KV pool blocks pinned by preempt/salvage checkpoints"
        )
        # pool byte footprint (ISSUE 14): the kv_dtype label says what actually
        # crosses HBM ("int8" under kv_quantize, else the compute dtype), and
        # the dense-equivalent gauge prices the same KV positions at full
        # precision — their ratio is the capacity doubling on dashboards
        self.pool_kv_bytes = m.gauge(
            "unionml_kv_pool_bytes",
            "Paged KV pool resident bytes as stored (scale arrays included)",
            ("kv_dtype",),
        )
        self.pool_kv_bytes_dense_equiv = m.gauge(
            "unionml_kv_pool_bytes_dense_equiv",
            "Same KV pool positions priced at the full compute dtype",
        )
        # info gauge (value pinned to 1): the impl label names the decode
        # attention backend the replica's traced programs dispatch to —
        # "pallas" (fused paged kernel, ISSUE 18) or "xla" (gather + attend).
        # Fleet operators fan this out to see which replicas run fused.
        self.paged_attn_impl = m.gauge(
            "unionml_paged_attn_impl",
            "Selected paged decode-attention backend (info gauge, value=1)",
            ("impl",),
        )
        self.blocks_per_request = m.histogram(
            "unionml_kv_blocks_per_request",
            "Pool blocks allocated per admitted request (paged engines)",
            log_buckets(1.0, 2.0, 12),
        )
        # per-class SLO surface (ISSUE 15): attainment over the longest
        # configured rolling window, and the error-budget burn rate per
        # (class, window) — the same numbers the generation.slo stats block
        # and the simulator's report read from the shared SLOTracker
        self.slo_attainment = m.gauge(
            "unionml_slo_attainment",
            "Rolling-window SLO attainment fraction per class",
            ("cls",),
        )
        self.slo_burn_rate = m.gauge(
            "unionml_slo_burn_rate",
            "Error-budget burn rate per class and rolling window",
            ("cls", "window"),
        )
        # speculative decoding (ISSUE 16): acceptance EMA per SLO class and the
        # live mean γ tell at a glance whether speculation is paying (α high,
        # γ ramped) or has adaptively degraded to vanilla (γ → 0); the raw
        # proposed/accepted counters give the exact accepted-tokens-per-
        # target-step the bench gates on: (accepted + rounds) / rounds
        self.spec_acceptance = m.gauge(
            "unionml_spec_acceptance",
            "Speculative acceptance EMA (mean over live speculative slots) per class",
            ("cls",),
        )
        self.spec_gamma = m.gauge(
            "unionml_spec_gamma",
            "Current adaptive gamma (mean over live speculative slots)",
        )
        self.spec_proposed_total = m.counter(
            "unionml_spec_proposed_total",
            "Draft tokens proposed by speculative rounds",
        )
        self.spec_accepted_total = m.counter(
            "unionml_spec_accepted_total",
            "Draft proposals accepted by target verification",
        )

    # ------------------------------------------------------------------ traces

    def new_trace(
        self,
        request_id: Optional[str] = None,
        *,
        cls: str = "standard",
        session_id: Optional[str] = None,
        **attrs: Any,
    ) -> str:
        """Open (or join) the trace for ``request_id``; returns the id.

        Idempotent on an already-active id — the fleet opens the trace
        before routing and the replica batcher joins it, so failover
        keeps one trace across engines. Re-opening refreshes nothing but
        merges ``attrs`` (and sets ``session_id`` when newly provided —
        the fleet knows it, the replica batcher does not).
        """
        rid = request_id if request_id else new_request_id()
        with self._lock:
            trace = self._active.get(rid)
            if trace is None:
                trace = Trace(
                    request_id=rid,
                    created_unix=time.time(),
                    t0=time.perf_counter(),
                    cls=cls,
                )
                self._active[rid] = trace
            if session_id is not None:
                trace.session_id = session_id
            if attrs:
                trace.attrs.update(attrs)
            if cls != "standard":
                trace.cls = cls
        return rid

    def set_class(self, request_id: Optional[str], cls: str) -> None:
        if request_id is None:
            return
        with self._lock:
            trace = self._active.get(request_id)
            if trace is not None:
                trace.cls = cls

    def span(
        self,
        request_id: Optional[str],
        kind: str,
        *,
        dur_ms: Optional[float] = None,
        at: Optional[float] = None,
        **attrs: Any,
    ) -> None:
        """Append a span to an active trace (no-op for unknown/ended ids).

        ``at`` is an optional ``time.perf_counter()`` stamp for spans whose
        event happened earlier than the record call (the engine buffers
        slot-keyed spans until the batcher binds the slot's request id)."""
        if request_id is None:
            return
        now = time.perf_counter() if at is None else at
        with self._lock:
            trace = self._active.get(request_id)
            if trace is None:
                return
            if len(trace.spans) >= self._max_spans:
                self._dropped_spans += 1
                trace.attrs["spans_dropped"] = trace.attrs.get("spans_dropped", 0) + 1
                return
            span_attrs = dict(attrs)
            if trace.session_id is not None and kind in ("admission", "queue_wait"):
                # journal v2: the replay loader reads the session off these
                # spans directly (emitters below the fleet never see it)
                span_attrs.setdefault("session_id", trace.session_id)
            trace.spans.append(Span(kind, (now - trace.t0) * 1e3, dur_ms, span_attrs))

    def note_tokens_in(self, request_id: Optional[str], n: int) -> None:
        self.tokens_in_total.inc(n)
        if request_id is None:
            return
        with self._lock:
            trace = self._active.get(request_id)
            if trace is not None:
                trace.tokens_in = int(n)

    def decode_tokens(
        self,
        request_id: Optional[str],
        n: int,
        *,
        at: Optional[float] = None,
        block_ms: Optional[float] = None,
    ) -> None:
        """Record ``n`` tokens surfacing from one fused decode-burst fetch.

        ``at`` is the fetch's existing ``time.perf_counter()`` completion
        stamp and ``block_ms`` its already-measured host-blocked time —
        both piggyback on measurements the engine takes anyway, so the
        decode path pays no new host↔device syncs for tracing.
        """
        self.tokens_out_total.inc(n)
        if block_ms is not None:
            self.decode_fetch_ms.observe(block_ms)
        if request_id is None:
            return
        t = at if at is not None else time.perf_counter()
        first: Optional[Trace] = None
        with self._lock:
            trace = self._active.get(request_id)
            if trace is None:
                return
            trace.tokens_out += int(n)
            trace.decode_bursts += 1
            trace.last_token_t = t
            if trace.first_token_t is None:
                trace.first_token_t = t
                first = trace
        if first is not None:
            ttft = first.ttft_ms
            if ttft is not None:
                self.ttft_ms.observe(ttft, first.cls)

    def end_trace(
        self,
        request_id: Optional[str],
        status: str = "ok",
        *,
        reason: Optional[str] = None,
        **attrs: Any,
    ) -> None:
        """Complete a trace: journal it and observe its latency aggregates.

        A trace survives preemption, quarantine-of-siblings, engine death,
        and failover — only terminal delivery (tokens, structured error,
        or shed) ends it. Ending an unknown id is a no-op.
        """
        if request_id is None:
            return
        now = time.perf_counter()
        with self._lock:
            trace = self._active.pop(request_id, None)
            if trace is None:
                return
            trace.status = status
            trace.reason = reason
            if attrs:
                trace.attrs.update(attrs)
            dur = (now - trace.t0) * 1e3
            if trace.tokens_out > 0 and trace.first_token_t is not None:
                # one aggregated decode span per request (per-burst detail
                # would be unbounded); timing reuses the fused-fetch stamps
                last = trace.last_token_t if trace.last_token_t is not None else trace.first_token_t
                trace.spans.append(
                    Span(
                        "decode",
                        (trace.first_token_t - trace.t0) * 1e3,
                        (last - trace.first_token_t) * 1e3,
                        {"tokens": trace.tokens_out, "bursts": trace.decode_bursts},
                    )
                )
            trace.spans.append(Span("end", dur, None, {"status": status} if reason is None else {"status": status, "reason": reason}))
            self._ring.append(trace)
            self._completed += 1
        self.requests_total.inc(1.0, status)
        itl = trace.itl_ms
        if itl is not None:
            self.itl_ms.observe(itl, trace.cls)
        # SLO scoring: TTFT compared at the journal's 3-decimal precision so
        # live gauges and a simulator replay of this journal line can never
        # disagree on a boundary case; gauges are set OUTSIDE both the
        # tracker's and this object's lock (all three are leaves)
        ttft = trace.ttft_ms
        signal = self.slo.record(
            trace.cls, status, None if ttft is None else round(ttft, 3)
        )
        if signal is not None:
            if signal["attainment"] is not None:
                self.slo_attainment.set(signal["attainment"], trace.cls)
            for window, burn in signal["burn"].items():
                self.slo_burn_rate.set(burn, trace.cls, window)
        if self.journal_path is not None:
            line = json.dumps(trace.to_dict(), separators=(",", ":"))
            try:
                with self._journal_lock, open(self.journal_path, "a") as fh:
                    fh.write(line + "\n")
            except OSError:  # journal loss must never take down serving
                pass

    # ---------------------------------------------------------------- readers

    def get_trace(self, request_id: str) -> Optional[Dict[str, Any]]:
        """The span tree for one request — active traces included."""
        with self._lock:
            trace = self._active.get(request_id)
            if trace is None:
                for t in self._ring:
                    if t.request_id == request_id:
                        trace = t
                        break
            return trace.to_dict() if trace is not None else None

    def recent(self, n: int = 50) -> List[Dict[str, Any]]:
        """The most recently completed traces, newest last."""
        with self._lock:
            items = list(self._ring)[-int(n):]
            return [t.to_dict() for t in items]

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "active_traces": len(self._active),
                "completed_traces": self._completed,
                "journal_depth": len(self._ring),
                "journal_path": self.journal_path,
                "spans_dropped": self._dropped_spans,
            }

    def assert_balanced(self, *, allow_active: bool = False) -> None:
        """Tests-only invariant check: every completed trace is terminated.

        The dynamic twin of the static ``trace`` resource rule
        (``new_trace`` must reach ``end_trace`` on every path): each trace in
        the completed ring must carry exactly one terminal ``"end"`` span, it
        must be the last span, and the trace status must no longer be
        ``"active"``. Unless ``allow_active`` is set, no trace may still be
        open in ``_active`` — a leftover entry means some code path acquired
        a trace and never ended it.

        Wired into test teardowns; never call this from serving paths.
        """
        with self._lock:
            for trace in self._ring:
                ends = [i for i, s in enumerate(trace.spans) if s.kind == "end"]
                if len(ends) != 1:
                    raise AssertionError(
                        f"trace {trace.request_id!r} has {len(ends)} 'end' "
                        f"spans (want exactly 1)"
                    )
                if ends[0] != len(trace.spans) - 1:
                    raise AssertionError(
                        f"trace {trace.request_id!r} has spans after 'end': "
                        f"{[s.kind for s in trace.spans[ends[0] + 1:]]}"
                    )
                if trace.status == "active":
                    raise AssertionError(
                        f"completed trace {trace.request_id!r} still marked "
                        f"'active'"
                    )
            if not allow_active and self._active:
                raise AssertionError(
                    "unterminated traces at teardown: "
                    f"{sorted(self._active)} — every new_trace() must reach "
                    f"end_trace()"
                )
