"""Resident predictor: a pre-compiled XLA executable serving online predictions.

Reference behavior: the FastAPI path routes every request through
``model.predict(features=...)`` interpreted Python (``unionml/fastapi.py:50-64``). The
TPU-native rebuild pre-lowers and compiles the predictor at server startup for a ladder
of padded batch shapes ("bucketing"), so the request path is: host->device transfer,
run resident executable, device->host — the p50-latency metric in BASELINE.md.

Dynamic request sizes vs XLA static shapes (SURVEY.md §7 "hard parts"): request batches
pad up to the nearest bucket; predictions slice back down. Two bucketing axes:

- **batch** (dim 0, always on): requests pad up the ``buckets`` ladder.
- **sequence** (dim 1, opt-in via ``seq_buckets``): tokenized inputs (BERT-style
  ``input_ids``/``attention_mask`` dicts) pad their sequence dimension up a second
  ladder, so a 37-token request reuses the 64-token executable instead of compiling
  a fresh shape per length.

Features may be a single array OR a dict/pytree of arrays sharing a leading batch dim
(multi-input models). Opaque model objects (sklearn/torch) bypass compilation and run
eagerly — same endpoint, same semantics.

Warmup sources, in priority order: an explicit ``example_features`` request payload
(rows exactly as a client would POST them — covers tokenized/multi-input models), else
the dataset's flat feature metadata. Pass ``example_features`` through
``model.serve(example_features=[...])``.
"""

import threading
import time
from collections import deque
from typing import Any, Optional, Sequence, Tuple

import jax
import numpy as np

from unionml_tpu._logging import logger
from unionml_tpu.stage import is_jax_compatible

DEFAULT_BUCKETS: Tuple[int, ...] = (1, 2, 4, 8, 16, 32, 64, 128, 256)


def _ladder_value(ladder: Tuple[int, ...], n: int) -> int:
    """Smallest ladder entry >= n; oversize rounds up to a multiple of the largest."""
    for rung in ladder:
        if rung >= n:
            return rung
    largest = ladder[-1]
    return ((n + largest - 1) // largest) * largest


class ResidentPredictor:
    """Holds a model artifact on-device with a compiled predict executable."""

    def __init__(
        self,
        model: Any,
        buckets: Sequence[int] = DEFAULT_BUCKETS,
        warmup: bool = True,
        seq_buckets: Optional[Sequence[int]] = None,
        example_features: Optional[Any] = None,
        mesh: Optional[Any] = None,
        param_specs: Optional[Any] = None,
    ):
        """``mesh`` (a ``jax.sharding.Mesh``) serves the compiled predictor across
        every mesh device: the model artifact commits to the mesh once at setup —
        laid out by ``param_specs`` (a ``PartitionSpec`` pytree matching the model
        object, e.g. a family's ``param_shardings`` table) or replicated when
        ``None`` — and request batches shard their leading dim over the ``data``
        axis when the padded bucket divides. Outputs are identical to the
        single-device predictor; only the layout changes."""
        self._model = model
        self._buckets = tuple(sorted(buckets))
        self._seq_buckets = tuple(sorted(seq_buckets)) if seq_buckets else None
        self._example_features = example_features
        self._mesh = mesh
        self._param_specs = param_specs
        self._warmup = warmup
        self._compiled = None
        self._device_model_object = None
        # serializes setup(): predict() runs on executor threads, and several
        # first requests can race into the lazy init — exactly one may compile
        # and commit the artifact to device (the rest wait, then see _ready)
        self._setup_lock = threading.Lock()
        self._ready = False  # guarded-by: _setup_lock
        # per-request device-side latency (dispatch + device->host fetch), ms —
        # the server-side half of the device/HTTP latency split (VERDICT r3 #8):
        # /stats quotes these so tunnel/client RTT never masquerades as model time.
        # predict() appends from executor threads while /stats reads on the event
        # loop; the lock keeps the snapshot safe (deques error on mutation mid-iter)
        self._device_times_ms: deque = deque(maxlen=2048)
        self._device_times_lock = threading.Lock()
        # shape signatures whose executable has already run once: the FIRST call
        # at a new padded shape pays trace+compile, which must not be recorded as
        # steady-state device latency (it would sit in the window as a bogus p99)
        self._timed_shapes: set = set()

    def device_stats(self) -> dict:
        """Percentiles of the compiled executable's per-request wall time."""
        with self._device_times_lock:
            times = sorted(self._device_times_ms)
        if not times:
            return {"count": 0}
        at = lambda q: round(times[min(int(len(times) * q), len(times) - 1)], 3)
        return {
            "count": len(times),
            "device_p50_ms": at(0.50),
            "device_p90_ms": at(0.90),
            "device_p99_ms": at(0.99),
        }

    def setup(self) -> None:
        """Decide the execution mode and (if traceable) compile + warm the predictor.

        Idempotent and thread-safe: concurrent first requests race through
        predict()'s fast-path readiness check, so the body runs under
        ``_setup_lock`` and re-checks — exactly one caller compiles and
        commits the artifact to device; the rest block until it is ready."""
        with self._setup_lock:
            if self._ready:
                return
            artifact = self._model.artifact
            if artifact is None:
                raise RuntimeError("ResidentPredictor.setup requires a loaded model artifact.")

            predictor = self._model._predictor
            model_object = artifact.model_object
            if is_jax_compatible(model_object):
                predictor_fn = getattr(predictor, "fn", predictor)
                if self._mesh is not None:
                    # mesh-resident artifact: parameters commit to every mesh device
                    # once (sharded per param_specs, else replicated); the compiled
                    # predictor then runs tensor/data-parallel across the mesh
                    from unionml_tpu.parallel.mesh import named_sharding_tree, replicated

                    shardings = (
                        named_sharding_tree(self._mesh, self._param_specs)
                        if self._param_specs is not None
                        else replicated(self._mesh)
                    )
                    self._device_model_object = jax.device_put(model_object, shardings)  # graftlint: disable=data-race -- published once under _setup_lock; readers run only after the _ready check, which happens-after this write
                else:
                    # keep the artifact resident on device: no host->device transfer per request
                    self._device_model_object = jax.tree_util.tree_map(jax.numpy.asarray, model_object)  # graftlint: disable=data-race -- published once under _setup_lock; readers run only after the _ready check, which happens-after this write
                self._compiled = jax.jit(predictor_fn)  # graftlint: disable=data-race -- published once under _setup_lock; readers run only after the _ready check, which happens-after this write
                if self._warmup:
                    self._warm()  # graftlint: disable=lock-order -- one-time init: racing first requests MUST wait for compile+warm before serving, so blocking under _setup_lock is the contract
            else:
                logger.info("Model object is not a jax pytree; serving will run the predictor eagerly.")
            self._ready = True

    def _warm(self) -> None:
        """Compile the smallest bucket ahead of the first request."""
        try:
            example = self._example_processed(self._buckets[0])
            if example is None:
                logger.info(
                    "No warmup template (pass example_features to serve()); first request will compile."
                )
                return
            from unionml_tpu.utils import hard_sync

            hard_sync(self._compiled(self._device_model_object, example))
            logger.info("Resident predictor warmed (bucket=%d).", self._buckets[0])
        except Exception as exc:
            # keep the compiled predictor: the synthetic example may simply have the
            # wrong dtype/shape for this model; the first real request still compiles
            logger.info("Warmup skipped (%s: %s); first request will compile.", type(exc).__name__, exc)

    def _example_processed(self, batch: int) -> Optional[Any]:
        """A processed, bucket-shaped feature pytree for warmup compilation.

        Priority: run the user-supplied ``example_features`` request rows through the
        real feature pipeline and pad them exactly like a live request (covers
        multi-input/tokenized models), else synthesize zero features from flat
        feature-column metadata.
        """
        if self._example_features is not None:
            example = self._example_features
            if isinstance(example, list) and example:
                # resize the example rows to the requested bucket so warmup compiles
                # the executable real requests will actually hit (smallest bucket)
                example = [example[i % len(example)] for i in range(batch)]
            processed = self._model.dataset.get_features(example)
            padded, _, _ = self._pad_to_buckets(processed)
            return padded
        feature_columns = getattr(self._model.dataset, "_features", None)
        if feature_columns:
            return jax.numpy.zeros((batch, len(feature_columns)), dtype=jax.numpy.float32)
        return None

    def _bucket_for(self, n: int) -> int:
        return _ladder_value(self._buckets, n)

    # ------------------------------------------------------------------ padding

    def _array_leaves(self, processed: Any):
        """Flatten processed features; returns (leaves, treedef) or None if any leaf
        is not a batch-dim array (opaque features run eagerly)."""
        leaves, treedef = jax.tree_util.tree_flatten(processed)
        if not leaves:
            return None
        arrays = []
        for leaf in leaves:
            if not is_jax_compatible(leaf) or not hasattr(leaf, "shape") or getattr(leaf, "ndim", 0) < 1:
                return None
            arrays.append(leaf)
        n = arrays[0].shape[0]
        if any(a.shape[0] != n for a in arrays):
            return None
        return arrays, treedef, n

    def _pad_to_buckets(self, processed: Any):
        """Pad every array leaf's batch dim (and sequence dim, when configured) up the
        bucket ladders. Returns (padded_pytree, original_batch, batch_bucket).

        Sequence-dim padding applies only to DICT (multi-input/tokenized) features: a
        single flat feature MATRIX — even an integer one (ordinal/categorical
        encodings) — has a fixed width that must never grow fabricated columns."""
        is_multi_input = isinstance(processed, dict)
        flat = self._array_leaves(processed)
        if flat is None:
            raise ValueError("features are not a batch-dim array pytree")
        arrays, treedef, n = flat
        bucket = self._bucket_for(n)
        padded = []
        for a in arrays:
            a = np.asarray(a) if not isinstance(a, jax.Array) else a
            if a.dtype == np.float64:
                a = a.astype(np.float32)
            pad = [(0, 0)] * a.ndim
            if bucket != n:
                pad[0] = (0, bucket - n)
            # dim 1 is a sequence axis for integer leaves (token ids / masks) and
            # rank>=3 leaves (batch, seq, features); a rank-2 FLOAT leaf is a flat
            # feature matrix whose width must never be padded (a dense (b, 10)
            # input would otherwise grow fabricated zero columns)
            is_seq_leaf = np.issubdtype(a.dtype, np.integer) or a.ndim >= 3
            if self._seq_buckets is not None and a.ndim >= 2 and is_seq_leaf and is_multi_input:
                seq = a.shape[1]
                seq_bucket = _ladder_value(self._seq_buckets, seq)
                if seq_bucket != seq:
                    pad[1] = (0, seq_bucket - seq)
            if any(p != (0, 0) for p in pad):
                a = np.pad(np.asarray(a), pad)
            padded.append(self._to_device(a, bucket))
        return jax.tree_util.tree_unflatten(treedef, padded), n, bucket

    def _to_device(self, leaf: Any, bucket: int) -> Any:
        """Place one padded leaf: batch-sharded over the mesh's data axis when the
        bucket divides evenly (per-row work fans out), replicated otherwise;
        plain single-device transfer without a mesh."""
        if self._mesh is None:
            return jax.numpy.asarray(leaf)
        from unionml_tpu.parallel.mesh import batch_axis_size, batch_sharding, replicated

        n_shards = batch_axis_size(self._mesh)
        sharding = (
            batch_sharding(self._mesh)
            if n_shards > 1 and bucket % n_shards == 0
            else replicated(self._mesh)
        )
        return jax.device_put(leaf, sharding)

    # ------------------------------------------------------------------ request path

    def predict(self, features: Any = None, **reader_kwargs) -> Any:
        """Request-path prediction; uses the resident executable when possible."""
        if not self._ready:  # graftlint: disable=data-race -- benign double-checked fast path; setup() re-checks under _setup_lock before doing any work
            self.setup()
        if self._compiled is None or features is None:
            return self._model.predict(features=features, **reader_kwargs)

        processed = self._model.dataset.get_features(features)
        try:
            padded, n, bucket = self._pad_to_buckets(processed)
        except ValueError:
            return self._model.predict(features=features, **reader_kwargs)

        shape_sig = tuple(
            (getattr(leaf, "shape", None), str(getattr(leaf, "dtype", "")))
            for leaf in jax.tree_util.tree_leaves(padded)
        )
        # warm status is snapshotted BEFORE dispatch: a request that starts while
        # another request is still paying this shape's trace+compile waits on that
        # same compile, so only requests that started after the shape was marked
        # warm (at a prior call's completion) may record a steady-state sample
        with self._device_times_lock:
            was_warm = shape_sig in self._timed_shapes
        t0 = time.perf_counter()
        try:
            predictions = self._compiled(self._device_model_object, padded)
        except Exception as exc:
            logger.info("Resident predict failed (%s); falling back to eager predict.", exc)
            self._compiled = None
            return self._model.predict(features=features, **reader_kwargs)
        predictions = jax.device_get(predictions)  # the fetch is the device barrier
        elapsed_ms = (time.perf_counter() - t0) * 1e3
        with self._device_times_lock:
            if was_warm:
                self._device_times_ms.append(elapsed_ms)
            else:  # this call (and any concurrent peer) paid trace+compile: never record it
                self._timed_shapes.add(shape_sig)
        # slice the padding off every batch-shaped leaf (predictor outputs may be pytrees)
        result = jax.tree_util.tree_map(
            lambda leaf: leaf[:n]
            if hasattr(leaf, "shape") and leaf.ndim >= 1 and leaf.shape[0] == bucket
            else leaf,
            predictions,
        )
        self._model._run_predict_callbacks(self._device_model_object, processed, result)
        return result
