"""Resident predictor: a pre-compiled XLA executable serving online predictions.

Reference behavior: the FastAPI path routes every request through
``model.predict(features=...)`` interpreted Python (``unionml/fastapi.py:50-64``). The
TPU-native rebuild pre-lowers and compiles the predictor at server startup for a ladder
of padded batch shapes ("bucketing"), so the request path is: host->device transfer,
run resident executable, device->host — the p50-latency metric in BASELINE.md.

Dynamic request sizes vs XLA static shapes (SURVEY.md §7 "hard parts"): request batches
pad up to the nearest bucket; predictions slice back down. Opaque model objects
(sklearn/torch) bypass compilation and run eagerly — same endpoint, same semantics.
"""

from typing import Any, Optional, Sequence, Tuple

import jax
import numpy as np

from unionml_tpu._logging import logger
from unionml_tpu.stage import is_jax_compatible

DEFAULT_BUCKETS: Tuple[int, ...] = (1, 2, 4, 8, 16, 32, 64, 128, 256)


class ResidentPredictor:
    """Holds a model artifact on-device with a compiled predict executable."""

    def __init__(
        self,
        model: Any,
        buckets: Sequence[int] = DEFAULT_BUCKETS,
        warmup: bool = True,
    ):
        self._model = model
        self._buckets = tuple(sorted(buckets))
        self._warmup = warmup
        self._compiled = None
        self._device_model_object = None
        self._ready = False

    def setup(self) -> None:
        """Decide the execution mode and (if traceable) compile + warm the predictor."""
        artifact = self._model.artifact
        if artifact is None:
            raise RuntimeError("ResidentPredictor.setup requires a loaded model artifact.")

        predictor = self._model._predictor
        model_object = artifact.model_object
        if is_jax_compatible(model_object):
            predictor_fn = getattr(predictor, "fn", predictor)
            # keep the artifact resident on device: no host->device transfer per request
            self._device_model_object = jax.tree_util.tree_map(jax.numpy.asarray, model_object)
            self._compiled = jax.jit(predictor_fn)
            if self._warmup:
                self._warm()
        else:
            logger.info("Model object is not a jax pytree; serving will run the predictor eagerly.")
        self._ready = True

    def _warm(self) -> None:
        """Compile the smallest bucket ahead of the first request."""
        try:
            example = self._example_features(self._buckets[0])
            if example is None:
                return
            jax.block_until_ready(self._compiled(self._device_model_object, example))
            logger.info("Resident predictor warmed (bucket=%d).", self._buckets[0])
        except Exception as exc:
            # keep the compiled predictor: the synthetic example may simply have the
            # wrong dtype/shape for this model; the first real request still compiles
            logger.info("Warmup skipped (%s: %s); first request will compile.", type(exc).__name__, exc)

    def _example_features(self, batch: int) -> Optional[Any]:
        """Synthesize zero features of bucket shape from the dataset's feature metadata."""
        n_features = getattr(self._model.dataset, "_features", None)
        if n_features:
            return jax.numpy.zeros((batch, len(n_features)), dtype=jax.numpy.float32)
        return None

    def _bucket_for(self, n: int) -> int:
        for bucket in self._buckets:
            if bucket >= n:
                return bucket
        # oversize requests round up to a multiple of the largest bucket
        largest = self._buckets[-1]
        return ((n + largest - 1) // largest) * largest

    def predict(self, features: Any = None, **reader_kwargs) -> Any:
        """Request-path prediction; uses the resident executable when possible."""
        if not self._ready:
            self.setup()
        if self._compiled is None or features is None:
            return self._model.predict(features=features, **reader_kwargs)

        processed = self._model.dataset.get_features(features)
        if not is_jax_compatible(processed) or not hasattr(processed, "shape"):
            return self._model.predict(features=features, **reader_kwargs)

        array = np.asarray(processed) if not isinstance(processed, jax.Array) else processed
        if array.dtype == np.float64:
            array = array.astype(np.float32)
        n = array.shape[0]
        bucket = self._bucket_for(n)
        if bucket != n:
            pad = [(0, bucket - n)] + [(0, 0)] * (array.ndim - 1)
            array = np.pad(np.asarray(array), pad)
        try:
            predictions = self._compiled(self._device_model_object, jax.numpy.asarray(array))
        except Exception as exc:
            logger.info("Resident predict failed (%s); falling back to eager predict.", exc)
            self._compiled = None
            return self._model.predict(features=features, **reader_kwargs)
        predictions = jax.device_get(predictions)
        # slice the padding off every batch-shaped leaf (predictor outputs may be pytrees)
        result = jax.tree_util.tree_map(
            lambda leaf: leaf[:n]
            if hasattr(leaf, "shape") and leaf.ndim >= 1 and leaf.shape[0] == bucket
            else leaf,
            predictions,
        )
        self._model._run_predict_callbacks(self._device_model_object, processed, result)
        return result
