"""SLO-aware request scheduling for the serving stack (admission control).

Every generation request used to enter a plain FIFO deque: no deadlines, no
priorities, no queue bound, and no way to reclaim a decode slot from a
2048-token batch job while an interactive request waited. At saturation the
scheduler — not the step function — determines tail latency (the Gemma-on-TPU
serving comparison and the TPU concurrency-limits study both measure exactly
this), so this module is the policy layer between the HTTP surface and the
decode engine:

- **Priority classes.** Requests carry a class — ``interactive`` (0),
  ``standard`` (1), ``batch`` (2) — and the queue pops in class order.
- **Anti-starvation aging.** A queued request's *effective* class improves one
  level per ``aging_s`` waited, so sustained interactive traffic cannot starve
  batch work forever; within a class, earliest-deadline-first, then arrival.
- **Bounded queue + load shedding.** The queue holds at most ``max_queue``
  requests. A submit against a full queue either displaces the worst queued
  request (when the newcomer's class is strictly better — the displaced
  request fails fast with :class:`QueueFullError`) or is itself shed. Failing
  fast with a structured, machine-readable error beats queueing unboundedly:
  the client can retry against ``Retry-After`` instead of timing out blind.
- **Deadline enforcement.** ``deadline_ms`` is a wall-clock budget from
  arrival to completion. Requests whose deadline already looks infeasible at
  submit (the queue-wait EMA alone exceeds it) shed immediately with
  :class:`DeadlineInfeasibleError`; requests that expire while queued *or
  while running* are cancelled with :class:`DeadlineExceededError` — a
  request that can no longer meet its SLO only burns slots other requests
  need.
- **Preempt-to-prefix-cache.** When a strictly-higher-class request waits and
  no slot is free, the batcher picks a victim (lowest class, most tokens
  remaining), checkpoints its prompt + generated KV into the radix prefix
  cache (:meth:`DecodeEngine.preempt`), and re-queues it — resuming costs one
  suffix prefill instead of recomputing the whole transcript. The checkpoint
  blocks are **pinned** against LRU eviction until the resume re-admits.

The scheduler is transport- and engine-agnostic pure host code: the
:class:`~unionml_tpu.serving.continuous.ContinuousBatcher` and
:class:`~unionml_tpu.serving.speculative.SpeculativeBatcher` both route
through it, so ``GET /stats`` reports one uniform counter set whichever
generator backs ``/generate``. ``SchedulerConfig(fifo=True)`` degrades the
policy to the old arrival-order queue (no priorities, no preemption) — the
control arm of the ``bench_serving.py --slo-mix`` A/B.
"""

import dataclasses
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

__all__ = [
    "PRIORITY_CLASSES",
    "DeadlineExceededError",
    "DeadlineInfeasibleError",
    "QueueFullError",
    "SchedulerConfig",
    "SchedulingError",
    "SLOScheduler",
    "Ticket",
    "parse_priority",
]

#: priority class name -> numeric class (lower = more urgent)
PRIORITY_CLASSES: Dict[str, int] = {"interactive": 0, "standard": 1, "batch": 2}
_CLASS_NAMES = {v: k for k, v in PRIORITY_CLASSES.items()}
DEFAULT_PRIORITY = PRIORITY_CLASSES["standard"]


def parse_priority(value: Any) -> int:
    """Normalize a request's priority field: a class name
    (``"interactive"``/``"standard"``/``"batch"``) or its numeric class.
    Raises ``ValueError`` for anything else (the route maps it to HTTP 400)."""
    if isinstance(value, str):
        try:
            return PRIORITY_CLASSES[value]
        except KeyError:
            raise ValueError(
                f"unknown priority {value!r}; expected one of {sorted(PRIORITY_CLASSES)}"
            ) from None
    if isinstance(value, bool) or not isinstance(value, int):
        raise ValueError(f"priority must be a class name or integer, got {value!r}")
    if value not in _CLASS_NAMES:
        raise ValueError(f"priority must be in {sorted(_CLASS_NAMES)}, got {value}")
    return value


def class_name(priority: int) -> str:
    """Human/stats name for a numeric priority class."""
    return _CLASS_NAMES.get(priority, str(priority))


class SchedulingError(RuntimeError):
    """Base of every structured scheduling rejection.

    ``reason`` is a machine-readable slug the HTTP layer forwards verbatim;
    ``retry_after_s`` (when set) becomes the ``Retry-After`` response header.
    """

    reason = "scheduling"

    def __init__(self, message: str, *, retry_after_s: Optional[float] = None) -> None:
        super().__init__(message)
        self.retry_after_s = retry_after_s


class QueueFullError(SchedulingError):
    """Shed: the bounded queue is full and the request did not outrank it (HTTP 429)."""

    reason = "queue_full"


class DeadlineInfeasibleError(SchedulingError):
    """Shed: the deadline cannot plausibly be met given current queueing (HTTP 503)."""

    reason = "deadline_infeasible"


class DeadlineExceededError(SchedulingError):
    """The deadline passed while the request was queued or running (HTTP 504)."""

    reason = "deadline_exceeded"


@dataclasses.dataclass(frozen=True)
class SchedulerConfig:
    """Policy knobs for :class:`SLOScheduler`.

    :param max_queue: bound on queued (not yet admitted) requests. Submits
        against a full queue shed — the newcomer, or the worst queued request
        when the newcomer's class is strictly better.
    :param aging_s: a queued request's effective class improves one level per
        this many seconds waited (anti-starvation). ``0`` disables aging.
    :param preempt: allow preempt-to-prefix-cache when a strictly-higher-class
        request waits with no free slot (requires the engine's prefix cache).
    :param shed_infeasible: shed submits whose deadline is already smaller
        than the observed queue-wait EMA (:class:`DeadlineInfeasibleError`).
    :param retry_after_s: advisory retry delay attached to shed errors (the
        HTTP layer emits it as ``Retry-After``).
    :param fifo: degrade to pure arrival order — priorities, aging, and
        preemption are ignored (deadlines and the queue bound still apply).
        The control arm of the scheduler-vs-FIFO bench A/B.
    :param speculative_classes: request classes that decode speculatively when
        the engine supports it (:class:`~unionml_tpu.serving.speculative.
        SpeculativeEngine`). Speculation is an ITL play — it spends draft
        compute to shorten per-token latency — so it defaults ON for
        ``interactive`` only: ``batch`` traffic wants plain throughput, and
        ``standard`` sits wherever the operator's bench says. A request's own
        ``sampling={"speculative": ...}`` always overrides the class default.
    """

    max_queue: int = 256
    aging_s: float = 2.0
    preempt: bool = True
    shed_infeasible: bool = True
    retry_after_s: float = 1.0
    fifo: bool = False
    speculative_classes: Tuple[str, ...] = ("interactive",)


@dataclasses.dataclass(eq=False)  # identity semantics: queue membership, not field equality
class Ticket:
    """One queued request: payload plus its SLO and bookkeeping state.

    ``sink`` is whatever completion callback the owning batcher uses (it is
    opaque to the scheduler). ``deadline`` is an absolute ``time.monotonic()``
    instant (or ``None``). ``resume`` holds a
    :class:`~unionml_tpu.serving.continuous.PreemptedSlot` when the ticket is
    a preempted request waiting to re-admit; resume tickets bypass the queue
    bound (shedding one would forfeit work already paid for) and keep their
    original ``enqueued`` time so aging continues across the preemption.
    """

    prompt: Any
    budget: int
    sampling: Dict[str, Any]
    sink: Any
    priority: int = DEFAULT_PRIORITY
    deadline: Optional[float] = None
    enqueued: float = 0.0
    seq: int = -1
    resume: Optional[Any] = None
    #: set by the scheduler when a later, higher-class submit displaces this
    #: queued ticket (the owner delivers/raises it)
    shed_exc: Optional[SchedulingError] = None
    #: queue wait measured at pop time (ms), for TTFT decomposition
    queue_wait_ms: Optional[float] = None
    #: trace correlation id (set by the owning batcher when telemetry is on;
    #: rides the ticket across preemption, salvage, and fleet failover)
    request_id: Optional[str] = None

    def effective_priority(self, now: float, aging_s: float) -> int:
        """Class after anti-starvation aging: one level better per ``aging_s``
        waited, floored at the most urgent class."""
        if aging_s <= 0:
            return self.priority
        return max(0, self.priority - int((now - self.enqueued) / aging_s))

    def expired(self, now: float) -> bool:
        return self.deadline is not None and now > self.deadline


class SLOScheduler:
    """Bounded multi-class request queue with aging, shedding, and deadlines.

    Thread-safe: submits arrive from asyncio handler threads while the engine
    worker pops — every mutation runs under the internal lock. The scheduler
    never touches the engine; preemption and cancellation are *decisions*
    surfaced to the owning batcher, which performs the engine work.
    """

    def __init__(self, config: Optional[SchedulerConfig] = None, *, telemetry: Optional[Any] = None) -> None:
        if config is not None and not isinstance(config, SchedulerConfig):
            raise TypeError(f"expected SchedulerConfig, got {type(config)!r}")
        self.config = config or SchedulerConfig()
        #: optional Telemetry; every record site is OUTSIDE _lock (lock-leaf)
        self._telemetry = telemetry
        #: optional zero-arg provider of the engine's block-pool occupancy
        #: (``DecodeEngine.pool_signal``; None on dense engines). Set by the
        #: owning batcher before traffic, like ``_telemetry`` — and invoked
        #: OUTSIDE ``_lock`` so the scheduler lock stays a leaf.
        self.pool_signal: Optional[Callable[[], Optional[Dict[str, Any]]]] = None
        self._lock = threading.Lock()
        self._queued: List[Ticket] = []  # guarded-by: _lock
        self._seq = 0  # guarded-by: _lock
        # lifetime counters (the /stats scheduler block) — guarded-by: _lock
        self.submitted = 0  # guarded-by: _lock
        self.admitted = 0  # guarded-by: _lock
        self.shed_queue_full = 0  # guarded-by: _lock
        self.shed_deadline_infeasible = 0  # guarded-by: _lock
        self.deadline_misses_queued = 0  # guarded-by: _lock
        self.deadline_misses_running = 0  # guarded-by: _lock
        self.preemptions = 0  # guarded-by: _lock
        self.resumes = 0  # guarded-by: _lock
        self.queue_wait_ema_ms: Optional[float] = None  # guarded-by: _lock
        # per-class queue-wait EMAs: interactive traffic should not inherit
        # batch-class waits in the infeasible-deadline estimate, and a fleet
        # router wants the class-resolved signal — guarded-by: _lock
        self.queue_wait_ema_ms_by_class: Dict[str, Optional[float]] = {
            name: None for name in PRIORITY_CLASSES
        }

    # ------------------------------------------------------------------ intake

    def make_ticket(
        self,
        prompt: Any,
        budget: int,
        sampling: Optional[Dict[str, Any]],
        sink: Any,
        *,
        priority: Any = None,
        deadline_ms: Optional[float] = None,
        now: Optional[float] = None,
    ) -> Ticket:
        """Build (but do not queue) a ticket, validating the SLO fields.

        ``deadline_ms`` is a wall budget from *now* to completion; it must be
        a positive number. ``priority`` accepts a class name or numeric class
        (``None`` = standard).
        """
        now = time.monotonic() if now is None else now
        pr = DEFAULT_PRIORITY if priority is None else parse_priority(priority)
        deadline = None
        if deadline_ms is not None:
            if isinstance(deadline_ms, bool) or not isinstance(deadline_ms, (int, float)):
                raise ValueError(f"deadline_ms must be a number, got {deadline_ms!r}")
            if deadline_ms <= 0:
                raise ValueError(f"deadline_ms must be > 0, got {deadline_ms}")
            deadline = now + float(deadline_ms) / 1e3
        return Ticket(
            prompt=prompt, budget=budget, sampling=dict(sampling or {}), sink=sink,
            priority=pr, deadline=deadline, enqueued=now,
        )

    def submit(self, ticket: Ticket, *, now: Optional[float] = None) -> Optional[Ticket]:
        """Queue a ticket, shedding on overload.

        Raises :class:`DeadlineInfeasibleError` when the observed queue-wait
        EMA already exceeds the ticket's remaining deadline, and
        :class:`QueueFullError` when the queue is at ``max_queue`` and the
        ticket does not strictly outrank the worst queued request. When it
        *does* outrank one, that request is displaced instead: it is removed,
        its ``shed_exc`` is set, and it is returned for the caller to fail —
        the scheduler never invokes sinks itself.
        """
        now = time.monotonic() if now is None else now
        with self._lock:
            self.submitted += 1
            # prefer the ticket's OWN class EMA (an interactive request should
            # not be shed because batch work waited long); fall back to the
            # global EMA until that class has observed a pop
            wait_ema = self.queue_wait_ema_ms_by_class.get(class_name(ticket.priority))
            if wait_ema is None:
                wait_ema = self.queue_wait_ema_ms
            if (
                self.config.shed_infeasible
                and ticket.deadline is not None
                and wait_ema is not None
                and wait_ema / 1e3 > ticket.deadline - now
            ):
                self.shed_deadline_infeasible += 1
                raise DeadlineInfeasibleError(
                    f"deadline {round((ticket.deadline - now) * 1e3)}ms is below the "
                    f"current queue wait (~{round(wait_ema)}ms)",
                    retry_after_s=self.config.retry_after_s,
                )
            displaced: Optional[Ticket] = None
            if len(self._queued) >= self.config.max_queue:
                displaced = self._displaceable(ticket, now)
                if displaced is None:
                    self.shed_queue_full += 1
                    raise QueueFullError(
                        f"queue full ({self.config.max_queue} requests waiting)",
                        retry_after_s=self.config.retry_after_s,
                    )
                self._queued.remove(displaced)
                displaced.shed_exc = QueueFullError(
                    "displaced by a higher-priority request under a full queue",
                    retry_after_s=self.config.retry_after_s,
                )
                self.shed_queue_full += 1
            ticket.seq = self._seq
            self._seq += 1
            self._queued.append(ticket)
            return displaced

    def requeue(self, ticket: Ticket, *, preemption: bool = True) -> None:
        """Put a preempted — or failure-salvaged, with ``preemption=False`` —
        ticket back in the queue (bypasses the bound and the infeasibility
        shed: its work is already partially paid for). Deadlines and class
        ride along unchanged, so SLO enforcement survives recovery."""
        with self._lock:
            ticket.seq = self._seq
            self._seq += 1
            ticket.queue_wait_ms = None
            self._queued.append(ticket)
            if preemption:
                self.preemptions += 1

    # ---------------------------------------------------------------- dispatch

    def _order_key(self, ticket: Ticket, now: float) -> Tuple:
        if self.config.fifo:
            return (ticket.seq,)
        return (
            ticket.effective_priority(now, self.config.aging_s),
            ticket.deadline if ticket.deadline is not None else float("inf"),
            ticket.seq,
        )

    def _displaceable(self, newcomer: Ticket, now: float) -> Optional[Ticket]:
        """Worst queued ticket a strictly-better newcomer may displace (never
        a resume ticket, never under FIFO). Strictly better means a more
        urgent EFFECTIVE class — arrival order never justifies displacing
        (that would turn the bound into a shove-the-queue race)."""
        if self.config.fifo:
            return None
        candidates = [t for t in self._queued if t.resume is None]  # graftlint: disable=data-race -- submit() is the only caller and already holds _lock
        if not candidates:
            return None
        worst = max(candidates, key=lambda t: self._order_key(t, now))
        if newcomer.effective_priority(now, self.config.aging_s) < worst.effective_priority(
            now, self.config.aging_s
        ):
            return worst
        return None

    def take_expired(self, now: Optional[float] = None) -> List[Ticket]:
        """Remove and return every queued ticket whose deadline has passed
        (the caller fails their sinks with :class:`DeadlineExceededError`)."""
        now = time.monotonic() if now is None else now
        with self._lock:
            expired = [t for t in self._queued if t.expired(now)]
            if expired:
                self._queued = [t for t in self._queued if not t.expired(now)]
                self.deadline_misses_queued += len(expired)
            return expired

    def pop(self, max_n: int, now: Optional[float] = None) -> List[Ticket]:
        """Up to ``max_n`` tickets in scheduling order (effective class, then
        earliest deadline, then arrival; pure arrival under FIFO). Records
        each ticket's queue wait into the EMA and ``ticket.queue_wait_ms``."""
        if max_n <= 0:
            return []
        now = time.monotonic() if now is None else now
        with self._lock:
            self._queued.sort(key=lambda t: self._order_key(t, now))
            taken, self._queued = self._queued[:max_n], self._queued[max_n:]
        for ticket in taken:
            self._note_pop(ticket, now)
        return taken

    def pop_ticket(self, ticket: Ticket, now: Optional[float] = None) -> bool:
        """Remove one specific ticket (the speculative facade's turn-taking
        pop); returns False when it is no longer queued (expired/displaced)."""
        now = time.monotonic() if now is None else now
        with self._lock:
            try:
                self._queued.remove(ticket)
            except ValueError:
                return False
        self._note_pop(ticket, now)
        return True

    def _note_pop(self, ticket: Ticket, now: float) -> None:
        """Account one admission (the ticket is already off the queue)."""
        wait_ms = max(0.0, (now - ticket.enqueued) * 1e3)
        ticket.queue_wait_ms = wait_ms
        cls = class_name(ticket.priority)
        with self._lock:
            self.queue_wait_ema_ms = (
                wait_ms
                if self.queue_wait_ema_ms is None
                else 0.8 * self.queue_wait_ema_ms + 0.2 * wait_ms
            )
            prev = self.queue_wait_ema_ms_by_class.get(cls)
            self.queue_wait_ema_ms_by_class[cls] = (
                wait_ms if prev is None else 0.8 * prev + 0.2 * wait_ms
            )
            self.admitted += 1
            if ticket.resume is not None:
                self.resumes += 1
        if self._telemetry is not None:  # outside _lock: telemetry is lock-leaf
            self._telemetry.set_class(ticket.request_id, cls)
            self._telemetry.queue_wait_ms.observe(wait_ms, cls)
            self._telemetry.span(
                ticket.request_id, "queue_wait", dur_ms=round(wait_ms, 3), cls=cls,
                resume=ticket.resume is not None,
            )
            if ticket.resume is not None:
                self._telemetry.resumes_total.inc()

    def peek(self, now: Optional[float] = None) -> Optional[Ticket]:
        """The ticket :meth:`pop` would return first (not removed)."""
        now = time.monotonic() if now is None else now
        with self._lock:
            if not self._queued:
                return None
            return min(self._queued, key=lambda t: self._order_key(t, now))

    def remove(self, ticket: Ticket) -> bool:
        """Drop one queued ticket (owner-side cancel); False when not queued."""
        with self._lock:
            try:
                self._queued.remove(ticket)
                return True
            except ValueError:
                return False

    def best_waiting_priority(self) -> Optional[int]:
        """The most urgent STATIC class currently queued (``None`` when empty,
        or under FIFO). Static — not aged — on purpose: aging exists to
        guarantee queue admission, not to let batch work preempt runners."""
        if self.config.fifo:
            return None
        with self._lock:
            if not self._queued:
                return None
            return min(t.priority for t in self._queued)

    def note_deadline_miss_running(self) -> None:
        """Count one running request cancelled at its deadline (batcher-side)."""
        with self._lock:
            self.deadline_misses_running += 1

    def drain(self) -> List[Ticket]:
        """Remove and return every queued ticket (batcher close)."""
        with self._lock:
            drained, self._queued = self._queued, []
            return drained

    # ------------------------------------------------------------------- stats

    @property
    def depth(self) -> int:
        with self._lock:
            return len(self._queued)

    def load_signal(self) -> Dict[str, Any]:
        """The ONE signal dict the fleet router and the autoscaler score
        from: queue depth plus the global and per-class queue-wait EMAs
        (taken in one lock hold so the numbers are mutually consistent),
        and — when the owning batcher wired a paged engine's
        ``pool_signal`` provider — the block-pool occupancy under
        ``"pool"`` (``num_blocks`` plus ``free``/``live``/``cached``/
        ``pinned`` fractions, ``available_blocks``, and the scalar
        ``pressure``; ``None`` on dense engines). The provider is called
        BEFORE the scheduler lock is taken (both locks stay leaves). Cheap
        enough to call on every route decision (host ints/floats only)."""
        provider = self.pool_signal
        pool = provider() if provider is not None else None
        with self._lock:
            return {
                "depth": len(self._queued),
                "queue_wait_ema_ms": self.queue_wait_ema_ms,
                "per_class": dict(self.queue_wait_ema_ms_by_class),
                "pool": pool,
            }

    def stats(self) -> Dict[str, Any]:
        """The ``GET /stats`` → ``generation.scheduler`` block: per-class
        queue depth, queue-wait EMA, shed / preemption / deadline-miss
        counters, the configured policy, and (paged engines) the same
        ``pool`` occupancy block :meth:`load_signal` carries."""
        provider = self.pool_signal
        pool = provider() if provider is not None else None
        with self._lock:
            depth_by_class = {name: 0 for name in PRIORITY_CLASSES}
            for ticket in self._queued:
                depth_by_class[class_name(ticket.priority)] += 1
            return {
                "policy": "fifo" if self.config.fifo else "priority",
                "max_queue": self.config.max_queue,
                "depth": len(self._queued),
                "depth_by_class": depth_by_class,
                "queue_wait_ema_ms": None
                if self.queue_wait_ema_ms is None
                else round(self.queue_wait_ema_ms, 3),
                "per_class": {
                    name: None if ema is None else round(ema, 3)
                    for name, ema in self.queue_wait_ema_ms_by_class.items()
                },
                "submitted": self.submitted,
                "admitted": self.admitted,
                "shed_queue_full": self.shed_queue_full,
                "shed_deadline_infeasible": self.shed_deadline_infeasible,
                "deadline_misses_queued": self.deadline_misses_queued,
                "deadline_misses_running": self.deadline_misses_running,
                "preemptions": self.preemptions,
                "resumes": self.resumes,
                "pool": pool,
            }
