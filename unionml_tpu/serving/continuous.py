"""Continuous batching for the GPT generation serving path.

The reference serves predictions one request at a time through FastAPI
(``unionml/fastapi.py:50-64``); its hot loop is a single predictor call. For
autoregressive generation that design wastes the accelerator: a new request must
wait for every in-flight generation to finish. Continuous batching — the
vLLM/Orca serving discipline — keeps ONE compiled decode step running over a
fixed set of slots, inserting incoming requests into free slots *between steps*
and evicting finished ones, so throughput stays at batch-decode levels while
per-request latency stays at single-request levels.

TPU-first shape discipline: everything the device sees is static.

- The KV cache is a ``(num_slots, heads, max_len, head_dim)`` pytree allocated
  once. A request occupies one slot; its cache rows are dense in ``[0, len)``.
- Each slot decodes at its OWN position: the decode step passes ``position`` as
  a ``(num_slots,)`` vector and the model scatters each row's K/V into its own
  column (see ``DecoderBlock`` per-row positions, ``models/gpt.py``). No global
  column counter, no gaps, no compaction; a freed slot is reusable immediately
  because a new request's mask (``k_pos <= position_r``) never reaches stale
  columns before its own decode overwrites them.
- Prefill runs per request at batch 1, padded right to a small set of bucket
  lengths (one compile per bucket), then one ``dynamic_update_slice`` per layer
  copies the bucket into the slot's cache rows.
- The decode step jit-compiles exactly once per engine (all shapes fixed).

``DecodeEngine`` is the synchronous core (useful directly in scripts/tests);
``ContinuousBatcher`` runs it on a worker thread behind an asyncio API for the
serving app's ``/generate`` route.
"""

import asyncio
import collections
import dataclasses
import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from unionml_tpu._logging import logger

#: default prompt-prefill bucket lengths (right-padded; one XLA compile each)
DEFAULT_PREFILL_BUCKETS = (16, 32, 64, 128, 256, 512)


@dataclasses.dataclass(frozen=True)
class StepEvent:
    """One slot's outcome for one engine step."""

    slot: int
    token: int
    #: False for an EOS token (consumed, not part of the completion)
    emit: bool
    finished: bool


class DecodeEngine:
    """Slot-based continuous-batching decode engine over a GPT-style model.

    :param model: a :class:`~unionml_tpu.models.gpt.GPTLMHeadModel` (anything with
        ``.config`` and ``.apply(variables, ids, cache=, position=)`` matching its
        incremental contract).
    :param variables: trained model variables (``{"params": ...}``).
    :param num_slots: concurrent sequences held on device (the decode batch).
    :param max_len: per-slot cache capacity (prompt + generated tokens). A slot
        force-finishes when its length reaches ``max_len - 1``.
    :param eos_token_id: token that terminates a completion (not emitted).
    :param temperature: 0 = greedy (exactly reproduces
        :func:`unionml_tpu.models.gpt.generate` row by row); > 0 samples — note
        sampled streams depend on engine scheduling order, unlike ``generate``.
    :param prefill_buckets: allowed padded prompt lengths; prompts longer than the
        largest bucket (or ``max_len``) are rejected with ``ValueError``.
    :param quantize: ``"int8"`` stores matmul kernels as per-channel int8
        (:mod:`unionml_tpu.ops.quant`) — single-token decode is HBM-bandwidth
        bound, so int8 weights halve the per-step weight traffic vs bf16;
        dequantization happens inside the compiled step and fuses into the
        matmuls. ``None`` (default) serves full-precision weights.
    """

    def __init__(
        self,
        model: Any,
        variables: Any,
        *,
        num_slots: int = 8,
        max_len: Optional[int] = None,
        eos_token_id: Optional[int] = None,
        temperature: float = 0.0,
        prefill_buckets: Sequence[int] = DEFAULT_PREFILL_BUCKETS,
        seed: int = 0,
        quantize: Optional[str] = None,
    ) -> None:
        from unionml_tpu.models.gpt import init_cache

        config = model.config
        max_len = max_len or config.max_position_embeddings
        if max_len > config.max_position_embeddings:
            raise ValueError(
                f"max_len ({max_len}) exceeds max_position_embeddings "
                f"({config.max_position_embeddings})"
            )
        if quantize not in (None, "int8"):
            raise ValueError(f"Unknown quantize mode {quantize!r}; expected None or 'int8'")
        if quantize == "int8":
            from unionml_tpu.ops.quant import dequantize_tree, quantize_tree

            variables = quantize_tree(variables)
            maybe_dequant = dequantize_tree
        else:
            maybe_dequant = lambda tree: tree

        self._model = model
        self._variables = variables
        self._config = config
        self.num_slots = num_slots
        self.max_len = max_len
        self.eos_token_id = eos_token_id
        self.temperature = float(temperature)
        # a bucket equal to max_len is fine: prompts are < max_len and the padded
        # prefill occupies exactly the slot's cache columns
        self._buckets = tuple(sorted(b for b in prefill_buckets if b <= max_len)) or (max_len - 1,)

        self._cache = init_cache(config, num_slots, max_len)
        self._lens = jnp.zeros((num_slots,), jnp.int32)
        self._last_logits = jnp.zeros((num_slots, config.vocab_size), jnp.float32)
        self._seed = seed
        self._resets = 0
        self._key = jax.random.PRNGKey(seed)

        # host mirrors (authoritative for scheduling; device arrays follow them)
        self._active = np.zeros(num_slots, dtype=bool)
        self._lens_host = np.zeros(num_slots, dtype=np.int64)
        self._remaining = np.zeros(num_slots, dtype=np.int64)
        # per-slot sampling controls (requests may override the engine defaults)
        self._slot_temp = np.full(num_slots, self.temperature, dtype=np.float32)
        self._slot_top_k = np.zeros(num_slots, dtype=np.int32)
        self._slot_top_p = np.ones(num_slots, dtype=np.float32)

        def _decode_body(variables, cache, last_logits, lens, active, key, temp, top_k, top_p, *, sampling):
            """One decode step — the single shared body for the single-step fns AND
            the lookahead scans, so sampling/freeze rules cannot drift between them.

            ``sampling`` is a trace-time switch: the all-greedy program skips the
            sort/softmax sampling machinery entirely; the sampling program honors
            per-slot temperature/top-k/top-p (greedy rows via ``temperature == 0``).
            """
            from unionml_tpu.ops.sampling import sample_logits

            # dequant here (not hoisted) so weight reads stay int8 in HBM
            variables = maybe_dequant(variables)
            key, subkey = jax.random.split(key)
            if sampling:
                tokens = sample_logits(last_logits, subkey, temp, top_k, top_p)
            else:
                tokens = jnp.argmax(last_logits, axis=-1).astype(jnp.int32)
            logits, cache = model.apply(variables, tokens[:, None], cache=cache, position=lens)
            # inactive rows freeze: length and logits unchanged, their (ignored)
            # cache write lands on a column their own future prefill/decode rewrites
            new_lens = jnp.where(active, jnp.minimum(lens + 1, max_len - 1), lens)
            new_logits = jnp.where(active[:, None], logits[:, -1, :], last_logits)
            return cache, new_logits, new_lens, tokens, key

        def _make_step(sampling: bool):
            def _fn(variables, cache, last_logits, lens, active, key, temp, top_k, top_p):
                return _decode_body(
                    variables, cache, last_logits, lens, active, key, temp, top_k, top_p,
                    sampling=sampling,
                )

            return jax.jit(_fn, donate_argnums=(1, 2))

        self._make_step = _make_step
        self._step_fns: Dict[bool, Any] = {}

        def _prefill(variables, prompt_ids, length):
            variables = maybe_dequant(variables)
            local_cache = init_cache(config, 1, prompt_ids.shape[1])
            logits, local_cache = model.apply(variables, prompt_ids, cache=local_cache, position=0)
            # right padding + causal attention: the logits at the last REAL token
            # are unaffected by the padded tail
            return local_cache, jnp.take(logits[0], length - 1, axis=0)

        self._prefill_fn = jax.jit(_prefill)  # re-traces per bucket shape (bounded)

        def _insert(cache, lens, last_logits, local_cache, local_logits, slot, length):
            def put(full, local):
                return jax.lax.dynamic_update_slice(full, local.astype(full.dtype), (slot, 0, 0, 0))

            cache = jax.tree_util.tree_map(put, cache, local_cache)
            return (
                cache,
                lens.at[slot].set(length),
                last_logits.at[slot].set(local_logits.astype(jnp.float32)),
            )

        self._insert_fn = jax.jit(_insert, donate_argnums=(0, 1, 2))

        def _make_multi_step(n_steps: int, sampling: bool):
            """K decode steps fused into one device program (``lax.scan``).

            One host↔device round-trip per K tokens instead of per token: the
            per-step token fetch is pure overhead (measured ~70ms over a remote
            device tunnel, TPU_PROBES.log 2026-07-29; host sync + launch cost
            device-local too). Slot retirement runs inside the scan with the same
            rules the host applies (eos / budget / cache room), so a fused burst
            emits exactly what K sequential :meth:`step` calls would; the host
            replays the fetched token matrix to update its mirrors identically.
            """

            def _multi(variables, cache, last_logits, lens, active, remaining, key, temp, top_k, top_p):
                def body(carry, _):
                    cache, last_logits, lens, active, remaining, key = carry
                    cache, new_logits, new_lens, tokens, key = _decode_body(
                        variables, cache, last_logits, lens, active, key, temp, top_k, top_p,
                        sampling=sampling,
                    )
                    new_remaining = jnp.where(active, remaining - 1, remaining)
                    finished = (new_remaining <= 0) | (new_lens >= max_len - 1)
                    if eos_token_id is not None:
                        finished = finished | (tokens == eos_token_id)
                    new_active = active & ~finished
                    carry = (cache, new_logits, new_lens, new_active, new_remaining, key)
                    return carry, (tokens, active)

                carry = (cache, last_logits, lens, active, remaining, key)
                (cache, last_logits, lens, active, remaining, key), (toks, masks) = jax.lax.scan(
                    body, carry, None, length=n_steps
                )
                return cache, last_logits, lens, key, toks, masks

            return jax.jit(_multi, donate_argnums=(1, 2))

        self._make_multi_step = _make_multi_step
        self._scan_fns: Dict[Tuple[int, bool], Any] = {}

    # ------------------------------------------------------------------ scheduling

    @property
    def free_slots(self) -> List[int]:
        return [int(s) for s in np.flatnonzero(~self._active)]

    @property
    def num_active(self) -> int:
        return int(self._active.sum())

    def bucket_for(self, prompt_len: int) -> int:
        for bucket in self._buckets:
            if bucket >= prompt_len:
                return bucket
        raise ValueError(
            f"prompt length {prompt_len} exceeds the largest prefill bucket "
            f"({self._buckets[-1]}); raise prefill_buckets/max_len or truncate"
        )

    def add_request(
        self,
        prompt_ids: Sequence[int],
        max_new_tokens: int,
        *,
        temperature: Optional[float] = None,
        top_k: int = 0,
        top_p: float = 1.0,
    ) -> int:
        """Prefill ``prompt_ids`` into a free slot; returns the slot index.

        ``temperature`` (``None`` = the engine default), ``top_k`` (``0`` = off)
        and ``top_p`` (``1.0`` = off) set THIS request's sampling controls; slots
        with heterogeneous settings share every decode step (one program, per-row
        controls — :mod:`unionml_tpu.ops.sampling`).

        Raises ``RuntimeError`` when no slot is free (callers should gate on
        ``free_slots``) and ``ValueError`` for empty/oversized prompts. The
        effective budget is capped by cache capacity: generation force-finishes
        when the slot's length reaches ``max_len - 1``.
        """
        prompt = np.asarray(prompt_ids, dtype=np.int32).reshape(-1)
        if prompt.size == 0:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if prompt.size >= self.max_len:
            raise ValueError(f"prompt length {prompt.size} >= max_len ({self.max_len})")
        from unionml_tpu.ops.sampling import validate_sampling

        temperature, top_k, top_p = validate_sampling(temperature, top_k, top_p)
        temperature = self.temperature if temperature is None else temperature
        free = self.free_slots
        if not free:
            raise RuntimeError("no free decode slots")
        slot = free[0]
        bucket = self.bucket_for(prompt.size)
        padded = np.zeros((1, bucket), dtype=np.int32)
        padded[0, : prompt.size] = prompt
        local_cache, local_logits = self._prefill_fn(
            self._variables, jnp.asarray(padded), prompt.size
        )
        self._cache, self._lens, self._last_logits = self._insert_fn(
            self._cache, self._lens, self._last_logits, local_cache, local_logits,
            slot, prompt.size,
        )
        self._active[slot] = True
        self._lens_host[slot] = prompt.size
        self._remaining[slot] = max_new_tokens
        self._slot_temp[slot] = temperature
        self._slot_top_k[slot] = int(top_k)
        self._slot_top_p[slot] = float(top_p)
        return slot

    def reset(self) -> None:
        """Reallocate device state and clear all slots.

        Required after a failed :meth:`step`: the step donates the cache/logits
        buffers, so a deferred device error (surfacing at the token fetch, after
        the state variables were already reassigned) leaves them poisoned and out
        of sync with the host mirrors. In-flight requests are abandoned.
        """
        from unionml_tpu.models.gpt import init_cache

        self._cache = init_cache(self._config, self.num_slots, self.max_len)
        self._lens = jnp.zeros((self.num_slots,), jnp.int32)
        self._last_logits = jnp.zeros((self.num_slots, self._config.vocab_size), jnp.float32)
        # the key is also a step output, so it is poisoned too; a fresh
        # reset-counted key keeps sampled streams from repeating the pre-crash run
        self._resets += 1
        self._key = jax.random.PRNGKey(self._seed + self._resets)
        self._active[:] = False
        self._lens_host[:] = 0
        self._remaining[:] = 0
        self._slot_temp[:] = self.temperature
        self._slot_top_k[:] = 0
        self._slot_top_p[:] = 1.0

    def _apply_token(self, slot: int, token: int) -> StepEvent:
        """Advance the host mirrors for one decoded token (same rules as on device)."""
        self._remaining[slot] -= 1
        self._lens_host[slot] = min(self._lens_host[slot] + 1, self.max_len - 1)
        is_eos = self.eos_token_id is not None and token == self.eos_token_id
        finished = (
            is_eos
            or self._remaining[slot] <= 0
            or self._lens_host[slot] >= self.max_len - 1
        )
        if finished:
            self._active[slot] = False
        return StepEvent(slot=slot, token=token, emit=not is_eos, finished=finished)

    def step(self, lookahead: int = 1) -> List[StepEvent]:
        """Decode for every active slot; returns per-slot events.

        :param lookahead: number of decode steps fused into ONE device program and
            ONE host sync (``lax.scan``). The burst emits exactly what ``lookahead``
            sequential calls would — slot retirement (eos / budget / cache room)
            runs inside the scan — at 1/lookahead the host-sync overhead. The
            trade-off is token delivery latency: streamed tokens arrive in bursts.
            Clamped to the largest useful depth for the current slots; compiled
            once per distinct depth.

        A device failure mid-step resets the engine (see :meth:`reset`) and
        re-raises; every in-flight request is lost but the engine stays usable.
        """
        if not self._active.any():
            return []
        lookahead = max(1, int(lookahead))
        if lookahead > 1:
            # no point scanning past the moment the last slot can retire — but a
            # clamp to the EXACT depth would compile a distinct scan program per
            # tail length, so round up to the next power of two: a bounded ladder
            # of programs (log2 K of them), at most `needed` wasted masked steps
            room = np.minimum(
                self._remaining[self._active],
                (self.max_len - 1) - self._lens_host[self._active],
            )
            needed = max(1, int(room.max()))
            if needed < lookahead:
                lookahead = min(lookahead, 1 << (needed - 1).bit_length())
        # the all-greedy program skips the sampling machinery; heterogeneous slots
        # share the sampling program with per-row controls
        sampling = bool((self._slot_temp[self._active] > 0).any())
        active_dev = jnp.asarray(self._active)
        temp_dev = jnp.asarray(self._slot_temp)
        top_k_dev = jnp.asarray(self._slot_top_k)
        top_p_dev = jnp.asarray(self._slot_top_p)
        if lookahead == 1:
            fn = self._step_fns.get(sampling)
            if fn is None:
                fn = self._step_fns[sampling] = self._make_step(sampling)
            try:
                self._cache, self._last_logits, self._lens, tokens, self._key = fn(
                    self._variables, self._cache, self._last_logits, self._lens,
                    active_dev, self._key, temp_dev, top_k_dev, top_p_dev,
                )
                tokens_host = np.asarray(jax.device_get(tokens))  # hard sync (see utils.hard_sync)
            except Exception:
                self.reset()
                raise
            return [
                self._apply_token(int(slot), int(tokens_host[int(slot)]))
                for slot in np.flatnonzero(self._active)
            ]

        fn = self._scan_fns.get((lookahead, sampling))
        if fn is None:
            fn = self._scan_fns[(lookahead, sampling)] = self._make_multi_step(lookahead, sampling)
        remaining_dev = jnp.asarray(
            np.minimum(self._remaining, np.iinfo(np.int32).max), dtype=jnp.int32
        )
        try:
            (
                self._cache,
                self._last_logits,
                self._lens,
                self._key,
                tokens,
                masks,
            ) = fn(
                self._variables, self._cache, self._last_logits, self._lens,
                active_dev, remaining_dev, self._key, temp_dev, top_k_dev, top_p_dev,
            )
            tokens_host = np.asarray(jax.device_get(tokens))
            masks_host = np.asarray(jax.device_get(masks))
        except Exception:
            self.reset()
            raise
        events: List[StepEvent] = []
        for i in range(tokens_host.shape[0]):
            events.extend(
                self._apply_token(int(slot), int(tokens_host[i, int(slot)]))
                for slot in np.flatnonzero(masks_host[i])
            )
        return events

    def abort_all(self) -> None:
        """Deactivate every slot (in-flight state is abandoned; cache reuse is safe)."""
        self._active[:] = False

    def cancel(self, slot: int) -> None:
        """Deactivate one slot (its request is abandoned; the slot is reusable)."""
        self._active[slot] = False

    def generate(
        self,
        prompt_ids: Sequence[int],
        max_new_tokens: int,
        *,
        lookahead: int = 1,
        temperature: Optional[float] = None,
        top_k: int = 0,
        top_p: float = 1.0,
    ) -> List[int]:
        """Single-request convenience driver (tests/scripts): run one request to
        completion on an otherwise-idle engine and return its emitted tokens."""
        slot = self.add_request(
            prompt_ids, max_new_tokens, temperature=temperature, top_k=top_k, top_p=top_p
        )
        out: List[int] = []
        while self._active[slot]:
            for event in self.step(lookahead):
                if event.slot == slot and event.emit:
                    out.append(event.token)
        return out


class _FutureSink:
    """Buffers emitted tokens; resolves an asyncio future with the full list."""

    #: set by the consumer when it abandons the request (disconnect/early exit);
    #: the worker cancels the slot instead of delivering to a dead consumer
    cancelled = False

    def __init__(self, loop: asyncio.AbstractEventLoop, future: asyncio.Future) -> None:
        self._loop = loop
        self._future = future
        self._tokens: List[int] = []

    def emit(self, token: int) -> None:
        self._tokens.append(token)

    def finish(self) -> None:
        tokens = list(self._tokens)
        self._loop.call_soon_threadsafe(
            lambda: self._future.done() or self._future.set_result(tokens)
        )

    def fail(self, exc: BaseException) -> None:
        self._loop.call_soon_threadsafe(
            lambda: self._future.done() or self._future.set_exception(exc)
        )


_STREAM_DONE = object()


class _QueueSink:
    """Forwards each token to an asyncio queue as it decodes (streaming)."""

    cancelled = False

    def __init__(self, loop: asyncio.AbstractEventLoop, queue: "asyncio.Queue") -> None:
        self._loop = loop
        self._queue = queue

    def emit(self, token: int) -> None:
        self._loop.call_soon_threadsafe(self._queue.put_nowait, token)

    def finish(self) -> None:
        self._loop.call_soon_threadsafe(self._queue.put_nowait, _STREAM_DONE)

    def fail(self, exc: BaseException) -> None:
        self._loop.call_soon_threadsafe(self._queue.put_nowait, exc)


class ContinuousBatcher:
    """Asyncio facade running a :class:`DecodeEngine` on a worker thread.

    ``await generate(prompt_ids, max_new_tokens)`` enqueues a request; the worker
    admits queued requests into free slots between decode steps and resolves each
    future with the completed token list. ``stream(...)`` yields tokens as they
    decode instead. One engine step at a time, no step blocking the event loop.

    :param lookahead: decode steps fused per device dispatch (see
        :meth:`DecodeEngine.step`). Raises throughput by cutting host syncs;
        streamed tokens arrive in bursts of up to this size, and queued requests
        wait up to a burst before admission — keep it small (4-16) for
        interactive serving.
    """

    def __init__(self, engine: DecodeEngine, *, lookahead: int = 1) -> None:
        self._engine = engine
        self._lookahead = max(1, int(lookahead))
        self._pending: "collections.deque[Tuple[np.ndarray, int, Dict[str, Any], Any]]" = collections.deque()
        self._sinks: Dict[int, Any] = {}
        self._lock = threading.Lock()
        self._work = threading.Event()
        self._closed = False
        self._worker: Optional[threading.Thread] = None

    @property
    def engine(self) -> DecodeEngine:
        return self._engine

    def _ensure_worker(self) -> None:
        if self._worker is None or not self._worker.is_alive():
            self._worker = threading.Thread(target=self._run, name="continuous-batcher", daemon=True)
            self._worker.start()

    def _submit(
        self, prompt_ids: Sequence[int], max_new_tokens: int, sink: Any, sampling: Optional[Dict[str, Any]] = None
    ) -> None:
        prompt = np.asarray(prompt_ids, dtype=np.int32).reshape(-1)
        # surface bad requests on the caller's side, not the worker's
        if prompt.size == 0:
            raise ValueError("empty prompt")
        self._engine.bucket_for(prompt.size)
        with self._lock:
            if self._closed:
                raise RuntimeError("batcher is closed")
            self._pending.append((prompt, int(max_new_tokens), sampling or {}, sink))
        self._ensure_worker()
        self._work.set()

    async def generate(
        self, prompt_ids: Sequence[int], max_new_tokens: int, **sampling
    ) -> List[int]:
        loop = asyncio.get_running_loop()
        future: asyncio.Future = loop.create_future()
        self._submit(prompt_ids, max_new_tokens, _FutureSink(loop, future), sampling)
        return await future

    async def stream(self, prompt_ids: Sequence[int], max_new_tokens: int, **sampling):
        """Async iterator of tokens, yielded as the engine decodes them.

        The request shares slots (and decode steps) with every other in-flight
        request; per-token latency is one engine step. Abandoning the iterator
        early (client disconnect) cancels the request's decode slot.
        """
        loop = asyncio.get_running_loop()
        queue: "asyncio.Queue" = asyncio.Queue()
        sink = _QueueSink(loop, queue)
        self._submit(prompt_ids, max_new_tokens, sink, sampling)
        try:
            while True:
                item = await queue.get()
                if item is _STREAM_DONE:
                    return
                if isinstance(item, BaseException):
                    raise item
                yield item
        finally:
            # reached on normal completion too (cancelling a finished request
            # is a no-op); on early exit it frees the slot for other requests
            sink.cancelled = True

    def _deliver(self, sink: Any, method: str, *args) -> bool:
        """Invoke a sink callback, absorbing consumer-side failures.

        A dead consumer (its event loop closed after a disconnect/early exit)
        raises from ``call_soon_threadsafe``; that must cost only this request —
        never the worker thread, which every other in-flight request depends on.
        """
        try:
            getattr(sink, method)(*args)
            return True
        except Exception:
            logger.warning("sink %s delivery failed (consumer gone?); dropping request", method)
            return False

    def _admit(self) -> None:
        while True:
            with self._lock:
                if not self._pending or not self._engine.free_slots:
                    return
                prompt, budget, sampling, sink = self._pending.popleft()
            if sink.cancelled:  # consumer gave up while queued
                continue
            try:
                slot = self._engine.add_request(prompt, budget, **sampling)
            except Exception as exc:  # reject this request, keep serving others
                self._deliver(sink, "fail", exc)
                continue
            self._sinks[slot] = sink

    def _run(self) -> None:
        while True:
            with self._lock:
                if self._closed and not self._pending and not self._sinks:
                    return
            self._admit()
            if self._engine.num_active == 0:
                self._work.clear()
                # re-check under the flag: a request may have landed just now
                with self._lock:
                    if self._pending or self._closed:
                        continue
                self._work.wait(timeout=0.5)
                continue
            try:
                # full house + queued work: shorten bursts so a retiring slot is
                # readmitted within a few steps — but not to 1, which would forfeit
                # the whole lookahead win for the entire duration of an overload
                with self._lock:
                    contended = bool(self._pending) and not self._engine.free_slots
                events = self._engine.step(
                    min(self._lookahead, 4) if contended else self._lookahead
                )
            except Exception as exc:  # fail every in-flight request loudly
                logger.exception("continuous-batching step failed")
                for sink in self._sinks.values():
                    self._deliver(sink, "fail", RuntimeError(str(exc)))
                self._sinks.clear()
                self._engine.abort_all()
                continue
            for event in events:
                sink = self._sinks.get(event.slot)
                if sink is None:
                    continue
                if sink.cancelled:  # consumer abandoned the stream mid-decode
                    del self._sinks[event.slot]
                    self._engine.cancel(event.slot)
                    continue
                ok = True
                if event.emit:
                    ok = self._deliver(sink, "emit", event.token)
                if not ok:
                    del self._sinks[event.slot]
                    self._engine.cancel(event.slot)
                    continue
                if event.finished:
                    del self._sinks[event.slot]
                    self._deliver(sink, "finish")

    def close(self) -> None:
        with self._lock:
            self._closed = True
        self._work.set()
        if self._worker is not None:
            self._worker.join(timeout=5.0)
