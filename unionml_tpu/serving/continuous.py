"""Continuous batching for the GPT generation serving path.

The reference serves predictions one request at a time through FastAPI
(``unionml/fastapi.py:50-64``); its hot loop is a single predictor call. For
autoregressive generation that design wastes the accelerator: a new request must
wait for every in-flight generation to finish. Continuous batching — the
vLLM/Orca serving discipline — keeps ONE compiled decode step running over a
fixed set of slots, inserting incoming requests into free slots *between steps*
and evicting finished ones, so throughput stays at batch-decode levels while
per-request latency stays at single-request levels.

TPU-first shape discipline: everything the device sees is static.

- The KV cache is a ``(num_slots, heads, max_len, head_dim)`` pytree allocated
  once. A request occupies one slot; its cache rows are dense in ``[0, len)``.
- Each slot decodes at its OWN position: the decode step passes ``position`` as
  a ``(num_slots,)`` vector and the model scatters each row's K/V into its own
  column (see ``DecoderBlock`` per-row positions, ``models/gpt.py``). No global
  column counter, no gaps, no compaction; a freed slot is reusable immediately
  because a new request's mask (``k_pos <= position_r``) never reaches stale
  columns before its own decode overwrites them.
- Prefill is BATCHED: queued prompts sharing a bucket prefill together, up to
  ``prefill_batch`` rows per device dispatch (one compile per (rows, bucket)
  shape, both ladders bounded), then one scatter per layer copies every row into
  its slot's cache rows — N queued prompts admit in ⌈N/prefill_batch⌉ prefill
  dispatches instead of N.
- Long prompts optionally prefill in CHUNKS (``prefill_chunk``): one chunk of
  the prompt runs per engine tick, interleaved between decode steps, so a
  512-token prompt never stalls the in-flight decode batch for its whole
  prefill.
- PREFIX CACHING (``prefix_cache_blocks``): completed prompts index their KV
  into a device-side block pool behind a host radix tree
  (:mod:`unionml_tpu.serving.prefix_cache`); an admitted prompt's longest
  cached prefix is restored with one shard-local gather instead of recomputed,
  and only the uncovered suffix runs through prefill — under shared-prefix
  traffic (system prompts, few-shot templates, chat history) prefill FLOPs
  drop by the shared fraction while outputs stay token-identical.
- The decode step jit-compiles exactly once per engine (all shapes fixed).
- PIPELINED DECODE (``pipeline=True``, default): slot lifecycle (``active``,
  ``remaining``) lives ON DEVICE and retires *inside* the compiled step, so
  each tick dispatches step N+1 *before* blocking on step N's token fetch —
  the host applies tokens, admits requests, and fans out events while the
  device runs the next step, instead of the device idling behind every
  ``device_get``. Outputs are token-identical to the unpipelined engine;
  ``cancel``/``abort_all`` flush or discard the in-flight step so slot reuse
  can never misattribute a stale token.

Mesh-sharded serving (``mesh=``): the engine lays the model parameters out with
the GPT family's Megatron-style ``param_shardings`` table and shards the KV
cache over attention HEADS on the mesh's ``tensor`` axis, so ONE compiled decode
step (and one compiled prefill) runs tensor-parallel across every device of the
mesh — XLA inserts the all-reduces over ICI. Outputs are token-identical to the
single-device engine; scheduling, admission, and the HTTP surface above are
unchanged.

``DecodeEngine`` is the synchronous core (useful directly in scripts/tests);
``ContinuousBatcher`` runs it on a worker thread behind an asyncio API for the
serving app's ``/generate`` route — admission no longer runs off a bare FIFO
deque but through the SLO scheduler (:mod:`unionml_tpu.serving.scheduler`):
priority classes with anti-starvation aging, a bounded queue that sheds with
structured errors, deadline enforcement on queued and running requests, and
preempt-to-prefix-cache (:meth:`DecodeEngine.preempt`) that checkpoints a
low-priority victim's KV into the PR-2 radix cache so a higher-priority
arrival gets its slot and the victim resumes for one suffix prefill.

FAULT TOLERANCE (ISSUE 7): the engine fails *well*. A device-side failure
captures every live slot's salvage (host transcript + pinned radix path),
rebuilds the device state in place from host-retained params — with PRNG
continuity, so resumed sampled streams stay bit-identical — and a supervised
batcher (:mod:`unionml_tpu.serving.supervisor`) re-queues every salvageable
request to resume token-identically, paying only a suffix prefill over its
pinned blocks. NaN/Inf logits quarantine the one poisoned slot (an in-program
finiteness flag rides the fused token fetch) instead of failing the batch; a
single request's prefill death rolls admission back atomically and fails only
that request. Every failure a consumer sees is a structured
:class:`~unionml_tpu.serving.faults.EngineFailure` with a machine-readable
reason, and all of it is deterministically injectable via
:class:`~unionml_tpu.serving.faults.FaultPlan` (see ``tests/unit/test_chaos.py``).
"""

import asyncio
import dataclasses
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from unionml_tpu._logging import logger
from unionml_tpu.serving.faults import EngineFailure, FaultPlan

#: default prompt-prefill bucket lengths (right-padded; one XLA compile each)
DEFAULT_PREFILL_BUCKETS = (16, 32, 64, 128, 256, 512)


def block_demand(prompt_len: int, budget: int, *, max_len: int, block_size: int) -> int:
    """Pool blocks one request needs for its whole lifetime: prompt plus
    budget, capped at cache capacity, rounded up to whole blocks.

    This is THE paged-admission arithmetic, split out as a pure function so
    the fleet simulator (``unionml_tpu.sim``) gates its virtual admissions on
    the identical math the live batcher uses —
    :meth:`DecodeEngine.block_demand` delegates here."""
    need = min(int(prompt_len) + int(budget), int(max_len))
    return -(-need // int(block_size))


@dataclasses.dataclass(frozen=True)
class StepEvent:
    """One slot's outcome for one engine step."""

    slot: int
    token: int
    #: False for an EOS token (consumed, not part of the completion)
    emit: bool
    finished: bool
    #: time the request spent queued before admission (ms), attached to the
    #: request's FIRST decoded token only — lets a TTFT measurement decompose
    #: into queue wait vs prefill+decode (None on every later event, and for
    #: requests admitted without a queue, e.g. direct ``add_request`` calls)
    queue_wait_ms: Optional[float] = None
    #: machine-readable failure slug when the ENGINE terminated this request
    #: (``nan_logits`` quarantine, ``prefill_failed`` chunked-prefill death):
    #: the event carries no token (``emit=False``, ``finished=True``) and the
    #: consumer must fail, not finish, the request
    error: Optional[str] = None


@dataclasses.dataclass
class PreemptedSlot:
    """A preempted request's resumable checkpoint (:meth:`DecodeEngine.preempt`).

    ``tokens`` is the slot's full transcript — prompt plus every token decoded
    so far — which becomes the resume prompt; ``path`` is the radix-tree node
    chain holding the transcript's KV blocks, PINNED against LRU eviction
    until :meth:`DecodeEngine.release_preempted` (called after the resume
    re-admission acquired its own references, or when the request is
    cancelled while re-queued)."""

    tokens: List[int]
    path: List[Any]


@dataclasses.dataclass
class SalvagedSlot:
    """One slot's resumable state captured at engine-failure time.

    Unlike :class:`PreemptedSlot` (a deliberate checkpoint that device-copies
    the transcript's KV into the pool first), salvage is captured while the
    device state may be POISONED, so it is host-only: ``tokens`` is the slot's
    replayed transcript (prompt + every token already delivered), ``path`` is
    whatever radix-tree chain the slot already held — pinned, it survives the
    rebuild and shrinks the resume to a suffix prefill — and ``remaining`` is
    the slot's unspent token budget. The collector must eventually unpin
    ``path`` (:meth:`DecodeEngine.release_preempted` accepts the same shape).
    """

    slot: int
    tokens: List[int]
    path: List[Any]
    remaining: int


class DecodeEngine:
    """Slot-based continuous-batching decode engine over a GPT-style model.

    :param model: a :class:`~unionml_tpu.models.gpt.GPTLMHeadModel` (anything with
        ``.config`` and ``.apply(variables, ids, cache=, position=)`` matching its
        incremental contract).
    :param variables: trained model variables (``{"params": ...}``).
    :param num_slots: concurrent sequences held on device (the decode batch).
    :param max_len: per-slot cache capacity (prompt + generated tokens). A slot
        force-finishes when its length reaches ``max_len - 1``.
    :param eos_token_id: token that terminates a completion (not emitted).
    :param temperature: 0 = greedy (exactly reproduces
        :func:`unionml_tpu.models.gpt.generate` row by row); > 0 samples — note
        sampled streams depend on engine scheduling order, unlike ``generate``.
    :param prefill_buckets: allowed padded prompt lengths; prompts longer than the
        largest bucket (or ``max_len``) are rejected with ``ValueError``.
    :param quantize: ``"int8"`` stores matmul kernels as per-channel int8
        (:mod:`unionml_tpu.ops.quant`) — single-token decode is HBM-bandwidth
        bound, so int8 weights halve the per-step weight traffic vs bf16;
        dequantization happens inside the compiled step and fuses into the
        matmuls. ``None`` (default) serves full-precision weights.
    :param mesh: a ``jax.sharding.Mesh`` (see :mod:`unionml_tpu.parallel.mesh`)
        for tensor-parallel serving: parameters shard Megatron-style
        (:func:`unionml_tpu.models.gpt.param_shardings`), the KV cache shards
        over attention heads on the ``tensor`` axis, and every compiled step runs
        across all mesh devices. ``None`` (default) serves single-device.
    :param prefill_batch: max prompts prefilled per device dispatch — queued
        prompts sharing a bucket admit together, ⌈N/prefill_batch⌉ dispatches
        for N prompts (one compile per (rows, bucket) shape).
    :param prefill_chunk: when set, prompts longer than this prefill in chunks of
        this many tokens, ONE chunk per engine tick between decode steps, so a
        long prompt cannot stall in-flight decodes for its whole prefill.
    :param prefix_cache_blocks: when > 0, enable PREFIX CACHING with a device
        KV block pool of this many blocks (see :meth:`enable_prefix_cache`):
        completed prompts index their KV block-by-block into a host radix tree
        (:class:`~unionml_tpu.serving.prefix_cache.PrefixCache`), and admission
        restores each prompt's longest cached prefix instead of recomputing it
        — only the uncovered suffix prefills. ``0`` (default) disables caching.
    :param prefix_block_size: tokens per cached KV block (match granularity and
        pool-copy unit); prefixes match in whole blocks only.
    :param prefix_cache_generated: also index a retiring slot's GENERATED
        tokens' KV, so a multi-turn follow-up prompt (previous prompt +
        completion + new text) hits the whole previous turn, not just its
        prompt.
    :param pipeline: depth-1 PIPELINED decode (default on): each :meth:`step`
        dispatches the next device step *before* fetching the previous step's
        tokens, so the host applies tokens / admits requests while the device
        runs — the device never idles waiting for host scheduling. Legal
        because slot lifecycle (``active``/``remaining``) lives on device and
        retires *inside* the compiled step; outputs are token-identical to
        ``pipeline=False`` (events are simply delivered one tick later).
        ``cancel``/``abort_all``/``reset`` flush or discard the in-flight
        step, so no stale token is ever applied to a reused slot.
    :param paged: PAGED KV decode (default on): the block pool is the ONLY KV
        storage — a slot's "cache" is an int32 block-table row plus a length,
        attention gathers K/V through the table inside the compiled step, and
        decode writes each new token into the slot's tail block in place.
        Admission allocates ``ceil(min(prompt+budget, max_len)/block_size)``
        blocks instead of reserving a dense ``max_len`` row, so concurrency is
        bounded by LIVE tokens, not worst-case length; exhaustion raises the
        structured ``EngineFailure(reason="pool_exhausted", retryable=True)``.
        Prefix-cache hits splice shared pool blocks straight into the table
        (no restore copy) and retiring slots index their blocks by adoption
        (no save copy). Outputs are token-identical to ``paged=False``: the
        gathered table is a contiguous logical view, masked columns contribute
        exactly zero, and the engine's scheduling is unchanged. ``False``
        selects the legacy dense per-slot caches (the A/B bench arm).
    :param pool_blocks: total pool size in blocks for paged mode (including
        one reserved scratch block that absorbs retired rows' masked writes).
        Default ``None`` sizes the pool so block admission can never fail when
        a slot is free — ``num_slots * ceil(max_len/block_size) +
        prefix_cache_blocks + 1`` — i.e. dense-equivalent capacity semantics;
        pass an explicit smaller value to serve more concurrent short requests
        than dense could at the same KV byte budget (the paged bench arm).
    :param kv_quantize: ``"int8"`` stores the paged block pool as symmetric
        int8 with per-block-per-head f32 scales resident alongside (see
        :func:`unionml_tpu.models.gpt.init_block_pool`) — int8 is what crosses
        HBM on every decode gather, so a fixed byte budget holds ~2× the
        blocks of a bf16 pool. All writes quantize in-program (prefill insert,
        chunk prefill, the in-place decode append) and the gather dequantizes
        inside the same compiled step; allocation/splice/adopt/preempt move
        block IDs only, so the scheduler is oblivious. Requires ``paged=True``.
        Quality is budgeted, not bit-exact: see the pinned
        ``KV_INT8_*_BUDGET`` constants in :mod:`unionml_tpu.ops.quant`.
    :param kv_quantize_skip_layers: layer indices whose pool stays full
        precision (outlier-sensitive layers); their leaves simply carry no
        scale arrays, which is how the attention layer detects the mode.
    :param faults: a :class:`~unionml_tpu.serving.faults.FaultPlan` arming
        deterministic fault injection (chaos tests and ``bench_serving
        --chaos`` only). ``None`` (production) makes every hook a single host
        branch — no device work, no host syncs added to the hot path.
    """

    def __init__(
        self,
        model: Any,
        variables: Any,
        *,
        num_slots: int = 8,
        max_len: Optional[int] = None,
        eos_token_id: Optional[int] = None,
        temperature: float = 0.0,
        prefill_buckets: Sequence[int] = DEFAULT_PREFILL_BUCKETS,
        seed: int = 0,
        quantize: Optional[str] = None,
        mesh: Optional[Any] = None,
        prefill_batch: int = 4,
        prefill_chunk: Optional[int] = None,
        prefix_cache_blocks: int = 0,
        prefix_block_size: int = 16,
        prefix_cache_generated: bool = False,
        pipeline: bool = True,
        paged: bool = True,
        pool_blocks: Optional[int] = None,
        kv_quantize: Optional[str] = None,
        kv_quantize_skip_layers: Sequence[int] = (),
        faults: Optional[FaultPlan] = None,
        telemetry: Optional[Any] = None,
    ) -> None:
        from unionml_tpu.models.gpt import init_cache

        config = model.config
        max_len = max_len or config.max_position_embeddings
        if max_len > config.max_position_embeddings:
            raise ValueError(
                f"max_len ({max_len}) exceeds max_position_embeddings "
                f"({config.max_position_embeddings})"
            )
        if quantize not in (None, "int8"):
            raise ValueError(f"Unknown quantize mode {quantize!r}; expected None or 'int8'")
        if kv_quantize not in (None, "int8"):
            raise ValueError(f"Unknown kv_quantize mode {kv_quantize!r}; expected None or 'int8'")
        if kv_quantize is not None and not paged:
            raise ValueError("kv_quantize requires paged=True (the block pool is what quantizes)")
        # quantize + mesh compose: quantization happens first (below), then
        # param_shardings assigns the int8 tree's {q, scale} leaves their specs
        # (the scale inherits the kernel's channel-axis split) and place_by_specs
        # lays the QuantizedArray nodes onto the mesh like any other leaf
        if quantize == "int8":
            from unionml_tpu.ops.quant import dequantize_tree, quantize_tree

            variables = quantize_tree(variables)
            maybe_dequant = dequantize_tree
        else:
            maybe_dequant = lambda tree: tree

        self._mesh = mesh
        self._cache_sharding = None
        self._replicated = None
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec

            from unionml_tpu.models._sharding import place_by_specs
            from unionml_tpu.models.gpt import kv_cache_spec, param_shardings
            from unionml_tpu.parallel.mesh import TENSOR_AXIS

            spec_tree = param_shardings(variables, tuple(mesh.axis_names))
            variables = place_by_specs(variables, mesh, spec_tree)
            cache_spec = kv_cache_spec(config, tuple(mesh.axis_names))
            tensor_size = int(mesh.shape[TENSOR_AXIS]) if TENSOR_AXIS in mesh.axis_names else 1
            if config.num_heads % max(tensor_size, 1) != 0:
                cache_spec = PartitionSpec()  # heads don't divide: replicate the cache
            self._cache_sharding = NamedSharding(mesh, cache_spec)
            self._replicated = NamedSharding(mesh, PartitionSpec())

        self._model = model
        self._variables = variables
        self._config = config
        self.num_slots = num_slots
        self.max_len = max_len
        self.eos_token_id = eos_token_id
        self.temperature = float(temperature)
        self.prefill_batch = max(1, int(prefill_batch))
        self.prefill_chunk = int(prefill_chunk) if prefill_chunk else None
        if self.prefill_chunk is not None and self.prefill_chunk < 1:
            raise ValueError(f"prefill_chunk must be >= 1, got {prefill_chunk}")
        # a bucket equal to max_len is fine: prompts are < max_len and the padded
        # prefill occupies exactly the slot's cache columns
        self._buckets = tuple(sorted(b for b in prefill_buckets if b <= max_len)) or (max_len - 1,)

        self._seed = seed
        self._resets = 0

        #: deterministic fault-injection script (None in production: every
        #: hook is a single host ``is not None`` branch — zero device work)
        self._faults = faults
        #: span/metrics collector (None = tracing off: every hook is the same
        #: single host ``is not None`` branch as the fault hooks — no device
        #: work, no host syncs; decode timing reuses the fused-fetch stamps)
        self._telemetry = telemetry
        if faults is not None and telemetry is not None and faults.telemetry is None:
            faults.telemetry = telemetry
        #: slot -> request_id of the occupant's trace (batcher-set); spans for
        #: a slot emitted before the id binds are buffered and flushed at bind
        self._slot_rid: Dict[int, str] = {}
        self._slot_pending_spans: Dict[int, List[Tuple[str, float, Optional[float], Dict[str, Any]]]] = {}
        #: engine-failure incidents survived (the batcher keys recovery off a
        #: delta of this counter, like the old ``_resets`` check but precise)
        self.failure_count = 0
        #: device-state rebuilds performed (in-place recovery + supervised)
        self.rebuilds = 0
        #: requests terminated by per-slot NaN/Inf-logits quarantine
        self.quarantined_requests = 0
        #: salvage captured at the last failure, awaiting :meth:`take_salvage`
        self._salvage: List[SalvagedSlot] = []  # holds: kv-pin
        #: set when an in-place rebuild itself failed: the engine refuses work
        #: until :meth:`rebuild` succeeds (the supervisor retries with backoff;
        #: unsupervised callers retry lazily via ``_ensure_usable``)
        self._failed = False
        #: set by a donating dispatch that raised (its donated engine state is
        #: poisoned); the public entry points escalate to a full failure
        self._device_poisoned = False
        #: key-consuming steps replayed since the key's base was (re)seeded —
        #: lets a resume-rebuild reconstruct the PRNG stream so recovered
        #: sampled requests stay token-identical to a fault-free run
        self._key_steps = 0
        #: liveness timestamp (monotonic) the supervisor's watchdog reads:
        #: refreshed at every step dispatch and token-fetch completion
        self.last_heartbeat = time.monotonic()
        #: slots admitted by the admit_many call in progress (rollback set for
        #: its atomic non-poisoning unwind); None outside admission
        self._admitting: Optional[List[int]] = None

        # host mirrors (authoritative for scheduling; device arrays follow them)
        self._active = np.zeros(num_slots, dtype=bool)
        #: slots holding an in-progress chunked prefill: not active (no decode
        #: yet), not free (their cache rows are being written)
        self._reserved = np.zeros(num_slots, dtype=bool)
        self._partials: Dict[int, Dict[str, Any]] = {}
        self._lens_host = np.zeros(num_slots, dtype=np.int64)
        self._remaining = np.zeros(num_slots, dtype=np.int64)
        # per-slot sampling controls (requests may override the engine defaults)
        self._slot_temp = np.full(num_slots, self.temperature, dtype=np.float32)
        self._slot_top_k = np.zeros(num_slots, dtype=np.int32)
        self._slot_top_p = np.ones(num_slots, dtype=np.float32)
        #: device dispatches spent on prefill since construction (admission
        #: batching makes this ⌈N/prefill_batch⌉ per N same-bucket prompts)
        self.prefill_dispatches = 0
        #: REAL prompt tokens run through prefill compute (padding excluded);
        #: prefix-cache hits shrink this to the uncovered suffix per request —
        #: the FLOP counter the prefix-heavy bench and its CI test assert on
        self.prefill_tokens_computed = 0
        #: pool→slot prefix restores / slot→pool block saves dispatched
        self.prefix_restore_dispatches = 0
        self.prefix_save_dispatches = 0

        #: depth-1 pipelining: dispatch step N+1 before fetching step N's tokens
        self.pipeline = bool(pipeline)
        #: the dispatched-but-unfetched step: ``(tokens, masks, bads, n_steps)``
        #: device arrays (leading axis = steps in the burst), or None when drained
        self._inflight: Optional[Tuple[Any, Any, Any, int]] = None
        #: slots QUARANTINED while ``_inflight`` was already dispatched: that
        #: burst still carries their (garbage) tokens under an active mask, so
        #: its replay must skip them — the slot may hold a NEW occupant by
        #: then, and crediting the stale token would corrupt its stream (the
        #: same hazard cancel() avoids by flushing first, which a quarantine —
        #: raised DURING a replay — cannot)
        self._inflight_skip: set = set()
        #: events replayed by an out-of-band flush (cancel/admission), delivered
        #: by the next :meth:`step` so the batcher's fan-out sees every token
        self._pending_events: List[StepEvent] = []
        #: lifetime generation counters (the /stats surface both generator
        #: kinds share — see serving.app and serving.speculative)
        self.requests_admitted = 0
        self.tokens_decoded = 0
        #: running slots checkpointed into the prefix cache by :meth:`preempt`
        self.preempted_requests = 0
        #: per-slot queue wait (ms) noted by the batcher at admission
        #: (:meth:`note_queue_wait`); attached to the slot's first StepEvent
        self._slot_queue_wait: Dict[int, float] = {}
        self.ema_queue_wait_ms: Optional[float] = None
        #: device-idle accounting: a dispatch is "idle" when the device queue
        #: was empty when it was enqueued (no in-flight step); the EMAs track
        #: the host gap the device sat idle (ms) and the time the host spent
        #: blocked in the token fetch (ms)
        self.step_dispatches = 0
        self.idle_dispatches = 0
        self.ema_host_gap_ms: Optional[float] = None
        self.ema_fetch_block_ms: Optional[float] = None
        self._last_fetch_done: Optional[float] = None

        # prefix cache (disabled until enable_prefix_cache): host radix index +
        # device KV block pool + per-slot held node paths / token transcripts
        self.prefix_cache: Optional[Any] = None
        self.prefix_cache_generated = bool(prefix_cache_generated)
        self._prefix_block_size = int(prefix_block_size)
        self._pool: Optional[Any] = None
        self._slot_path: Dict[int, List[Any]] = {}
        self._slot_tokens: Dict[int, List[int]] = {}

        #: paged KV decode: the pool is the ONLY KV storage (no dense cache)
        self.paged = bool(paged)
        #: block allocator backing the paged pool; doubles as the radix index
        #: when prefix caching is enabled. None on dense engines.
        self._allocator: Optional[Any] = None
        #: per-slot PRIVATE blocks: block index -> pool block id the slot owns
        #: (shared spliced prefix entries live in _slot_path, not here).
        #: Freeing on retirement is safe even with a step in flight: every
        #: pool WRITE chains through the pool's donation (admission inserts
        #: queue after the in-flight step), and a reused block's new positions
        #: are always written by the new owner before its attention reads them.
        self._slot_block_map: Dict[int, Dict[int, int]] = {}  # holds: kv-block
        self._explicit_pool_blocks = pool_blocks is not None
        #: int8 KV pool mode ("int8" or None) + the layers kept full-precision
        self.kv_quantize = kv_quantize
        self.kv_quantize_skip_layers = tuple(int(i) for i in kv_quantize_skip_layers)
        if any(i < 0 or i >= config.num_layers for i in self.kv_quantize_skip_layers):
            raise ValueError(
                f"kv_quantize_skip_layers {self.kv_quantize_skip_layers} out of range "
                f"for {config.num_layers} layers"
            )
        if self.paged:
            from unionml_tpu.models.gpt import block_table_width
            from unionml_tpu.serving.prefix_cache import PrefixCache

            # the pool's block size IS the prefix cache's block size (one
            # layout, spliced freely); clamp so short-context engines with the
            # default granularity still page
            bs = min(int(prefix_block_size), max_len)
            self._prefix_block_size = bs
            self._table_width = block_table_width(max_len, bs)
            per_slot = self._table_width - 1  # data columns (excludes scratch)
            if pool_blocks is None:
                # dense-equivalent capacity: a free slot can always allocate
                pool_blocks = num_slots * per_slot + int(prefix_cache_blocks) + 1
            if int(pool_blocks) < 2:
                raise ValueError(f"pool_blocks must be >= 2 (1 usable + scratch), got {pool_blocks}")
            self.pool_blocks = int(pool_blocks)
            #: reserved block absorbing retired rows' masked scatter; never allocated
            self._scratch_block = self.pool_blocks - 1
            self._allocator = PrefixCache(
                self.pool_blocks - 1, bs, telemetry=self._telemetry
            )
            # resolve the decode-attention backend ONCE (same shape key the
            # model's dispatcher sees at trace time: full table width + pool
            # block size) so telemetry reports what the traced program runs
            from unionml_tpu.ops.paged_attention import resolve_paged_impl

            self.paged_attn_impl: Optional[str] = resolve_paged_impl(
                getattr(config, "paged_attn_impl", "auto"),
                self._table_width,
                bs,
                config.num_heads,
                config.head_dim,
            )
            if self._telemetry is not None:
                self._telemetry.paged_attn_impl.set(1.0, self.paged_attn_impl)
        else:
            self.paged_attn_impl = None

        self._init_device_state()
        self._sync_sampling_mirrors()

        cache_sharding = self._cache_sharding

        def _constrain_cache(tree):
            # keep the head-sharded layout pinned through every compiled program:
            # propagation alone may let GSPMD re-layout the (donated) cache
            if cache_sharding is None:
                return tree
            return jax.tree_util.tree_map(
                lambda leaf: jax.lax.with_sharding_constraint(leaf, cache_sharding), tree
            )

        def _decode_body(variables, cache, last_logits, lens, active, key, temp, top_k, top_p, *, sampling):
            """One decode step — the single shared body for every step program
            (any burst depth), so sampling/freeze rules cannot drift between them.

            ``sampling`` is a trace-time switch: the all-greedy program skips the
            sort/softmax sampling machinery entirely; the sampling program honors
            per-slot temperature/top-k/top-p (greedy rows via ``temperature == 0``).
            """
            from unionml_tpu.ops.sampling import sample_logits

            # dequant here (not hoisted) so weight reads stay int8 in HBM
            variables = maybe_dequant(variables)
            new_key, subkey = jax.random.split(key)
            # an all-inactive step consumes NO key: pipelining may dispatch one
            # masked step past full retirement, and sampled streams must stay
            # identical to an engine that (knowing the retirement) never ran it
            new_key = jnp.where(jnp.any(active), new_key, key)
            # per-slot finiteness of the logits this step SAMPLES from: a
            # NaN/Inf row (weight corruption, a NaN storm, injected poison)
            # flags only its own slot, rides the fetch with tokens/masks, and
            # quarantines that request host-side — siblings keep decoding
            bad = ~jnp.all(jnp.isfinite(last_logits), axis=-1)
            if sampling:
                tokens = sample_logits(last_logits, subkey, temp, top_k, top_p)
            else:
                tokens = jnp.argmax(last_logits, axis=-1).astype(jnp.int32)
            logits, cache = model.apply(variables, tokens[:, None], cache=cache, position=lens)
            cache = _constrain_cache(cache)
            # inactive rows freeze: length and logits unchanged, their (ignored)
            # cache write lands on a column their own future prefill/decode rewrites
            new_lens = jnp.where(active, jnp.minimum(lens + 1, max_len - 1), lens)
            new_logits = jnp.where(active[:, None], logits[:, -1, :], last_logits)
            return cache, new_logits, new_lens, tokens, new_key, bad

        def _make_step(n_steps: int, sampling: bool):
            """K decode steps fused into one device program (``lax.scan``;
            ``n_steps=1`` is the plain per-tick step).

            The program CARRIES the slot lifecycle: ``active``/``remaining``
            ride as device-resident inputs and retirement (eos / budget / cache
            room — :func:`unionml_tpu.models.gpt.advance_slot_state`) runs
            inside the scan, so the next step can be dispatched before this
            one's tokens are fetched (depth-1 pipelining) and a fused burst
            emits exactly what K sequential steps would. The host replays the
            fetched ``(tokens, masks)`` to update its mirrors identically.
            """
            from unionml_tpu.models.gpt import advance_slot_state

            def _multi(variables, cache, last_logits, lens, active, remaining, key, temp, top_k, top_p):
                def body(carry, _):
                    cache, last_logits, lens, active, remaining, key = carry
                    cache, new_logits, new_lens, tokens, key, bad = _decode_body(
                        variables, cache, last_logits, lens, active, key, temp, top_k, top_p,
                        sampling=sampling,
                    )
                    new_active, new_remaining = advance_slot_state(
                        active, remaining, new_lens, tokens, max_len, eos_token_id
                    )
                    carry = (cache, new_logits, new_lens, new_active, new_remaining, key)
                    return carry, (tokens, active, bad)

                carry = (cache, last_logits, lens, active, remaining, key)
                (cache, last_logits, lens, active, remaining, key), (toks, masks, bads) = jax.lax.scan(
                    body, carry, None, length=n_steps
                )
                return cache, last_logits, lens, active, remaining, key, toks, masks, bads

            return jax.jit(_multi, donate_argnums=(1, 2))

        self._make_step = _make_step
        self._step_fns: Dict[Tuple[int, bool], Any] = {}

        def _slot_update(active, remaining, temp, top_k, top_p, slot, is_active, budget, t, k, p):
            """Point-update the device slot mirrors for one admission/cancel —
            ONE tiny dispatch, preserving every other slot's device-side value
            (which may embed retirements from a still-unfetched in-flight step,
            so a full host upload here would be WRONG, not just slow)."""
            return (
                active.at[slot].set(is_active),
                remaining.at[slot].set(budget),
                temp.at[slot].set(t),
                top_k.at[slot].set(k),
                top_p.at[slot].set(p),
            )

        self._slot_update_fn = jax.jit(_slot_update, donate_argnums=(0, 1, 2, 3, 4))

        def _prefill(variables, prompt_ids, lengths):
            """Batched bucket prefill: (rows, bucket) prompts, one device dispatch.

            Rows are right-padded to the shared bucket; causal attention keeps
            each row's logits at its last REAL token unaffected by the padded
            tail (and by the other rows — rows are attention-independent).
            """
            variables = maybe_dequant(variables)
            rows, bucket = prompt_ids.shape
            local_cache = init_cache(config, rows, bucket)
            logits, local_cache = model.apply(variables, prompt_ids, cache=local_cache, position=0)
            idx = jnp.clip(lengths.astype(jnp.int32) - 1, 0, bucket - 1)
            last = jnp.take_along_axis(logits, idx[:, None, None], axis=1)[:, 0, :]
            return _constrain_cache(local_cache), last

        self._prefill_fn = jax.jit(_prefill)  # re-traces per (rows, bucket) shape (bounded)

        def _chunk_apply(variables, chunk_ids, local_cache, position):
            """One chunk of a long prefill: attends over the cache prefix written
            by earlier chunks (``position`` is traced — one compile per
            (chunk, cache_len) shape, not per offset)."""
            variables = maybe_dequant(variables)
            logits, local_cache = model.apply(
                variables, chunk_ids, cache=local_cache, position=position
            )
            return logits, _constrain_cache(local_cache)

        self._chunk_fn = jax.jit(_chunk_apply, donate_argnums=(2,))

        def _pick_last(logits, idx):
            """Row ``idx`` of a batch-1 chunk's logits, selected IN-PROGRAM: an
            eager ``logits[:, idx, :]`` lowers to dynamic_slice whose start
            indices ride the host→device lane implicitly — which the
            transfer-guard admission regression disallows."""
            return jax.lax.dynamic_index_in_dim(logits[0], idx, axis=0, keepdims=False)[None, :]

        self._pick_last_fn = jax.jit(_pick_last)

        def _insert(cache, lens, last_logits, local_cache, local_logits, slots, lengths):
            def put(full, local):
                width = local.shape[2]
                return full.at[slots, :, :width, :].set(local.astype(full.dtype))

            cache = jax.tree_util.tree_map(put, cache, local_cache)
            return (
                _constrain_cache(cache),
                lens.at[slots].set(lengths.astype(lens.dtype)),
                last_logits.at[slots].set(local_logits.astype(jnp.float32)),
            )

        self._insert_fn = jax.jit(_insert, donate_argnums=(0, 1, 2))

        def _restore(pool, block_ids, pad_len):
            """Gather cached prefix blocks into a fresh batch-1 local cache
            (columns beyond the prefix zero, written by the suffix prefill).
            The gather indexes the unsharded block axis: shard-local on a mesh."""
            from unionml_tpu.models.gpt import gather_block_prefix

            return _constrain_cache(gather_block_prefix(pool, block_ids, pad_len))

        # one compile per (n_blocks, pad_len) — both from small bounded ladders
        self._restore_fn = jax.jit(_restore, static_argnums=(2,))

        def _save(pool, cache, row, start_block, dst_ids, block_size):
            """Scatter one slot's cache blocks [start, start+n) into the pool at
            ``dst_ids``; row/start are traced (one compile per block count)."""
            from unionml_tpu.models.gpt import slice_cache_blocks

            blocks = slice_cache_blocks(cache, row, start_block, dst_ids.shape[0], block_size)

            def put(pool_leaf, blk):
                return pool_leaf.at[dst_ids].set(blk.astype(pool_leaf.dtype))

            return _constrain_cache(jax.tree_util.tree_map(put, pool, blocks))

        self._save_fn = jax.jit(_save, static_argnums=(5,), donate_argnums=(0,))

        if self.paged:
            # The paged programs below read self._prefix_block_size /
            # self._table_width at TRACE time, never as __init__-captured
            # locals: enable_prefix_cache can re-lay-out the pool after
            # construction, and any block-size/width change alters the pool
            # leaf and table shapes, forcing every jitted paged program to
            # retrace — which is exactly when the fresh values are re-read.

            def _decode_body_paged(
                variables, pool, tables, last_logits, lens, active, key, temp, top_k, top_p,
                *, sampling,
            ):
                """Paged twin of ``_decode_body``: same sampling/freeze/key
                rules, but K/V reads gather through the block tables and the
                token write scatters into each row's tail block. Tables ride as
                a NON-donated input — they change only at admission, between
                dispatches, so an in-flight step always reads a consistent map."""
                from unionml_tpu.ops.sampling import sample_logits

                variables = maybe_dequant(variables)
                new_key, subkey = jax.random.split(key)
                new_key = jnp.where(jnp.any(active), new_key, key)
                bad = ~jnp.all(jnp.isfinite(last_logits), axis=-1)
                if sampling:
                    tokens = sample_logits(last_logits, subkey, temp, top_k, top_p)
                else:
                    tokens = jnp.argmax(last_logits, axis=-1).astype(jnp.int32)
                # a retired row still scatters one K/V column per step (the
                # program is unmasked); aiming its position at the sentinel
                # (>= (width-1)*block_size maps every masked write to
                # table[:, -1], the trailing scratch column) sends that write
                # to scratch, so a freed block can be re-owned by another slot
                # without this row's stale table corrupting it
                sentinel = (self._table_width - 1) * self._prefix_block_size
                pos = jnp.where(active, lens, sentinel)
                cache = {"table": tables, **pool}
                logits, new_cache = model.apply(variables, tokens[:, None], cache=cache, position=pos)
                pool = {name: leaf for name, leaf in new_cache.items() if name != "table"}
                pool = _constrain_cache(pool)
                new_lens = jnp.where(active, jnp.minimum(lens + 1, max_len - 1), lens)
                new_logits = jnp.where(active[:, None], logits[:, -1, :], last_logits)
                return pool, new_logits, new_lens, tokens, new_key, bad

            def _make_step_paged(n_steps: int, sampling: bool):
                """Paged ``_make_step``: identical scan/lifecycle contract; the
                carried KV state is the (donated) pool instead of a dense cache."""
                from unionml_tpu.models.gpt import advance_slot_state

                def _multi(
                    variables, pool, tables, last_logits, lens, active, remaining, key,
                    temp, top_k, top_p,
                ):
                    def body(carry, _):
                        pool, last_logits, lens, active, remaining, key = carry
                        pool, new_logits, new_lens, tokens, key, bad = _decode_body_paged(
                            variables, pool, tables, last_logits, lens,
                            active, key, temp, top_k, top_p, sampling=sampling,
                        )
                        new_active, new_remaining = advance_slot_state(
                            active, remaining, new_lens, tokens, max_len, eos_token_id
                        )
                        carry = (pool, new_logits, new_lens, new_active, new_remaining, key)
                        return carry, (tokens, active, bad)

                    carry = (pool, last_logits, lens, active, remaining, key)
                    (pool, last_logits, lens, active, remaining, key), (toks, masks, bads) = (
                        jax.lax.scan(body, carry, None, length=n_steps)
                    )
                    return pool, last_logits, lens, active, remaining, key, toks, masks, bads

                return jax.jit(_multi, donate_argnums=(1, 3))

            self._make_step = _make_step_paged

            def _paged_insert(pool, tables, lens, last_logits, local_cache, local_logits, slots, lengths):
                """Scatter a batched bucket prefill's dense workspace into the
                admitted slots' pool blocks through their table rows. Padded
                columns past a slot's allocation map to scratch (the rows'
                unmapped tail), so the full-precision scatter needs no per-row
                length mask. Quantized layers DO mask: a padded column landing
                in an owned block must not inflate that block's absmax scale,
                so positions at/after a row's real length quantize as zeros."""
                # graftlint: disable=retrace -- deliberate trace-time read: block_size is an axis of every pool leaf and fixes the table width, so any host mutation (enable_prefix_cache re-layout) changes this program's input shapes and forces the retrace that re-reads it
                block_size = self._prefix_block_size
                rows_tables = tables[slots]  # (rows, width)
                bucket = jax.tree_util.tree_leaves(local_cache)[0].shape[2]
                cols = jnp.arange(bucket)
                blk, off = cols // block_size, cols % block_size
                dst = rows_tables[:, blk]  # (rows, bucket)
                nb = -(-bucket // block_size)
                dst_blocks = rows_tables[:, :nb]  # (rows, nb)
                pad = nb * block_size - bucket
                valid = (
                    jnp.arange(nb * block_size).reshape(nb, block_size)[None, :, :]
                    < lengths[:, None, None]
                )  # (rows, nb, bs)

                def put_full(pool_leaf, local_leaf):
                    src = jnp.moveaxis(local_leaf, 2, 1).astype(pool_leaf.dtype)
                    return pool_leaf.at[dst, :, off[None, :], :].set(src)

                def put_quantized(pool_q, pool_scale, local_leaf):
                    from unionml_tpu.ops.quant import quantize_blockwise

                    rows, heads, _, head_dim = local_leaf.shape
                    src = local_leaf.astype(jnp.float32)
                    if pad:
                        src = jnp.pad(src, ((0, 0), (0, 0), (0, pad), (0, 0)))
                    # (rows, nb, heads, bs, hd): block layout, padded tail zeroed
                    src = src.reshape(rows, heads, nb, block_size, head_dim).transpose(0, 2, 1, 3, 4)
                    src = jnp.where(valid[:, :, None, :, None], src, 0.0)
                    q, scale = quantize_blockwise(src, reduce_axes=(3, 4))
                    return pool_q.at[dst_blocks].set(q), pool_scale.at[dst_blocks].set(scale)

                new_pool = {}
                for name, layer in pool.items():
                    local = local_cache[name]
                    if "k_scale" in layer:
                        out = {}
                        for key in ("k", "v"):
                            out[key], out[key + "_scale"] = put_quantized(
                                layer[key], layer[key + "_scale"], local[key]
                            )
                        new_pool[name] = out
                    else:
                        new_pool[name] = {key: put_full(layer[key], local[key]) for key in ("k", "v")}
                pool = _constrain_cache(new_pool)
                return (
                    pool,
                    lens.at[slots].set(lengths.astype(lens.dtype)),
                    last_logits.at[slots].set(local_logits.astype(jnp.float32)),
                )

            self._paged_insert_fn = jax.jit(_paged_insert, donate_argnums=(0, 2, 3))

            def _paged_chunk(variables, chunk_ids, pool, tables, slot, position):
                """One batch-1 prefill chunk written STRAIGHT into the slot's
                pool blocks through its table row (no local workspace): this is
                both the chunked-prefill tick and the prefix-hit suffix — the
                matched prefix is already pool-resident behind the same table,
                so attending over the gathered row IS the copy-free restore."""
                variables = maybe_dequant(variables)
                row = jax.lax.dynamic_slice_in_dim(tables, slot, 1, axis=0)  # (1, width)
                cache = {"table": row, **pool}
                logits, new_cache = model.apply(variables, chunk_ids, cache=cache, position=position)
                pool = {name: leaf for name, leaf in new_cache.items() if name != "table"}
                return logits, _constrain_cache(pool)

            self._paged_chunk_fn = jax.jit(_paged_chunk, donate_argnums=(2,))

            def _write_row(tables, slot, row):
                """Point-update one slot's table row at admission (explicit
                device_put operands; the in-flight step keeps the OLD tables
                array, so this is pipelining-safe like _slot_update)."""
                return tables.at[slot].set(row)

            self._write_row_fn = jax.jit(_write_row, donate_argnums=(0,))

            def _finish_slot(lens, last_logits, slot, length, last):
                """Seal a table-resident prefill (chunked final tick / prefix
                suffix): the KV is already in the slot's blocks, only the
                length and sampling logits need the point-update."""
                return (
                    lens.at[slot].set(length),
                    last_logits.at[slot].set(last[0].astype(jnp.float32)),
                )

            self._finish_slot_fn = jax.jit(_finish_slot, donate_argnums=(0, 1))

        if prefix_cache_blocks:
            self.enable_prefix_cache(
                prefix_cache_blocks, prefix_block_size, cache_generated=prefix_cache_generated
            )

    # ------------------------------------------------------------------ scheduling

    def _init_device_state(self) -> None:
        """(Re)allocate the device-side state, laid out on the mesh when sharded.

        Paged mode allocates the block pool + per-slot block tables instead of
        the dense per-slot cache — the pool is the ONLY KV storage, so this is
        also where a rebuild discards a poisoned pool (the step donates it)."""
        from unionml_tpu.models.gpt import (
            init_block_pool, init_block_tables, init_cache, init_slot_state,
        )

        if self.paged:
            self._cache = None
            pool = init_block_pool(
                self._config,
                self.pool_blocks,
                self._prefix_block_size,
                kv_quantize=self.kv_quantize,
                kv_quantize_skip_layers=self.kv_quantize_skip_layers,
            )
            tables = init_block_tables(
                self.num_slots, self.max_len, self._prefix_block_size, self._scratch_block
            )
        else:
            self._cache = init_cache(self._config, self.num_slots, self.max_len)
        lens = jnp.zeros((self.num_slots,), jnp.int32)
        last_logits = jnp.zeros((self.num_slots, self._config.vocab_size), jnp.float32)
        key = jax.random.PRNGKey(self._seed + self._resets)
        active, remaining = init_slot_state(self.num_slots)
        if self._mesh is not None:
            if self.paged:
                pool = jax.device_put(pool, self._cache_sharding)
                tables = jax.device_put(tables, self._replicated)
            else:
                self._cache = jax.device_put(self._cache, self._cache_sharding)
            lens = jax.device_put(lens, self._replicated)
            last_logits = jax.device_put(last_logits, self._replicated)
            key = jax.device_put(key, self._replicated)
            active = jax.device_put(active, self._replicated)
            remaining = jax.device_put(remaining, self._replicated)
        if self.paged:
            self._pool, self._tables = pool, tables
        self._lens, self._last_logits, self._key = lens, last_logits, key
        self._active_dev, self._remaining_dev = active, remaining
        # any dispatched-but-unfetched step referenced the old buffers: dead now
        self._inflight = None
        self._inflight_skip = set()

    def _sync_sampling_mirrors(self) -> None:
        """Refresh the device mirrors of the per-slot sampling controls from the
        host arrays — a FULL upload, so callable only when no step is in flight
        (construction, :meth:`reset`, :meth:`abort_all`); per-admission changes
        go through the point-update path in :meth:`_activate` instead.
        """
        self._temp_dev = jnp.asarray(self._slot_temp)
        self._top_k_dev = jnp.asarray(self._slot_top_k)
        self._top_p_dev = jnp.asarray(self._slot_top_p)

    def _sync_slot_mirrors(self) -> None:
        """Re-upload the device slot lifecycle (``active``/``remaining``) from
        the host arrays. Same full-upload caveat as the sampling mirrors: the
        host view lags a dispatched step, so callers must have flushed or
        discarded the pipeline first."""
        active = jnp.asarray(self._active)
        remaining = jnp.asarray(
            np.minimum(self._remaining, np.iinfo(np.int32).max), dtype=jnp.int32
        )
        if self._mesh is not None:
            active = jax.device_put(active, self._replicated)
            remaining = jax.device_put(remaining, self._replicated)
        self._active_dev, self._remaining_dev = active, remaining

    def enable_prefix_cache(
        self, num_blocks: int, block_size: int = 16, *, cache_generated: bool = False
    ) -> None:
        """Allocate the prefix cache: a host radix index over token-id blocks
        plus a device KV block pool of ``num_blocks`` blocks of ``block_size``
        tokens, laid out with the slot cache's head-sharded spec under a mesh
        (pool↔slot copies stay shard-local). ``cache_generated`` also indexes a
        retiring slot's generated tokens for multi-turn reuse. Callable once,
        either via the constructor (``prefix_cache_blocks=``) or after
        construction (serving-app plumbing)."""
        from unionml_tpu.models.gpt import init_block_pool
        from unionml_tpu.serving.prefix_cache import PrefixCache

        if self.prefix_cache is not None:
            raise RuntimeError("prefix cache is already enabled on this engine")
        block_size = int(block_size)
        if not 1 <= block_size < self.max_len:
            raise ValueError(
                f"prefix_block_size must be in [1, max_len) = [1, {self.max_len}), got {block_size}"
            )
        if self.paged:
            # the allocator IS the index: indexing just turns on over the same
            # pool the slots already page through. A post-construction call
            # (serving-app plumbing) may change the block size / add headroom,
            # which re-lays-out the pool — only legal while nothing is held.
            from unionml_tpu.models.gpt import block_table_width

            width = block_table_width(self.max_len, block_size)
            pool_blocks = self.pool_blocks
            if not self._explicit_pool_blocks:
                pool_blocks = self.num_slots * (width - 1) + int(num_blocks) + 1
            if block_size != self._prefix_block_size or pool_blocks != self.pool_blocks:
                if self.busy or self._inflight is not None or self._allocator.slot_blocks:
                    raise RuntimeError(
                        "enable_prefix_cache cannot re-layout the block pool while "
                        "requests hold blocks; call it before admitting work"
                    )
                self._prefix_block_size = block_size
                self._table_width = width
                self.pool_blocks = pool_blocks
                self._scratch_block = pool_blocks - 1
                self._allocator = PrefixCache(
                    pool_blocks - 1, block_size, telemetry=self._telemetry
                )
                # the shape-class key changed with the re-layout: re-resolve
                # the decode backend the retraced program will dispatch to
                from unionml_tpu.ops.paged_attention import resolve_paged_impl

                self.paged_attn_impl = resolve_paged_impl(
                    getattr(self._config, "paged_attn_impl", "auto"),
                    width,
                    block_size,
                    self._config.num_heads,
                    self._config.head_dim,
                )
                if self._telemetry is not None:
                    self._telemetry.paged_attn_impl.set(1.0, self.paged_attn_impl)
                self._init_device_state()
                self._sync_sampling_mirrors()
            self.prefix_cache = self._allocator
            self.prefix_cache_generated = bool(cache_generated)
            return
        self.prefix_cache = PrefixCache(int(num_blocks), block_size, telemetry=self._telemetry)
        self.prefix_cache_generated = bool(cache_generated)
        self._prefix_block_size = block_size
        self._pool = init_block_pool(self._config, int(num_blocks), block_size)
        if self._mesh is not None:
            self._pool = jax.device_put(self._pool, self._cache_sharding)

    @property
    def free_slots(self) -> List[int]:
        # reserved slots (chunked prefill in progress) are neither active nor free
        return [int(s) for s in np.flatnonzero(~(self._active | self._reserved))]

    @property
    def num_active(self) -> int:
        return int(self._active.sum())

    @property
    def has_pending_prefill(self) -> bool:
        """Whether any slot holds an in-progress chunked prefill (the engine must
        keep ticking even with zero active decodes)."""
        return bool(self._partials)

    def bucket_for(self, prompt_len: int) -> int:
        for bucket in self._buckets:
            if bucket >= prompt_len:
                return bucket
        raise ValueError(
            f"prompt length {prompt_len} exceeds the largest prefill bucket "
            f"({self._buckets[-1]}); raise prefill_buckets/max_len or truncate"
        )

    def validate_request(
        self,
        prompt_ids: Sequence[int],
        max_new_tokens: int,
        *,
        temperature: Optional[float] = None,
        top_k: int = 0,
        top_p: float = 1.0,
    ) -> Tuple[np.ndarray, int, float, int, float]:
        """Normalize one request, raising ``ValueError`` for anything the engine
        cannot serve (empty/oversized prompt, bad budget or sampling controls).
        Returns ``(prompt, budget, temperature, top_k, top_p)``."""
        prompt = np.asarray(prompt_ids, dtype=np.int32).reshape(-1)
        if prompt.size == 0:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if prompt.size >= self.max_len:
            raise ValueError(f"prompt length {prompt.size} >= max_len ({self.max_len})")
        from unionml_tpu.ops.sampling import validate_sampling

        temperature, top_k, top_p = validate_sampling(temperature, top_k, top_p)
        temperature = self.temperature if temperature is None else temperature
        try:
            self.bucket_for(prompt.size)  # raises for prompts beyond the bucket ladder
        except ValueError:
            # a cached prefix can stand in for the missing bucket: only the
            # uncovered suffix runs prefill, so a preempted transcript longer
            # than the largest bucket (its blocks pinned) still re-admits
            if not self._prefix_coverable(prompt):
                raise
        if self.paged:
            demand = self.block_demand(prompt.size, max_new_tokens)
            if demand > self._allocator.num_blocks:
                # PERMANENT: no amount of retirement frees enough blocks, so
                # reject now (ValueError) instead of the retryable
                # pool_exhausted failure transient contention raises
                raise ValueError(
                    f"request needs {demand} KV blocks but the pool has only "
                    f"{self._allocator.num_blocks}; raise pool_blocks or lower "
                    "max_new_tokens"
                )
        return prompt, int(max_new_tokens), float(temperature), int(top_k), float(top_p)

    def _prefix_coverable(self, prompt: np.ndarray) -> bool:
        """True when the cached prefix of ``prompt`` leaves a suffix that fits
        the bucket ladder and the slot's cache rows — the admission path a
        preempted transcript resumes through. A non-acquiring probe: the
        actual match happens at admission (pinned resume blocks cannot be
        evicted in between)."""
        if self.prefix_cache is None:
            return False
        if self.prefill_chunk is not None and int(prompt.size) < self.max_len:
            return True  # the chunked path handles any in-capacity suffix
        block = self._prefix_block_size
        covered = self.prefix_cache.probe(prompt, (int(prompt.size) - 1) // block) * block
        if covered <= 0:
            return False
        try:
            return covered + self.bucket_for(int(prompt.size) - covered) <= self.max_len
        except ValueError:
            return False

    # ------------------------------------------------------------- paged blocks

    def block_demand(self, prompt_len: int, budget: int) -> int:
        """Pool blocks one request needs for its WHOLE lifetime: prompt plus
        budget, capped by cache capacity (generation force-finishes at
        ``max_len - 1``). Zero on dense engines (no block accounting) — and a
        prefix-cache hit at admission can shrink the private share below this,
        so it is the CONSERVATIVE demand the batcher gates on."""
        if not self.paged:
            return 0
        return block_demand(
            prompt_len, budget, max_len=self.max_len, block_size=self._prefix_block_size
        )

    def available_blocks(self) -> Optional[int]:
        """Blocks an admission could allocate right now — the free list plus
        every evictable cached chain; ``None`` on dense engines (unbounded).
        The batcher gates admission and block-pressure preemption on this."""
        if not self.paged:
            return None
        return self._allocator.available_blocks()

    def pool_signal(self) -> Optional[Dict[str, Any]]:
        """Counter-derived block-pool occupancy for the scheduler's
        :meth:`~unionml_tpu.serving.scheduler.SLOScheduler.load_signal`
        (fleet routing + autoscaling): ``None`` on dense engines, else
        ``num_blocks``, the free/live/cached/pinned fractions,
        ``available_blocks`` (free plus cached-minus-pinned — an upper
        bound on what eviction could reclaim), and ``pressure`` (1 minus
        the available fraction). Plain counter reads only — the EXACT
        evictable-chain walk (:meth:`available_blocks`) stays on the
        worker-thread admission path, because it traverses the radix tree
        this signal must not race with."""
        if not self.paged:
            return None
        stats = self._allocator.stats()
        total = max(1, int(stats["num_blocks"]))
        free = int(stats["free_blocks"])
        live = int(stats["slot_blocks"])
        cached = int(stats["cached_blocks"])
        pinned = int(stats["pinned_blocks"])
        available = max(0, min(total, free + cached - pinned))
        return {
            "num_blocks": total,
            "free_frac": round(free / total, 4),
            "live_frac": round(live / total, 4),
            "cached_frac": round(cached / total, 4),
            "pinned_frac": round(pinned / total, 4),
            "available_blocks": available,
            "pressure": round(1.0 - available / total, 4),
        }

    # transfers: kv-block
    def _alloc_slot_blocks(self, slot: int, start: int, need: int) -> List[int]:
        """Acquire ``need`` private pool blocks for ``slot``'s table columns
        ``[start, start+need)``, flushing the in-flight burst once on shortfall
        (its unreplayed retirements may be sitting on frees). Still short →
        the structured pool-exhaustion failure: ``retryable``, because blocks
        free as live requests retire. The grant is recorded in
        ``_slot_block_map`` immediately, so every unwind path (cancel, the
        admission orphan sweep) sees the ownership."""
        if need <= 0:
            self._slot_block_map.setdefault(slot, {})
            return []
        ids = self._allocator.alloc_blocks(need)
        if ids is None:
            if self._inflight is not None:
                self._pending_events.extend(self._fetch_inflight())
                ids = self._allocator.alloc_blocks(need)
            if ids is None:
                raise EngineFailure(
                    f"KV block pool exhausted: need {need} block(s), "
                    f"{self._allocator.available_blocks()} reclaimable of "
                    f"{self._allocator.num_blocks}",
                    reason="pool_exhausted", retryable=True,
                )
        self._slot_block_map[slot] = {start + i: b for i, b in enumerate(ids)}
        if self._telemetry is not None:
            self._telemetry.blocks_per_request.observe(float(need))
            self._note_span(slot, "block_alloc", blocks=need, shared=start)
            self._note_pool_gauges()
        return ids

    # owns: kv-block
    def _free_slot_blocks(self, slot: int) -> None:
        """Return ``slot``'s remaining private blocks to the allocator
        (retire / cancel / quarantine / preempt leftovers — blocks the radix
        index adopted already left the map). Safe mid-pipeline: see the
        ordering note on ``_slot_block_map``."""
        ids = self._slot_block_map.pop(slot, None)
        if ids:
            self._allocator.free_blocks(list(ids.values()))
            if self._telemetry is not None:
                self._note_pool_gauges()

    def _note_pool_gauges(self) -> None:
        """Refresh the pool-occupancy gauges (host counters only — no device
        work; callers gate on ``self._telemetry is not None``)."""
        stats = self._allocator.stats()
        self._telemetry.pool_free_blocks.set(float(stats["free_blocks"]))
        self._telemetry.pool_live_blocks.set(float(stats["slot_blocks"]))
        self._telemetry.pool_cached_blocks.set(float(stats["cached_blocks"]))
        self._telemetry.pool_pinned_blocks.set(float(stats["pinned_blocks"]))
        kv = self.kv_pool_stats()
        if kv:  # {} on dense engines / before the pool exists
            self._telemetry.pool_kv_bytes.set(float(kv["kv_pool_bytes"]), kv["kv_dtype"])
            self._telemetry.pool_kv_bytes_dense_equiv.set(float(kv["kv_pool_bytes_dense_equiv"]))
            if kv.get("impl"):
                self._telemetry.paged_attn_impl.set(1.0, kv["impl"])

    def kv_pool_stats(self) -> Dict[str, Any]:
        """Byte accounting of the resident KV pool layout (shapes only — no
        device sync): ``kv_dtype`` (what crosses HBM per decode gather),
        ``kv_pool_bytes`` (as stored, scale arrays included) and
        ``kv_pool_bytes_dense_equiv`` (the same positions priced at the full
        compute dtype — what capacity dashboards compare against). Empty on
        dense engines (their per-slot caches are not pool-accounted)."""
        if not self.paged or self._pool is None:
            return {}
        from unionml_tpu.models.gpt import kv_pool_bytes

        stored, full = kv_pool_bytes(self._pool, self._config.dtype)
        return {
            "kv_dtype": self.kv_quantize or str(jnp.dtype(self._config.dtype).name),
            "kv_pool_bytes": stored,
            "kv_pool_bytes_dense_equiv": full,
            # which decode-attention backend this replica's traced programs
            # run ("pallas" = fused paged kernel, "xla" = gather + attend)
            "impl": self.paged_attn_impl,
        }

    def _write_slot_row(self, slot: int, block_ids: Sequence[int]) -> None:
        """Upload one slot's block-table row: shared spliced prefix ids first,
        then private ids; every unmapped tail column points at scratch, so the
        row's masked writes always land somewhere harmless. One EXPLICIT
        ``device_put`` plus a point-update dispatch (same admission-path
        transfer discipline as ``_slot_device_update``); the in-flight step
        keeps the OLD tables array, so this never disturbs a running burst."""
        row = np.full((self._table_width,), self._scratch_block, dtype=np.int32)
        row[: len(block_ids)] = block_ids
        try:
            self._tables = self._write_row_fn(
                self._tables, *jax.device_put((np.int32(slot), row))
            )
        except Exception:
            # the row write donates the tables: a failure here consumed them
            self._device_poisoned = True
            raise

    def _activate(self, slot: int, length: int, budget: int, temp: float, top_k: int, top_p: float) -> None:
        self._active[slot] = True
        self._reserved[slot] = False
        self._lens_host[slot] = length
        self._remaining[slot] = budget
        self._slot_temp[slot] = temp
        self._slot_top_k[slot] = top_k
        self._slot_top_p[slot] = top_p
        self.requests_admitted += 1
        if self._admitting is not None:
            self._admitting.append(slot)
        self._slot_device_update(slot, True, budget, temp, top_k, top_p)

    def _slot_device_update(
        self, slot: int, is_active: bool, budget: int, temp: float, top_k: int, top_p: float
    ) -> None:
        """Mirror one slot's lifecycle + sampling controls onto the device with
        a single point-update dispatch. Admission and cancel go through here —
        never a full host upload, which would roll back OTHER slots' in-flight
        device-side retirements — so step() pays zero per-tick host→device
        transfers for any of these vectors. The scalar uploads are one EXPLICIT
        ``device_put`` (a python scalar at the jit boundary is an implicit
        transfer, which the transfer-guard admission regression disallows)."""
        scalars = jax.device_put((
            np.int32(slot), np.bool_(is_active),
            np.int32(min(int(budget), np.iinfo(np.int32).max)),
            np.float32(temp), np.int32(top_k), np.float32(top_p),
        ))
        try:
            (
                self._active_dev,
                self._remaining_dev,
                self._temp_dev,
                self._top_k_dev,
                self._top_p_dev,
            ) = self._slot_update_fn(
                self._active_dev, self._remaining_dev,
                self._temp_dev, self._top_k_dev, self._top_p_dev,
                *scalars,
            )
        except Exception:
            # the point-update donates every slot mirror: a failure here left
            # them consumed, which the public entry points escalate
            self._device_poisoned = True
            raise

    def add_request(
        self,
        prompt_ids: Sequence[int],
        max_new_tokens: int,
        *,
        temperature: Optional[float] = None,
        top_k: int = 0,
        top_p: float = 1.0,
    ) -> int:
        """Prefill ``prompt_ids`` into a free slot; returns the slot index.

        ``temperature`` (``None`` = the engine default), ``top_k`` (``0`` = off)
        and ``top_p`` (``1.0`` = off) set THIS request's sampling controls; slots
        with heterogeneous settings share every decode step (one program, per-row
        controls — :mod:`unionml_tpu.ops.sampling`).

        Raises ``RuntimeError`` when no slot is free (callers should gate on
        ``free_slots``) and ``ValueError`` for empty/oversized prompts. The
        effective budget is capped by cache capacity: generation force-finishes
        when the slot's length reaches ``max_len - 1``.

        The single-request form of :meth:`admit_many`.
        """
        return self.admit_many(
            [(prompt_ids, max_new_tokens, dict(temperature=temperature, top_k=top_k, top_p=top_p))]
        )[0]

    def admit_many(self, requests: Sequence[Tuple]) -> List[int]:
        """Admit several requests at once with BATCHED bucket prefills.

        ``requests`` is a sequence of ``(prompt_ids, max_new_tokens)`` or
        ``(prompt_ids, max_new_tokens, sampling_dict)``. Prompts sharing a
        prefill bucket run through ONE (rows, bucket) prefill dispatch, up to
        ``prefill_batch`` rows each — N queued prompts admit in
        ⌈N/prefill_batch⌉ dispatches per bucket instead of N. Prompts longer
        than ``prefill_chunk`` (when configured) admit as chunked prefills
        advanced one chunk per :meth:`step` instead.

        All requests validate BEFORE any device work (one bad request rejects
        the call with nothing scheduled); ``RuntimeError`` when fewer slots are
        free than requests. Returns the assigned slot per request, in order.

        With the prefix cache enabled, admission is TWO-PASS: a request whose
        prefix a same-call sibling is about to index (detected on host, by
        token-block comparison) defers to a second pass and restores that KV
        instead of recomputing it — a cold burst of N same-prefix prompts pays
        ONE full prefill plus N-1 suffixes, not N full prefills.

        Admission is ATOMIC against non-poisoning failures: when a prefill
        dispatch dies without consuming shared engine state, every slot this
        call already admitted is cancelled before the exception re-raises, so
        the caller can attribute the failure per-request by re-admitting one
        at a time (the batcher does exactly this). A failure that consumed
        donated engine state escalates to a full engine failure instead —
        salvage captured, device state rebuilt in place (see :meth:`rebuild`).
        """
        self._ensure_usable()
        if self._faults is not None:
            self._faults.begin_admit()
        failures_before = self.failure_count
        self._admitting = []
        try:
            return self._admit_many_inner(requests)
        except Exception:
            if self.failure_count == failures_before:
                if self._device_poisoned:
                    # a donating dispatch died mid-admission: the shared
                    # engine state is consumed, so this is a full failure
                    self._on_failure()
                else:
                    # clean unwind: the engine (and every other request) is
                    # intact — only this call's own admissions roll back
                    for slot in list(self._admitting):
                        self.cancel(slot)
                    if self.paged:
                        # blocks granted to slots that never reached _activate
                        # (a sibling's dispatch died mid-batch): sweep them
                        for slot in list(self._slot_block_map):
                            if not (self._active[slot] or self._reserved[slot]):
                                self._free_slot_blocks(slot)
            raise
        finally:
            self._admitting = None
            if self._faults is not None:
                self._faults.end_admit()

    def _admit_many_inner(self, requests: Sequence[Tuple]) -> List[int]:
        normalized = []
        for req in requests:
            prompt_ids, budget = req[0], req[1]
            sampling = dict(req[2]) if len(req) > 2 and req[2] else {}
            normalized.append(self.validate_request(prompt_ids, budget, **sampling))
        free = self.free_slots
        if len(normalized) > len(free) and self._inflight is not None:
            # the in-flight pipelined step may hold retirements the host has not
            # replayed yet: fetch it before refusing, so admission is exactly as
            # responsive as an unpipelined engine (the events reach the caller
            # through the next step())
            self._pending_events.extend(self._fetch_inflight())
            free = self.free_slots
        if len(normalized) > len(free):
            raise RuntimeError("no free decode slots")
        slots = [free[i] for i in range(len(normalized))]

        groups: Dict[int, List[int]] = {}
        deferred: List[int] = []
        sibling_prefixes: set = set()
        for i, norm in enumerate(normalized):
            prompt = norm[0]
            if self.prefix_cache is not None:
                if self._defer_for_sibling(prompt, sibling_prefixes):
                    deferred.append(i)
                    continue
                self._note_prefixes(prompt, sibling_prefixes)
            self._admit_one(slots[i], norm, groups)
        self._flush_groups(groups, normalized, slots)
        if deferred:
            # the siblings' blocks are indexed now: deferred requests re-match
            # and admit as hits (or fall back cleanly if the pool filled up)
            groups = {}
            for i in deferred:
                self._admit_one(slots[i], normalized[i], groups)
            self._flush_groups(groups, normalized, slots)
        return slots

    def _admit_one(self, slot: int, norm: Tuple, groups: Dict[int, List[int]]) -> None:
        """Route one validated request: chunked prefill, one-shot prefix-cache
        hit, or the batched bucket path (queued in ``groups`` for
        :meth:`_flush_groups`). Prefix matching happens here so the chunked and
        one-shot paths both see the restored-prefix length."""
        prompt, budget, temp, top_k, top_p = norm
        path, matched = self._match_prefix(prompt)
        if self._start_chunked(slot, prompt, budget, temp, top_k, top_p, path, matched):
            return
        if matched and self._admit_with_prefix(
            slot, prompt, budget, temp, top_k, top_p, path, matched
        ):
            return
        groups.setdefault(self.bucket_for(prompt.size), []).append(slot)

    def _flush_groups(
        self, groups: Dict[int, List[int]], normalized: Sequence[Tuple], slots: Sequence[int]
    ) -> None:
        """Run the batched bucket prefills: per bucket, up to ``prefill_batch``
        rows per device dispatch, then one scatter into the slot cache rows."""
        slot_to_norm = {slot: norm for slot, norm in zip(slots, normalized)}
        for bucket, idxs in groups.items():
            for start in range(0, len(idxs), self.prefill_batch):
                chunk = idxs[start : start + self.prefill_batch]
                rows = len(chunk)
                padded = np.zeros((rows, bucket), dtype=np.int32)
                lengths = np.zeros((rows,), dtype=np.int32)
                for r, slot in enumerate(chunk):
                    prompt = slot_to_norm[slot][0]
                    padded[r, : prompt.size] = prompt
                    lengths[r] = prompt.size
                if self.paged:
                    # block admission: each slot's table row maps exactly its
                    # lifetime demand; bucket padding past the allocation lands
                    # on the row's scratch tail inside the paged insert
                    for slot in chunk:
                        norm = slot_to_norm[slot]
                        private = self._alloc_slot_blocks(
                            slot, 0, self.block_demand(norm[0].size, norm[1])
                        )
                        self._write_slot_row(slot, private)
                if self._faults is not None:
                    self._faults.check_prefill()
                local_cache, local_logits = self._prefill_fn(
                    self._variables, jnp.asarray(padded), jnp.asarray(lengths)
                )
                self._insert_into_slots(
                    local_cache, local_logits,
                    jnp.asarray(chunk, dtype=jnp.int32),
                    jnp.asarray(lengths),
                )
                self.prefill_dispatches += 1
                for r, slot in enumerate(chunk):
                    prompt, budget, temp, top_k, top_p = slot_to_norm[slot]
                    self._activate(slot, int(lengths[r]), budget, temp, top_k, top_p)
                    self.prefill_tokens_computed += int(prompt.size)
                    self._index_prompt(slot, prompt)
                    if self._telemetry is not None:
                        self._telemetry.prefill_tokens_total.inc(float(prompt.size))
                        self._note_span(
                            slot, "prefill",
                            tokens=int(prompt.size), bucket=int(bucket), batch_rows=rows,
                        )

    def _defer_for_sibling(self, prompt: np.ndarray, sibling_prefixes: set) -> bool:
        """True when an earlier request in THIS admit_many call is about to
        index a longer block-prefix of ``prompt`` than the tree matches today —
        deferring lets this request restore that KV instead of recomputing it."""
        block = self._prefix_block_size
        max_blocks = (int(prompt.size) - 1) // block
        for k in range(max_blocks, 0, -1):
            if tuple(int(t) for t in prompt[: k * block]) in sibling_prefixes:
                return k > self.prefix_cache.probe(prompt, max_blocks)
        return False

    def _note_prefixes(self, prompt: np.ndarray, sibling_prefixes: set) -> None:
        """Record every block-prefix this request will index once it prefills
        (its full blocks), for :meth:`_defer_for_sibling` checks that follow."""
        block = self._prefix_block_size
        for k in range(1, int(prompt.size) // block + 1):
            sibling_prefixes.add(tuple(int(t) for t in prompt[: k * block]))

    # -------------------------------------------------------------- prefix cache

    def _match_prefix(self, prompt: np.ndarray) -> Tuple[List[Any], int]:
        """Longest cached full-block prefix of ``prompt``; ``([], 0)`` when the
        cache is disabled or nothing matches. Matching is capped one token short
        of the prompt: at least one real token must run prefill to produce the
        ``last_logits`` that seed decoding. The returned node path is
        reference-held until the slot retires (or admission declines the hit).
        """
        if self.prefix_cache is None:
            return [], 0
        max_blocks = (int(prompt.size) - 1) // self._prefix_block_size
        if max_blocks <= 0:
            return [], 0
        path = self.prefix_cache.match(prompt, max_blocks)
        return path, len(path) * self._prefix_block_size

    def _admit_with_prefix(
        self, slot: int, prompt: np.ndarray, budget: int,
        temp: float, top_k: int, top_p: float, path: List[Any], matched: int,
    ) -> bool:
        """One-shot admission of a prefix-cache hit: restore the matched blocks
        into a batch-1 local cache (shard-local gather), prefill ONLY the
        uncovered suffix over it (bucket-padded, the chunk program), insert into
        the slot. The match shrinks block-by-block if the suffix bucket would
        overflow the slot's cache rows; returns False (path fully released) when
        nothing survives, and the caller falls back to the batched bucket path.
        """
        block = self._prefix_block_size
        while matched:
            try:
                if matched + self.bucket_for(prompt.size - matched) <= self.max_len:
                    break
            except ValueError:
                # the suffix outgrew the bucket ladder while shrinking: this
                # prompt is only admissible through its cached prefix, so the
                # hit path cannot proceed — release and fall back (the caller
                # raises a clean oversized-prompt error)
                self.prefix_cache.release(path)
                path.clear()
                return False
            self.prefix_cache.release([path.pop()])
            matched -= block
        if not matched:
            return False
        suffix_len = int(prompt.size) - matched
        bucket = self.bucket_for(suffix_len)
        ids = np.zeros((1, bucket), dtype=np.int32)
        ids[0, :suffix_len] = prompt[matched:]
        if self.paged:
            # COPY-FREE restore: the matched blocks are already pool-resident,
            # so the hit just splices their ids into the slot's table row and
            # runs the suffix prefill over the gathered row — no copy-out
            # dispatch at all (the restore counter still ticks: it now counts
            # logical restores, and stays comparable with the dense engine)
            try:
                private = self._alloc_slot_blocks(
                    slot, len(path), self.block_demand(prompt.size, budget) - len(path)
                )
                self._write_slot_row(slot, [node.block_id for node in path] + private)
                self.prefix_restore_dispatches += 1
                if self._faults is not None:
                    self._faults.check_prefill()
                logits = self._run_paged_chunk(ids, slot, matched)
                self.prefill_dispatches += 1
                self.prefill_tokens_computed += suffix_len
                last = self._pick_last_fn(logits, jax.device_put(np.int32(suffix_len - 1)))
                self._seal_slot(slot, int(prompt.size), last)
            except Exception:
                # release the matched-path references AND the private grant
                # (a poisoning failure clears the allocator wholesale anyway;
                # a clean one — pool_exhausted, injected prefill — must not
                # strand either resource)
                self.prefix_cache.release(path)
                path.clear()
                self._free_slot_blocks(slot)
                raise
        else:
            pad_len = matched + bucket  # exact: the suffix write never clamps
            # hit-admission uploads are EXPLICIT device_puts: this is one of the
            # two hot entry points the transfer-guard regression drives under
            # disallow-implicit, so every host array states its transfer
            block_ids = jax.device_put(
                np.asarray([node.block_id for node in path], dtype=np.int32)
            )
            local_cache = self._restore_fn(self._pool, block_ids, pad_len)
            self.prefix_restore_dispatches += 1
            try:
                if self._faults is not None:
                    self._faults.check_prefill()
                logits, local_cache = self._chunk_fn(
                    self._variables, jax.device_put(ids), local_cache,
                    jax.device_put(np.int32(matched)),
                )
                self.prefill_dispatches += 1
                self.prefill_tokens_computed += suffix_len
                last = self._pick_last_fn(logits, jax.device_put(np.int32(suffix_len - 1)))
                self._insert_into_slots(
                    local_cache, last,
                    jax.device_put(np.asarray([slot], dtype=np.int32)),
                    jax.device_put(np.asarray([prompt.size], dtype=np.int32)),
                )
            except Exception:
                # whatever died, this request's matched-path references must not
                # leak with it (the blocks stay indexed for future hits)
                self.prefix_cache.release(path)
                path.clear()
                raise
        self.prefix_cache.record_hit(matched)
        self._activate(slot, int(prompt.size), budget, temp, top_k, top_p)
        self._slot_path[slot] = path
        self._index_prompt(slot, prompt)
        if self._telemetry is not None:
            self._telemetry.prefill_tokens_total.inc(float(suffix_len))
            self._note_span(slot, "prefix_hit", matched_tokens=matched, blocks=len(path))
            self._note_span(slot, "prefill", tokens=suffix_len, restored=matched)
        return True

    def _run_paged_chunk(self, ids: np.ndarray, slot: int, position: int) -> Any:
        """Dispatch one batch-1 prefill chunk straight into ``slot``'s pool
        blocks (``_paged_chunk_fn``). The pool is DONATED: a dispatch failure
        consumed the only KV storage, so it poisons the device state — unlike
        the dense chunked path, a paged chunk death always escalates."""
        try:
            logits, self._pool = self._paged_chunk_fn(
                self._variables, jax.device_put(ids), self._pool, self._tables,
                *jax.device_put((np.int32(slot), np.int32(position))),
            )
        except Exception:
            self._device_poisoned = True
            raise
        return logits

    def _seal_slot(self, slot: int, length: int, last: Any) -> None:
        """Point-update one table-resident prefill's length + sampling logits
        (``_finish_slot_fn`` donates both vectors — failure poisons them)."""
        try:
            self._lens, self._last_logits = self._finish_slot_fn(
                self._lens, self._last_logits,
                *jax.device_put((np.int32(slot), np.int32(length))), last,
            )
        except Exception:
            self._device_poisoned = True
            raise

    def _index_prompt(self, slot: int, prompt: np.ndarray) -> None:
        """Start the slot's token transcript and (cache on) index the prompt's
        KV into the pool. Runs AFTER :meth:`_activate`, on every admission path.

        The transcript serves generated-KV capture at retirement
        (``prefix_cache_generated``), preempt-to-prefix-cache checkpointing,
        AND failure salvage — the last works without the cache, so the
        transcript is kept unconditionally (host ints: cost is trivial)."""
        self._slot_tokens[slot] = [int(t) for t in prompt]
        if self.prefix_cache is None:
            return
        self._extend_index(slot, prompt)

    def _extend_index(self, slot: int, tokens: np.ndarray) -> None:
        """Extend the slot's held radix path over ``tokens``' full blocks and
        device-copy KV for the NEW blocks out of the slot's cache rows.

        Caching failures never kill the request: an exhausted pool (every
        block referenced — or injected) simply indexes nothing new, and a
        failed block save (which donates, i.e. poisons, only the POOL) rebuilds
        the pool in place and forgets every cached prefix — the slot cache,
        and therefore the request, are untouched either way."""
        path = self._slot_path.pop(slot, [])
        if self._faults is not None and self._faults.pool_exhausted():
            # injected exhaustion: behave exactly like extend() against a
            # fully-referenced pool — keep what is held, index nothing new
            self._faults.note_observed("pool_exhausted")
            if path:
                self._slot_path[slot] = path
            return
        if self.paged:
            # ADOPTION, not a copy: the slot's own blocks already hold exactly
            # the KV the tree wants, so indexing moves ownership slot → tree
            # for each full block the tree lacks — zero device work. Where a
            # sibling indexed the same block first, the existing node wins and
            # the slot keeps (and later frees) its identical duplicate.
            # ownership moves kv-block slot → radix tree via block_map pops
            full, adopted = self.prefix_cache.adopt(
                path, tokens, int(tokens.size) // self._prefix_block_size,
                self._slot_block_map.setdefault(slot, {}),
            )
            if adopted:
                # one logical save per adoption event: keeps the counter
                # comparable with the dense engine's per-retirement save
                self.prefix_save_dispatches += 1
                if self._telemetry is not None:
                    self._note_pool_gauges()
            if full:
                self._slot_path[slot] = full
            return
        # graftlint: disable=resource-leak -- the pool-rebuild return path drops 'full' deliberately: _rebuild_pool() forgets every cached prefix, so the refs die with the rebuilt cache
        full, new = self.prefix_cache.extend(
            path, tokens, int(tokens.size) // self._prefix_block_size
        )
        if new:
            start = len(full) - len(new)  # new nodes are always the path's tail
            # explicit uploads: block saves run at retirement, INSIDE the
            # steady-state step path the transfer guard disallows implicits on
            dst = jax.device_put(np.asarray([node.block_id for node in new], dtype=np.int32))
            try:
                self._pool = self._save_fn(
                    self._pool, self._cache, jax.device_put(np.int32(slot)),
                    jax.device_put(np.int32(start)), dst, self._prefix_block_size,
                )
            except Exception as exc:
                logger.warning(
                    "prefix-cache block save failed (%s); rebuilding the pool in place", exc
                )
                self._rebuild_pool()
                return
            self.prefix_save_dispatches += 1
        if full:
            self._slot_path[slot] = full

    def _rebuild_pool(self) -> None:
        """Reallocate the (poisoned or reset) KV block pool and forget every
        cached prefix. Held node paths — other slots', pinned checkpoints' —
        now reference orphaned nodes; their later release/unpin calls mutate
        those orphans harmlessly, and re-admissions simply re-index."""
        from unionml_tpu.models.gpt import init_block_pool

        self.prefix_cache.clear()
        self._slot_path.clear()
        self._pool = init_block_pool(
            self._config, self.prefix_cache.num_blocks, self._prefix_block_size
        )
        if self._mesh is not None:
            self._pool = jax.device_put(self._pool, self._cache_sharding)

    def _capture_generated(self, slot: int) -> None:
        """At retirement (``prefix_cache_generated``): index the slot's FULL
        token transcript — prompt plus every decoded token, eos included — so a
        multi-turn follow-up hits the whole previous turn. Cache columns map
        1:1 to transcript positions; the valid count is the slot's length."""
        tokens = self._slot_tokens.get(slot)
        if not tokens:
            return
        valid = int(self._lens_host[slot])
        self._extend_index(slot, np.asarray(tokens[:valid], dtype=np.int32))

    def _release_prefix(self, slot: int) -> None:
        """Drop the slot's references into the radix tree (retirement/cancel)."""
        path = self._slot_path.pop(slot, None)
        if path and self.prefix_cache is not None:
            self.prefix_cache.release(path)
        self._slot_tokens.pop(slot, None)

    # ------------------------------------------------------------- chunked prefill

    def _start_chunked(self, slot: int, prompt: np.ndarray, budget: int,
                       temp: float, top_k: int, top_p: float,
                       path: Sequence[Any] = (), matched: int = 0) -> bool:
        """Reserve ``slot`` for a chunked prefill when the prompt qualifies.

        Qualifies when ``prefill_chunk`` is configured, the UNCOVERED part of
        the prompt (``matched`` tokens restore from the prefix cache) is longer
        than one chunk, and the padded length still fits the slot's cache rows
        (otherwise the one-shot hit / bucketed batch paths handle it). With a
        hit, the local cache starts as the restored prefix and chunking resumes
        at ``consumed = matched``; the pad length anchors at ``matched`` so the
        final chunk's cache write never clamps."""
        chunk = self.prefill_chunk
        if chunk is None or prompt.size - matched <= chunk:
            return False
        padded_len = matched + -(-(prompt.size - matched) // chunk) * chunk
        if padded_len > self.max_len:
            return False
        if self.paged:
            # no local workspace at all: allocate the slot's lifetime blocks,
            # splice any matched prefix straight into the row, and let every
            # chunk write through the table (``_run_paged_chunk``)
            try:
                private = self._alloc_slot_blocks(
                    slot, len(path), self.block_demand(prompt.size, budget) - len(path)
                )
                self._write_slot_row(slot, [node.block_id for node in path] + private)
            except Exception:
                if path:
                    self.prefix_cache.release(list(path))
                self._free_slot_blocks(slot)
                raise
            local_cache = None
            if matched:
                self.prefix_restore_dispatches += 1  # copy-free splice
                self.prefix_cache.record_hit(matched)
                self._slot_path[slot] = list(path)
                if self._telemetry is not None:
                    self._note_span(slot, "prefix_hit", matched_tokens=matched, blocks=len(path))
        elif matched:
            block_ids = jnp.asarray([node.block_id for node in path], dtype=jnp.int32)
            local_cache = self._restore_fn(self._pool, block_ids, padded_len)
            self.prefix_restore_dispatches += 1
            self.prefix_cache.record_hit(matched)
            self._slot_path[slot] = list(path)
            if self._telemetry is not None:
                self._note_span(slot, "prefix_hit", matched_tokens=matched, blocks=len(path))
        else:
            from unionml_tpu.models.gpt import init_cache

            local_cache = init_cache(self._config, 1, padded_len)
            if self._mesh is not None:
                local_cache = jax.device_put(local_cache, self._cache_sharding)
        self._reserved[slot] = True
        if self._admitting is not None:
            self._admitting.append(slot)
        self._partials[slot] = {
            "prompt": prompt, "consumed": matched, "cache": local_cache,
            "budget": budget, "temp": temp, "top_k": top_k, "top_p": top_p,
        }
        return True

    def _advance_partials(self) -> None:  # graftlint: off-path (admission work, not steady-state decode)
        """Run ONE chunk of every in-progress chunked prefill (called per tick,
        between decode dispatches); completed prefills insert + activate.

        A failure in a slot's OWN chunk dispatch (the chunk program donates
        only that slot's local cache) kills only that request — the partial
        is dropped and a structured ``prefill_failed`` event reaches its
        consumer — while every other slot keeps prefilling and decoding. Only
        the slot-insert dispatch (which donates the shared engine cache) can
        escalate to a whole-engine failure."""
        for slot in list(self._partials):
            state = self._partials[slot]
            prompt, consumed = state["prompt"], state["consumed"]
            chunk = self.prefill_chunk
            take = min(chunk, prompt.size - consumed)
            ids = np.zeros((1, chunk), dtype=np.int32)
            ids[0, :take] = prompt[consumed : consumed + take]
            try:
                if self._faults is not None:
                    self._faults.check_prefill()
                if self.paged:
                    logits = self._run_paged_chunk(ids, slot, int(consumed))
                else:
                    logits, state["cache"] = self._chunk_fn(
                        self._variables, jnp.asarray(ids), state["cache"],
                        jnp.asarray(consumed, dtype=jnp.int32),
                    )
            except Exception as exc:  # this slot's local dispatch: fail it alone
                if self._device_poisoned:
                    # paged chunks donate the POOL — the only KV storage — so
                    # a REAL dispatch death cannot be contained to this slot;
                    # injected prefill faults raise pre-dispatch (above) and
                    # keep the per-slot isolation contract
                    raise
                rid = self._slot_rid.get(slot)
                logger.warning(
                    "chunked prefill failed for slot %d: %s%s",
                    slot, exc, f" (request_id={rid})" if rid is not None else "",
                )
                self._fail_partial(slot)
                continue
            self.prefill_dispatches += 1
            self.prefill_tokens_computed += int(take)
            state["consumed"] = consumed + take
            if self._telemetry is not None:
                self._telemetry.prefill_tokens_total.inc(float(take))
                self._note_span(
                    slot, "prefill_chunk",
                    tokens=int(take), consumed=int(state["consumed"]), total=int(prompt.size),
                )
            if state["consumed"] < prompt.size:
                continue
            # final chunk: logits at the prompt's last REAL token seed decoding
            last = self._pick_last_fn(
                logits, jax.device_put(np.int32(prompt.size - 1 - consumed))
            )
            if self.paged:
                # the KV is already pool-resident behind the slot's row: only
                # the length + sampling logits need the point-update
                self._seal_slot(slot, int(prompt.size), last)
            else:
                self._insert_into_slots(
                    state["cache"], last,
                    jnp.asarray([slot], dtype=jnp.int32),
                    jnp.asarray([prompt.size], dtype=jnp.int32),
                )
            del self._partials[slot]
            self._activate(
                slot, prompt.size, state["budget"], state["temp"], state["top_k"], state["top_p"]
            )
            self._index_prompt(slot, prompt)

    def _fail_partial(self, slot: int) -> None:
        """Drop one in-progress chunked prefill whose own dispatch died: free
        the slot, release its restored-prefix references, and buffer the
        structured failure event for its consumer."""
        self._partials.pop(slot, None)
        self._reserved[slot] = False
        self._slot_queue_wait.pop(slot, None)
        self._release_prefix(slot)
        if self.paged:
            self._free_slot_blocks(slot)
        if self._telemetry is not None:
            self._drop_rid(slot)
        self._pending_events.append(
            StepEvent(slot=slot, token=-1, emit=False, finished=True, error="prefill_failed")
        )

    def _insert_into_slots(self, local_cache: Any, local_logits: Any, slots: Any, lengths: Any) -> None:
        """Run the donating slot-insert dispatch (paged: scatter the bucket
        workspace through the admitted rows' block tables into the pool). A
        failure here has CONSUMED the shared engine KV/lens/logits, so it marks
        the device state poisoned — the public entry point escalates to a full
        engine failure instead of pretending the batch survived."""
        try:
            if self.paged:
                self._pool, self._lens, self._last_logits = self._paged_insert_fn(
                    self._pool, self._tables, self._lens, self._last_logits,
                    local_cache, local_logits, slots, lengths,
                )
            else:
                self._cache, self._lens, self._last_logits = self._insert_fn(
                    self._cache, self._lens, self._last_logits, local_cache, local_logits,
                    slots, lengths,
                )
        except Exception:
            self._device_poisoned = True
            raise

    def reset(self) -> None:  # graftlint: off-path (error recovery, not steady-state decode)
        """Reallocate device state and clear all slots.

        Required after a failed :meth:`step`: the step donates the cache/logits
        buffers, so a deferred device error (surfacing at the token fetch, after
        the state variables were already reassigned) leaves them poisoned and out
        of sync with the host mirrors. In-flight requests are abandoned.
        """
        # the key is also a step output, so it is poisoned too; a fresh
        # reset-counted key keeps sampled streams from repeating the pre-crash run
        self._resets += 1
        self._key_steps = 0
        self.discard_salvage()
        self._failed = False
        self._device_poisoned = False
        # a dispatched-but-unfetched step is poisoned with the rest of the
        # device state: DISCARD it (never fetch), and drop its replayed events
        self._pending_events.clear()
        self._init_device_state()
        self._active[:] = False
        self._reserved[:] = False
        self._partials.clear()
        self._lens_host[:] = 0
        self._remaining[:] = 0
        self._slot_queue_wait.clear()
        self._slot_rid.clear()
        self._slot_pending_spans.clear()
        self._slot_temp[:] = self.temperature
        self._slot_top_k[:] = 0
        self._slot_top_p[:] = 1.0
        self._sync_sampling_mirrors()
        if self.paged:
            # the pool was reallocated above (_init_device_state): every block
            # returns to the free list and the radix index forgets everything,
            # held paths and pins included
            self._allocator.clear()
            self._slot_block_map.clear()
            self._slot_path.clear()
        elif self.prefix_cache is not None:
            # a full reset forgets every cached prefix too: the caller is
            # abandoning everything, held paths included
            self._rebuild_pool()
        self._slot_tokens.clear()

    # ------------------------------------------------------ failure & recovery

    @property
    def busy(self) -> bool:
        """Whether live requests should be making progress — the supervisor's
        watchdog only treats a stale heartbeat as a stall while this is True.
        Keyed on host-visible work (active slots, chunked prefills), NOT on
        ``_inflight``: a trailing dispatched-but-unfetched masked step idles
        harmlessly after the last slot retires and must not read as a stall."""
        return bool(self._active.any()) or bool(self._partials)

    @property
    def failed(self) -> bool:
        """True while an in-place rebuild has failed and not yet been retried
        successfully — the engine refuses work (the supervisor retries
        :meth:`rebuild` with backoff; unsupervised callers retry lazily)."""
        return self._failed

    def _ensure_usable(self) -> None:
        if self._failed:
            # unsupervised auto-recovery: retry the rebuild fresh-keyed (no
            # resume — whoever could have collected the salvage never did)
            self.rebuild(resume=False)

    def note_external_failure(self) -> None:
        """Escalate a poisoning failure raised from an out-of-band engine call
        (``cancel``/``preempt`` point-updates): the owner calls this from its
        catch-all so donated-state loss is never papered over. Idempotent —
        a failure already handled by the entry-point wrappers is a no-op."""
        if self._device_poisoned:
            self._on_failure()

    def _on_failure(self) -> None:  # graftlint: off-path (error recovery, not steady-state decode)
        """A device-side failure consumed donated engine state: capture every
        salvageable slot (host transcripts plus already-indexed radix paths,
        PINNED against eviction), then rebuild the device state in place with
        PRNG-stream continuity. The engine is immediately usable again; a
        supervising batcher collects :meth:`take_salvage` and re-queues the
        requests so they resume token-identically, paying only the prefill of
        whatever their pinned prefix does not cover. If the rebuild itself
        fails, the engine marks itself failed for the supervisor's
        bounded-backoff retry loop."""
        self.failure_count += 1
        self._device_poisoned = False
        # the in-flight step is poisoned with the rest: never fetch it (its
        # steps re-decode after the resume, consuming the same key stream)
        self._inflight = None
        self._inflight_skip = set()
        self._pending_events.clear()
        self._capture_salvage()
        try:
            self.rebuild(resume=True)
        except Exception:
            self._failed = True
            logger.exception("in-place engine rebuild failed; engine marked failed")

    def _capture_salvage(self) -> None:
        """Snapshot every active/reserved slot's resumable state — HOST data
        only (the device may be poisoned): the replayed transcript, the
        unspent budget, and (dense engines) whatever radix path the slot
        already held, pinned so the blocks survive the rebuild and LRU until
        the resume. PAGED engines salvage transcripts only: the pool itself
        rides the failed step's donation, so no block outlives the rebuild."""
        self.discard_salvage()  # a prior incident's uncollected records
        if self.paged:
            # return every slot-owned block NOW (host-side accounting): the
            # rebuild also clears the allocator, but if the rebuild itself
            # fails the engine must still not report leaked slot blocks
            for blk_slot in list(self._slot_block_map):
                self._free_slot_blocks(blk_slot)
        records: List[SalvagedSlot] = []
        for slot in np.flatnonzero(self._active | self._reserved):
            slot = int(slot)
            if self._reserved[slot]:
                # chunked prefill in progress: nothing delivered yet — the
                # resume is simply the original prompt at full budget
                part = self._partials.get(slot)
                tokens = [int(t) for t in part["prompt"]] if part else []
                remaining = int(part["budget"]) if part else 0
            else:
                transcript = self._slot_tokens.get(slot) or []
                valid = int(self._lens_host[slot])
                tokens = [int(t) for t in transcript[:valid]]
                remaining = int(self._remaining[slot])
            path = self._slot_path.pop(slot, [])
            if self.paged:
                # the failed step consumed the POOL — the only KV storage — so
                # no block survives the rebuild: paged salvage is TRANSCRIPT-
                # only (release the refs; the rebuild clears the tree anyway)
                # and the resume pays a full re-prefill instead of a suffix
                if path and self.prefix_cache is not None:
                    self.prefix_cache.release(path)
                path = []
            elif path and self.prefix_cache is not None and tokens and remaining > 0:
                self.prefix_cache.pin(path)
                self.prefix_cache.release(path)  # the slot's own working refs
            else:
                if path and self.prefix_cache is not None:
                    self.prefix_cache.release(path)
                path = []
            if not tokens or remaining <= 0:
                continue  # nothing to resume from
            records.append(
                SalvagedSlot(slot=slot, tokens=tokens, path=path, remaining=remaining)
            )
        self._salvage = records

    # transfers: kv-pin
    def take_salvage(self) -> List[SalvagedSlot]:
        """Collect (and clear) the salvage captured by the last failure. The
        caller owns the records' eviction pins from here on — drop each via
        :meth:`release_preempted` once its resume re-admitted or its request
        was abandoned."""
        salvage, self._salvage = self._salvage, []
        return salvage

    # owns: kv-pin
    def discard_salvage(self) -> None:
        """Unpin and drop uncollected salvage (reset/abort/unsupervised paths)."""
        for rec in self._salvage:
            if rec.path and self.prefix_cache is not None:
                self.prefix_cache.unpin(rec.path)
        self._salvage = []

    def rebuild(self, *, resume: bool = True) -> None:  # graftlint: off-path (error recovery, not steady-state decode)
        """Reallocate the engine's device state from host-retained params.

        On DENSE engines — unlike :meth:`reset` — the prefix-cache pool and
        radix index SURVIVE (block saves donate only the pool, and their
        failures rebuild it locally — see ``_extend_index``), so salvaged
        requests re-admit through the ordinary prefix-hit path and pay only a
        suffix prefill. On PAGED engines the pool IS the decode state and rode
        the failed step's donation, so the rebuild restarts the allocator and
        index empty and salvaged requests re-prefill in full.

        ``resume=True`` (supervised recovery) reconstructs the PRNG key by
        replaying the recorded number of key-consuming steps from the seeded
        base, so resumed SAMPLED streams continue token-identically to a
        fault-free run. ``resume=False`` (standalone auto-recovery; in-flight
        work abandoned) reseeds like :meth:`reset` and drops uncollected
        salvage.

        Raises when the rebuild itself fails (a real allocation error, or an
        injected ``FaultPlan.rebuild_failures``): the engine stays failed and
        the supervisor retries with bounded exponential backoff.
        """
        if self._faults is not None:
            self._faults.check_rebuild()
        if not resume:
            self._resets += 1
            self._key_steps = 0
            self.discard_salvage()
        self._pending_events.clear()
        self._active[:] = False
        self._reserved[:] = False
        self._partials.clear()
        self._lens_host[:] = 0
        self._remaining[:] = 0
        self._slot_queue_wait.clear()
        self._slot_rid.clear()
        self._slot_pending_spans.clear()
        self._slot_temp[:] = self.temperature
        self._slot_top_k[:] = 0
        self._slot_top_p[:] = 1.0
        for slot in list(self._slot_path):
            self._release_prefix(slot)  # salvage holds its own pins by now
        self._slot_tokens.clear()
        self._init_device_state()
        if self.paged:
            # the failed step consumed the pool itself; the reallocation above
            # emptied it, so the allocator and radix index restart from scratch
            # (salvage is transcript-only in paged mode for exactly this reason)
            self._allocator.clear()
            self._slot_block_map.clear()
        self._sync_sampling_mirrors()
        if resume and self._key_steps:
            # replay the consumed key advances (one split per any-active step)
            # so the stream continues exactly where the failed burst cut it
            key = self._key
            for _ in range(self._key_steps):
                key = jax.random.split(key)[0]
            if self._mesh is not None:
                key = jax.device_put(key, self._replicated)
            self._key = key
        self._device_poisoned = False
        self._failed = False
        self.rebuilds += 1

    def _apply_token(self, slot: int, token: int) -> StepEvent:
        """Advance the host mirrors for one decoded token (same rules as the
        device applies in-program — :func:`~unionml_tpu.models.gpt.advance_slot_state` —
        so host and device views re-converge at every fetch)."""
        self.tokens_decoded += 1
        self._remaining[slot] -= 1
        self._lens_host[slot] = min(self._lens_host[slot] + 1, self.max_len - 1)
        tokens = self._slot_tokens.get(slot)
        if tokens is not None:  # generated-KV capture: eos included, emit or not
            tokens.append(int(token))
        is_eos = self.eos_token_id is not None and token == self.eos_token_id
        finished = (
            is_eos
            or self._remaining[slot] <= 0
            or self._lens_host[slot] >= self.max_len - 1
        )
        # the request's first decoded token carries its queue wait, so a
        # client-side TTFT decomposes into queue vs prefill+decode time
        queue_wait_ms = self._slot_queue_wait.pop(slot, None)
        if finished:
            self._active[slot] = False
            if self.prefix_cache is not None and self.prefix_cache_generated:
                self._capture_generated(slot)  # paged: adopts blocks in place
            self._release_prefix(slot)
            if self.paged:
                # whatever the index did not adopt (partial tail, unused
                # budget) goes back to the free list right now — safe even
                # with a burst in flight (see _slot_block_map's ordering note)
                self._free_slot_blocks(slot)
            if self._telemetry is not None:
                self._drop_rid(slot)
        return StepEvent(
            slot=slot, token=token, emit=not is_eos, finished=finished,
            queue_wait_ms=queue_wait_ms,
        )

    @property
    def has_pending_events(self) -> bool:
        """Events replayed by an out-of-band pipeline flush (cancel/admission),
        awaiting delivery through the next :meth:`step` — drive loops must keep
        ticking while any are queued."""
        return bool(self._pending_events)

    def take_pending_events(self) -> List[StepEvent]:
        """Drain the events buffered by an out-of-band pipeline flush.

        Callers that keep their own slot→request mapping MUST drain these
        right after :meth:`admit_many` and attribute them under the mapping
        that existed BEFORE the call: a flush inside admission can retire a
        slot's previous occupant, and the buffered events belong to it — not
        to whichever request the freed slot was just handed to. (The
        :class:`ContinuousBatcher` does exactly this before re-keying its
        sinks.) Events left undrained are delivered by the next :meth:`step`.
        """
        events, self._pending_events = self._pending_events, []
        return events

    def pipeline_stats(self) -> Dict[str, Any]:
        """Pipeline observability for ``GET /stats``: configured depth, whether a
        step is currently in flight, dispatch/idle counters, and the host-gap /
        fetch-block EMAs (ms)."""
        return {
            "depth": 1 if self.pipeline else 0,
            "inflight": self._inflight is not None,
            "step_dispatches": self.step_dispatches,
            "idle_dispatches": self.idle_dispatches,
            "ema_host_gap_ms": None
            if self.ema_host_gap_ms is None
            else round(self.ema_host_gap_ms, 3),
            "ema_fetch_block_ms": None
            if self.ema_fetch_block_ms is None
            else round(self.ema_fetch_block_ms, 3),
            "ema_queue_wait_ms": None
            if self.ema_queue_wait_ms is None
            else round(self.ema_queue_wait_ms, 3),
        }

    def robustness_stats(self) -> Dict[str, Any]:
        """Engine-side robustness counters for ``GET /stats`` (the supervisor
        merges its own health/recovery counters alongside these)."""
        stats: Dict[str, Any] = {
            "engine_failures": self.failure_count,
            "engine_rebuilds": self.rebuilds,
            "quarantined_requests": self.quarantined_requests,
            "salvage_pending": len(self._salvage),
        }
        if self._faults is not None:
            stats["faults"] = self._faults.stats()
        return stats

    def note_queue_wait(self, slot: int, wait_ms: Optional[float]) -> None:
        """Record how long ``slot``'s request sat queued before admission (the
        batcher calls this right after ``admit_many``). The value rides on the
        slot's first :class:`StepEvent` and feeds the queue-wait EMA that
        :meth:`pipeline_stats` (and ``GET /stats``) report.

        .. deprecated:: PR-11
            ``StepEvent.queue_wait_ms`` (populated only on the first token)
            is kept for compatibility; the telemetry trace's ``queue_wait``
            span is the one source of truth for TTFT decomposition.
        """
        if wait_ms is None:
            return
        self._slot_queue_wait[slot] = float(wait_ms)
        self.ema_queue_wait_ms = (
            float(wait_ms)
            if self.ema_queue_wait_ms is None
            else 0.8 * self.ema_queue_wait_ms + 0.2 * float(wait_ms)
        )

    def note_request_id(self, slot: int, request_id: Optional[str]) -> None:
        """Bind ``slot``'s occupant to its trace (batcher-set at registration,
        right after :meth:`note_queue_wait`); flushes any spans the admission
        path buffered for the slot before the id was known."""
        if self._telemetry is None or request_id is None:
            return
        self._slot_rid[slot] = request_id
        for kind, at, dur_ms, attrs in self._slot_pending_spans.pop(slot, ()):
            self._telemetry.span(request_id, kind, dur_ms=dur_ms, at=at, **attrs)

    def _note_span(self, slot: int, kind: str, dur_ms: Optional[float] = None, **attrs: Any) -> None:
        """Record a slot-keyed span, buffering when the request id is not yet
        bound (admission-time prefill spans precede batcher registration).
        Callers gate on ``self._telemetry is not None`` (zero-cost-off)."""
        rid = self._slot_rid.get(slot)
        if rid is not None:
            self._telemetry.span(rid, kind, dur_ms=dur_ms, **attrs)
        else:
            self._slot_pending_spans.setdefault(slot, []).append(
                (kind, time.perf_counter(), dur_ms, attrs)
            )

    def _drop_rid(self, slot: int) -> None:
        """Forget a retired slot's trace binding (the trace itself ends at the
        batcher, which owns terminal delivery)."""
        self._slot_rid.pop(slot, None)
        self._slot_pending_spans.pop(slot, None)

    def _fetch_inflight(self) -> List[StepEvent]:
        """Fetch the dispatched-but-unfetched step (no-op when none) and replay
        its tokens into the host mirrors under the slot mapping the step was
        dispatched with."""
        if self._inflight is None:
            return []
        burst, skip = self._inflight, self._inflight_skip
        self._inflight, self._inflight_skip = None, set()
        return self._replay_burst(burst, skip)

    def _replay_burst(
        self, burst: Tuple[Any, Any, Any, int], skip: frozenset = frozenset()
    ) -> List[StepEvent]:
        """Block on one dispatched burst's ``(tokens, masks, bads)`` and apply them.

        ONE fused ``device_get`` for tokens, masks, and the per-step NaN
        flags; a device failure surfacing here poisons the donated buffers,
        so it fails the engine exactly like a dispatch failure. A flagged
        ``(step, slot)`` quarantines THAT slot (its sampled token is garbage
        and never delivered) while every other slot's tokens apply normally."""
        tokens, masks, bads, _ = burst
        t0 = time.perf_counter()
        try:
            if self._faults is not None:
                stall_ms = self._faults.take_fetch_stall_ms()
                if stall_ms is not None:
                    time.sleep(stall_ms / 1e3)  # a wedged device queue, to the watchdog's eye
                self._faults.check_fetch()
            # graftlint: disable=host-sync -- the ONE designed sync per tick: tokens+masks+nan-flags fused into a single device_get (PR-3 pipelined-decode contract)
            tokens_host, masks_host, bads_host = map(
                np.asarray, jax.device_get((tokens, masks, bads))
            )
        except Exception:
            self._on_failure()
            raise
        done = time.perf_counter()
        self.last_heartbeat = time.monotonic()
        block_ms = (done - t0) * 1e3
        self.ema_fetch_block_ms = (
            block_ms
            if self.ema_fetch_block_ms is None
            else 0.8 * self.ema_fetch_block_ms + 0.2 * block_ms
        )
        self._last_fetch_done = done
        events: List[StepEvent] = []
        telemetry = self._telemetry
        emitted: Dict[Optional[str], int] = {}
        for i in range(tokens_host.shape[0]):
            if masks_host[i].any():
                # mirrors the in-program key gate (any(active) at step start):
                # lets a resume-rebuild replay the PRNG stream to this point
                self._key_steps += 1
            for slot in np.flatnonzero(masks_host[i]):
                slot = int(slot)
                if slot in skip:
                    # the slot was quarantined while this burst was in flight:
                    # its tokens here are garbage, and the slot may already
                    # belong to a new occupant — drop them unconditionally
                    continue
                if not self._active[slot]:
                    continue  # quarantined earlier in this burst: later steps are void
                if bads_host[i, slot]:
                    events.append(self._quarantine(slot))
                    continue
                rid = self._slot_rid.get(slot) if telemetry is not None else None
                event = self._apply_token(slot, int(tokens_host[i, slot]))
                events.append(event)
                if telemetry is not None and event.emit:
                    emitted[rid] = emitted.get(rid, 0) + 1
        if telemetry is not None and emitted:
            # per-burst decode timing piggybacks on the stamps this fetch took
            # anyway (t0/done/block_ms above): ZERO new host<->device syncs —
            # everything here reads the already-fetched host arrays
            telemetry.decode_fetch_ms.observe(block_ms)
            for rid, n in emitted.items():
                telemetry.decode_tokens(rid, n, at=done)
        return events

    def _quarantine(self, slot: int) -> StepEvent:
        """Terminate ONE slot whose logits went NaN/Inf: release it (without
        indexing its possibly-poisoned generated KV), point-update its device
        mirror inactive, and emit the structured failure event — siblings keep
        decoding, which is the whole point vs the old batch-wide failure."""
        self.quarantined_requests += 1
        self._active[slot] = False
        self._reserved[slot] = False
        self._remaining[slot] = 0
        self._slot_temp[slot] = self.temperature
        self._slot_top_k[slot] = 0
        self._slot_top_p[slot] = 1.0
        self._slot_queue_wait.pop(slot, None)
        self._release_prefix(slot)  # no generated-KV capture: it may be poisoned
        if self.paged:
            # NaN-poisoned block CONTENT is harmless once re-owned: the next
            # owner's prefill overwrites every position before reading it
            self._free_slot_blocks(slot)
        self._slot_device_update(slot, False, 0, self.temperature, 0, 1.0)
        if self._inflight is not None:
            # the already-dispatched next burst still decodes this slot under
            # an active mask: its replay must not credit those garbage tokens
            # to whoever occupies the slot by then
            self._inflight_skip.add(slot)
        if self._faults is not None:
            self._faults.note_observed("nan_logits")
        if self._telemetry is not None:
            self._note_span(slot, "quarantine", reason="nan_logits")
            self._telemetry.quarantines_total.inc()
        rid = self._slot_rid.get(slot)
        self._drop_rid(slot)
        logger.warning(
            "slot %d quarantined: non-finite logits%s",
            slot, f" (request_id={rid})" if rid is not None else "",
        )
        return StepEvent(slot=slot, token=-1, emit=False, finished=True, error="nan_logits")

    def _dispatch_step(self, lookahead: int) -> Tuple[Any, Any, Any, int]:
        """Dispatch ONE compiled decode burst; return ``(tokens, masks, bads,
        n_steps)`` of the in-flight result (device arrays, not yet fetched).

        The seam :meth:`step` drives and subclasses override: the speculative
        engine swaps in its round program here (returning ``n_steps`` = the
        round's burst rows) while every surrounding concern — fault paths,
        pipelining, accounting, replay — stays in :meth:`step` unchanged.
        Exceptions propagate to the caller's ``_on_failure`` path.
        """
        # the all-greedy program skips the sampling machinery; heterogeneous slots
        # share the sampling program with per-row controls. Everything the step
        # consumes — activity, budgets, sampling controls — rides as
        # device-resident mirrors (refreshed in _activate/cancel/reset), so a
        # steady-state tick performs ZERO host→device transfers (pinned by the
        # transfer-guard regression test).
        sampling = bool((self._slot_temp[self._active] > 0).any())
        fn = self._step_fns.get((lookahead, sampling))
        if fn is None:
            fn = self._step_fns[(lookahead, sampling)] = self._make_step(lookahead, sampling)
        if self._faults is not None:
            # injected dispatch failures take the SAME except path a real
            # device error takes (nothing below special-cases injection)
            self._faults.check_step_dispatch()
        if self.paged:
            # the pool rides the dispatch donated (argnums pin it); the
            # TABLES ride as a non-donated input — they only change at
            # admission, between dispatches, so the burst reads one
            # consistent map for its whole scan
            # graftlint: disable=use-after-donate -- paged _make_step donates argnums (1, 3): the pool and last_logits; self._tables at position 2 is a plain input (the dense maker's (1, 2) map does not apply to this call)
            (
                self._pool,
                self._last_logits,
                self._lens,
                self._active_dev,
                self._remaining_dev,
                self._key,
                tokens,
                masks,
                bads,
            ) = fn(
                self._variables, self._pool, self._tables, self._last_logits,
                self._lens, self._active_dev, self._remaining_dev, self._key,
                self._temp_dev, self._top_k_dev, self._top_p_dev,
            )
        else:
            (
                self._cache,
                self._last_logits,
                self._lens,
                self._active_dev,
                self._remaining_dev,
                self._key,
                tokens,
                masks,
                bads,
            ) = fn(
                self._variables, self._cache, self._last_logits, self._lens,
                self._active_dev, self._remaining_dev, self._key,
                self._temp_dev, self._top_k_dev, self._top_p_dev,
            )
        return tokens, masks, bads, lookahead

    def step(self, lookahead: int = 1) -> List[StepEvent]:  # graftlint: hot-path
        """Decode for every active slot; returns per-slot events.

        :param lookahead: number of decode steps fused into ONE device program and
            ONE host sync (``lax.scan``). The burst emits exactly what ``lookahead``
            sequential calls would — slot retirement (eos / budget / cache room)
            runs inside the scan — at 1/lookahead the host-sync overhead. The
            trade-off is token delivery latency: streamed tokens arrive in bursts.
            Clamped to the largest useful depth for the current slots; compiled
            once per distinct depth.

        With ``pipeline=True`` (the default) each call DISPATCHES the next
        step/burst *before* fetching the previous one's tokens: the device runs
        step N+1 while the host applies step N's tokens, admits requests, and
        fans out events — so events arrive one call later than the dispatch
        that produced them, and the device never idles on host scheduling.
        Retirement runs inside the compiled step either way, so pipelined and
        unpipelined engines emit identical streams (greedy and fixed-seed
        sampled) under identical call schedules.

        A device failure mid-step FAILS the engine (see :meth:`_on_failure`):
        salvage is captured for a supervising batcher, the device state is
        rebuilt in place from host-retained params, and the exception
        re-raises — the engine stays usable either way.
        """
        self._ensure_usable()
        events: List[StepEvent] = []
        if self._pending_events:
            # replayed by an out-of-band flush (cancel / contended admission):
            # deliver them FIRST — they predate anything this tick produces
            events.extend(self._pending_events)
            self._pending_events.clear()
        if self._partials:
            # chunked prefills advance one chunk per tick, between decode
            # dispatches, so long prompts never stall the in-flight batch;
            # per-slot chunk failures are absorbed inside (only a poisoning
            # slot-insert failure reaches this handler)
            try:
                self._advance_partials()
            except Exception:
                self._on_failure()
                raise
        if not self._active.any():
            return events
        lookahead = max(1, int(lookahead))
        # host-side accounting of the dispatched-but-unfetched burst: the host
        # mirrors lag it, so depth planning subtracts its steps
        inflight_steps = self._inflight[3] if self._inflight is not None else 0
        room = np.minimum(
            self._remaining[self._active],
            (self.max_len - 1) - self._lens_host[self._active],
        )
        # every active slot runs at least one more step (a slot admitted at the
        # cache-room boundary decodes once and force-finishes), hence the floor
        headroom = max(1, int(room.max())) - inflight_steps
        if headroom <= 0:
            # budget/cache-room retirement is deterministic: every slot the host
            # still thinks active retires within the in-flight burst. Fetch it
            # instead of dispatching a guaranteed-masked step.
            events.extend(self._fetch_inflight())
            return events
        if lookahead > 1:
            # no point scanning past the moment the last slot can retire — but a
            # clamp to the EXACT depth would compile a distinct scan program per
            # tail length, so round up to the next power of two: a bounded ladder
            # of programs (log2 K of them), at most `needed` wasted masked steps
            if headroom < lookahead:
                lookahead = min(lookahead, 1 << (headroom - 1).bit_length())
        # the all-greedy program skips the sampling machinery; heterogeneous slots
        # share the sampling program with per-row controls. Everything the step
        # consumes — activity, budgets, sampling controls — rides as
        # device-resident mirrors (refreshed in _activate/cancel/reset), so a
        # steady-state tick performs ZERO host→device transfers (pinned by the
        # transfer-guard regression test).
        t0 = time.perf_counter()
        device_was_idle = self._inflight is None
        try:
            tokens, masks, bads, lookahead = self._dispatch_step(lookahead)
        except Exception:
            self._on_failure()
            raise
        self.last_heartbeat = time.monotonic()
        if self._faults is not None:
            for bad_slot in self._faults.take_nan_slots():
                # poison the slot's NEXT sampling input: the following step's
                # in-program finiteness flag trips and the host quarantines it
                self._last_logits = self._last_logits.at[bad_slot].set(jnp.nan)
        self.step_dispatches += 1
        if device_was_idle and self._last_fetch_done is not None:
            self.idle_dispatches += 1
        if self._last_fetch_done is not None:
            # host gap = how long the device queue sat EMPTY before this
            # dispatch (0 when a step was still in flight — the pipelined case).
            # Clamped so a genuine idle wait for traffic cannot poison the EMA.
            gap_ms = (
                min((t0 - self._last_fetch_done) * 1e3, 250.0) if device_was_idle else 0.0
            )
            self.ema_host_gap_ms = (
                gap_ms
                if self.ema_host_gap_ms is None
                else 0.8 * self.ema_host_gap_ms + 0.2 * gap_ms
            )
        previous, prev_skip = self._inflight, self._inflight_skip
        self._inflight, self._inflight_skip = (tokens, masks, bads, lookahead), set()
        if previous is not None:
            # dispatch-ahead: the new step is already queued on the device
            # while the host blocks on (and then applies) the previous one
            events.extend(self._replay_burst(previous, prev_skip))
        if not self.pipeline:
            events.extend(self._fetch_inflight())  # hard sync (see utils.hard_sync)
        return events

    def abort_all(self) -> None:
        """Deactivate every slot (in-flight state is abandoned; cache reuse is safe).

        A dispatched-but-unfetched pipelined step is DISCARDED, not flushed:
        every request it could emit for is being abandoned, so fetching it
        would only manufacture events with no consumer. The device slot
        mirrors re-upload from the (now all-inactive) host arrays — legal
        precisely because the pipeline is empty.
        """
        self._inflight = None
        self._inflight_skip = set()
        self._pending_events.clear()
        self.discard_salvage()
        self._active[:] = False
        self._reserved[:] = False
        self._partials.clear()
        for slot in list(self._slot_path):
            self._release_prefix(slot)
        if self.paged:
            for slot in list(self._slot_block_map):
                self._free_slot_blocks(slot)
        self._slot_tokens.clear()
        self._slot_queue_wait.clear()
        self._slot_rid.clear()
        self._slot_pending_spans.clear()
        self._remaining[:] = 0
        self._sync_slot_mirrors()

    def cancel(self, slot: int) -> None:
        """Deactivate one slot (its request is abandoned; the slot is reusable).

        With a pipelined step in flight the engine FLUSHES it first: the step
        was dispatched while this slot (and its neighbors) were still live, so
        its tokens must be applied under the OLD slot mapping — deferring the
        fetch past a readmission would credit the stale token to the slot's
        next occupant. Survivors' flushed events are delivered by the next
        :meth:`step`; the cancelled slot's device mirror is then point-updated
        to inactive so the device stops decoding it.
        """
        self._ensure_usable()
        self._pending_events.extend(self._fetch_inflight())
        # the flush may have buffered this slot's own tokens: its consumer is
        # gone, and delivering them later could credit them to the slot's NEXT
        # occupant — drop them (survivors' events stay queued)
        self._pending_events = [ev for ev in self._pending_events if ev.slot != slot]
        self._active[slot] = False
        self._reserved[slot] = False
        self._remaining[slot] = 0
        self._slot_temp[slot] = self.temperature
        self._slot_top_k[slot] = 0
        self._slot_top_p[slot] = 1.0
        self._partials.pop(slot, None)
        self._slot_queue_wait.pop(slot, None)
        if self._telemetry is not None:
            self._drop_rid(slot)
        self._release_prefix(slot)
        if self.paged:
            self._free_slot_blocks(slot)  # pipeline flushed above: nothing reads them
        self._slot_device_update(slot, False, 0, self.temperature, 0, 1.0)

    # transfers: kv-pin
    def preempt(self, slot: int) -> Optional[PreemptedSlot]:  # graftlint: off-path (scheduler policy action, not steady-state decode)
        """Checkpoint a RUNNING slot into the prefix cache and free it.

        The preempt-to-prefix-cache primitive the SLO scheduler drives: the
        slot's full transcript (prompt + generated tokens) is indexed into the
        radix tree block-by-block — paged engines ADOPT the slot's own pool
        blocks in place (the checkpoint is pure ownership bookkeeping: no
        re-slicing, no device copy); dense engines device-copy KV only for
        blocks the tree does not already hold — and the resulting node path is
        PINNED against LRU eviction. The slot then deactivates exactly like :meth:`cancel`
        (pipeline flushed first, so the transcript and the delivered token
        stream agree), and the returned :class:`PreemptedSlot` lets the caller
        re-queue the request: re-admitting ``tokens`` as the prompt restores
        the pinned blocks through the ordinary prefix-hit path and pays only a
        suffix prefill. The caller MUST eventually call
        :meth:`release_preempted` — after the resume re-admission (which holds
        its own references by then) or when the request is abandoned.

        Returns ``None`` — leaving the slot untouched and running — when the
        slot retired during the pipeline flush, when no transcript exists
        (cache enabled after this slot was admitted), or when the checkpoint
        would not be re-admissible (pool too full to capture enough blocks for
        a transcript beyond the bucket ladder). Raises ``RuntimeError`` when
        the prefix cache is disabled.
        """
        if self.prefix_cache is None:
            raise RuntimeError("preempt requires the prefix cache (prefix_cache_blocks > 0)")
        self._ensure_usable()
        # flush the in-flight step under the OLD slot mapping (same rule as
        # cancel): its tokens are real — they extend this slot's transcript
        # and reach its consumer through the buffered events
        self._pending_events.extend(self._fetch_inflight())
        if not self._active[slot]:
            return None  # retired during the flush: nothing left to preempt
        transcript = self._slot_tokens.get(slot)
        if transcript is None:
            return None  # cache enabled after admission: no transcript to resume
        valid = int(self._lens_host[slot])
        tokens = np.asarray(transcript[:valid], dtype=np.int32)
        # capture: index every full block of the transcript (prompt + generated),
        # device-copying KV out of the slot's cache rows for the new ones only
        self._extend_index(slot, tokens)
        covered = len(self._slot_path.get(slot, ())) * self._prefix_block_size
        try:
            admissible = covered + self.bucket_for(valid - covered) <= self.max_len
        except ValueError:
            admissible = False
        if self.prefill_chunk is not None and valid < self.max_len:
            admissible = True  # the chunked path re-admits any in-capacity suffix
        if not admissible:
            # a pool too full to capture enough blocks: abandoning the slot
            # would strand the request, so decline — it keeps running and the
            # early-captured blocks simply age out of the tree
            return None
        path = self._slot_path.pop(slot, [])
        self.prefix_cache.pin(path)  # survives LRU + the working-ref release below
        try:
            self.prefix_cache.release(path)
            self._slot_tokens.pop(slot, None)
            if self.paged:
                # NEAR-FREE handoff: the checkpoint's blocks were ADOPTED by
                # the index inside _extend_index above — ownership moved, no
                # dense re-slicing, no device copy. Only the un-adopted
                # leftovers (partial tail, unused budget) return to the pool.
                self._free_slot_blocks(slot)
            self._active[slot] = False
            self._reserved[slot] = False
            self._remaining[slot] = 0
            self._slot_temp[slot] = self.temperature
            self._slot_top_k[slot] = 0
            self._slot_top_p[slot] = 1.0
            self._slot_queue_wait.pop(slot, None)
            self.preempted_requests += 1
            if self._telemetry is not None:
                self._note_span(
                    slot, "preempted",
                    transcript_tokens=int(valid), pinned_blocks=len(path),
                )
                self._telemetry.preemptions_total.inc()
                self._drop_rid(slot)
            self._slot_device_update(slot, False, 0, self.temperature, 0, 1.0)
        except Exception:
            # the checkpoint never reached the caller: drop the eviction pin
            # before propagating, or the blocks stay fenced forever
            self.prefix_cache.unpin(path)
            raise
        return PreemptedSlot(tokens=[int(t) for t in tokens], path=path)

    # owns: kv-pin
    def release_preempted(self, state: PreemptedSlot) -> None:
        """Drop a preempted checkpoint's eviction pin — after its resume
        re-admitted (the new slot holds its own references by then) or when
        the re-queued request was cancelled. Idempotence is the caller's job:
        unpinning twice would free blocks a resume still depends on."""
        if self.prefix_cache is not None and state.path:
            self.prefix_cache.unpin(state.path)

    def generate(
        self,
        prompt_ids: Sequence[int],
        max_new_tokens: int,
        *,
        lookahead: int = 1,
        temperature: Optional[float] = None,
        top_k: int = 0,
        top_p: float = 1.0,
    ) -> List[int]:
        """Single-request convenience driver (tests/scripts): run one request to
        completion on an otherwise-idle engine and return its emitted tokens."""
        slot = self.add_request(
            prompt_ids, max_new_tokens, temperature=temperature, top_k=top_k, top_p=top_p
        )
        out: List[int] = []
        # reserved = chunked prefill still in progress: keep ticking until done
        while self._active[slot] or slot in self._partials:
            for event in self.step(lookahead):
                if event.slot == slot and event.emit:
                    out.append(event.token)
        return out


class _FutureSink:
    """Buffers emitted tokens; resolves an asyncio future with the full list."""

    #: set by the consumer when it abandons the request (disconnect/early exit);
    #: the worker cancels the slot instead of delivering to a dead consumer
    cancelled = False

    def __init__(self, loop: asyncio.AbstractEventLoop, future: asyncio.Future) -> None:
        self._loop = loop
        self._future = future
        self._tokens: List[int] = []

    def emit(self, token: int) -> None:
        self._tokens.append(token)

    def finish(self) -> None:
        tokens = list(self._tokens)
        self._loop.call_soon_threadsafe(
            lambda: self._future.done() or self._future.set_result(tokens)
        )

    def fail(self, exc: BaseException) -> None:
        self._loop.call_soon_threadsafe(
            lambda: self._future.done() or self._future.set_exception(exc)
        )


def _as_engine_failure(
    exc: BaseException, *, reason: str = "engine_failure", retryable: bool = True
) -> EngineFailure:
    """Wrap an arbitrary engine-side exception as the structured failure a
    sink receives — never a bare ``str(exc)`` sink (injected faults keep
    their site slug so chaos tests can assert attribution)."""
    if isinstance(exc, EngineFailure):
        return exc
    site = getattr(exc, "site", None)
    if site is not None:
        reason = f"injected_{site}"
    return EngineFailure(f"{type(exc).__name__}: {exc}", reason=reason, retryable=retryable)


_STREAM_DONE = object()


class _QueueSink:
    """Forwards each token to an asyncio queue as it decodes (streaming)."""

    cancelled = False

    def __init__(self, loop: asyncio.AbstractEventLoop, queue: "asyncio.Queue") -> None:
        self._loop = loop
        self._queue = queue

    def emit(self, token: int) -> None:
        self._loop.call_soon_threadsafe(self._queue.put_nowait, token)

    def finish(self) -> None:
        self._loop.call_soon_threadsafe(self._queue.put_nowait, _STREAM_DONE)

    def fail(self, exc: BaseException) -> None:
        self._loop.call_soon_threadsafe(self._queue.put_nowait, exc)


class ContinuousBatcher:
    """Asyncio facade running a :class:`DecodeEngine` on a worker thread.

    ``await generate(prompt_ids, max_new_tokens)`` enqueues a request; the worker
    admits queued requests into free slots between decode steps and resolves each
    future with the completed token list. ``stream(...)`` yields tokens as they
    decode instead. One engine step at a time, no step blocking the event loop.

    :param lookahead: decode steps fused per device dispatch (see
        :meth:`DecodeEngine.step`). Raises throughput by cutting host syncs;
        streamed tokens arrive in bursts of up to this size, and queued requests
        wait up to a burst before admission — keep it small (4-16) for
        interactive serving.
    :param scheduler: the SLO admission-control policy
        (:class:`~unionml_tpu.serving.scheduler.SLOScheduler`, or a
        :class:`~unionml_tpu.serving.scheduler.SchedulerConfig` to build one).
        Every request routes through it: bounded multi-class queueing with
        anti-starvation aging, load shedding (structured
        ``QueueFullError``/``DeadlineInfeasibleError``), deadline enforcement
        on queued AND running requests, and — when the engine's prefix cache
        is enabled — preempt-to-prefix-cache for strictly-higher-class
        arrivals against a full house. ``None`` builds the default policy
        (requests without ``priority``/``deadline_ms`` behave like the old
        FIFO queue, now bounded).
    :param supervisor: an
        :class:`~unionml_tpu.serving.supervisor.EngineSupervisor` enabling
        SUPERVISED RECOVERY: on an engine-wide failure every salvageable
        request is checkpoint-resumed through the scheduler (token-identical,
        its sink keeping the tokens already delivered) after an in-place
        engine rebuild — with bounded-exponential-backoff retries and a
        health state machine ``/healthz`` can serve. ``None`` preserves the
        unsupervised contract: in-flight work fails (with structured,
        machine-readable reasons) and the engine auto-recovers for the next
        request.
    """

    #: app-layer capability flag: generate()/stream() accept ``request_id=``
    accepts_request_id = True

    def __init__(
        self,
        engine: DecodeEngine,
        *,
        lookahead: int = 1,
        scheduler: Optional[Any] = None,
        supervisor: Optional[Any] = None,
        telemetry: Optional[Any] = None,
    ) -> None:
        from unionml_tpu.serving.scheduler import SchedulerConfig, SLOScheduler

        self._engine = engine
        self._lookahead = max(1, int(lookahead))
        #: span/metrics collector shared by the whole request path; the batcher
        #: is the wiring hub — it propagates one instance into the engine, the
        #: scheduler, the supervisor, the fault plan, and the prefix cache, so
        #: callers only attach telemetry at ONE place (here or the engine)
        self._telemetry = telemetry if telemetry is not None else engine._telemetry
        if self._telemetry is not None:
            if engine._telemetry is None:
                engine._telemetry = self._telemetry
            if engine._faults is not None and engine._faults.telemetry is None:
                engine._faults.telemetry = self._telemetry
            if engine.prefix_cache is not None and engine.prefix_cache.telemetry is None:
                engine.prefix_cache.telemetry = self._telemetry
            if supervisor is not None and getattr(supervisor, "_telemetry", None) is None:
                supervisor._telemetry = self._telemetry
        #: the recovery policy layer (:class:`~unionml_tpu.serving.supervisor.
        #: EngineSupervisor`): with one attached, an engine failure salvages
        #: and RESUMES every recoverable request instead of failing the house;
        #: None preserves the fail-everything-structured behavior
        self.supervisor = supervisor
        if supervisor is not None:
            supervisor.attach(engine)
        #: the SLO admission-control queue (thread-safe: owns its own lock)
        self.scheduler = (
            scheduler
            if isinstance(scheduler, SLOScheduler)
            else SLOScheduler(
                scheduler if isinstance(scheduler, SchedulerConfig) else None,
                telemetry=self._telemetry,
            )
        )
        if self._telemetry is not None and getattr(self.scheduler, "_telemetry", None) is None:
            self.scheduler._telemetry = self._telemetry
        # one signal dict for router + autoscaler: the scheduler's load_signal
        # carries the paged pool's occupancy next to the queue-wait EMAs
        if getattr(self.scheduler, "pool_signal", None) is None:
            self.scheduler.pool_signal = engine.pool_signal
        #: slot -> sink; worker-thread-only by design (admission fan-out and
        #: event dispatch both run on the worker), so no guard is declared
        self._sinks: Dict[int, Any] = {}
        #: slot -> Ticket for the slot's current occupant (deadline enforcement
        #: and preemption-victim choice); worker-thread-only like _sinks
        self._slot_meta: Dict[int, Any] = {}
        self._lock = threading.Lock()
        self._work = threading.Event()
        self._closed = False  # guarded-by: _lock
        #: preempted checkpoints whose tickets died off-worker (close with the
        #: worker live): the worker unpins them, keeping every prefix-cache
        #: mutation on one thread
        self._orphans: List[Any] = []  # guarded-by: _lock
        self._worker: Optional[threading.Thread] = None
        #: fleet hand-off hook: called (worker thread) with this batcher's
        #: orphaned tickets when rebuild exhaustion leaves the engine dead;
        #: returns the tickets it could NOT place elsewhere, which then fail
        #: with the structured unavailable error. None = no fleet (all fail).
        self.on_tickets_orphaned: Optional[Callable[[List[Any]], Sequence[Any]]] = None

    @property
    def engine(self) -> DecodeEngine:
        return self._engine

    def attach_telemetry(self, telemetry: Any) -> None:
        """Wire a span/metrics collector into a PREBUILT batcher (no-op when
        one is already attached): same propagation as construction-time
        wiring, so the app layer instruments prebuilt generators uniformly.
        Call before the first submission — the hooks are read without a lock
        on the assumption they are set before traffic."""
        if telemetry is None or self._telemetry is not None:
            return
        self._telemetry = telemetry  # graftlint: disable=data-race -- documented contract: called before the first submission, so the wiring happens-before every worker read
        engine = self._engine
        if engine._telemetry is None:
            engine._telemetry = telemetry
        if engine._faults is not None and engine._faults.telemetry is None:
            engine._faults.telemetry = telemetry
        if engine.prefix_cache is not None and engine.prefix_cache.telemetry is None:
            engine.prefix_cache.telemetry = telemetry
        if self.supervisor is not None and getattr(self.supervisor, "_telemetry", None) is None:
            self.supervisor._telemetry = telemetry  # graftlint: disable=data-race -- pre-traffic wiring (see docstring); supervisor is never rebound after __init__
        if getattr(self.scheduler, "_telemetry", None) is None:
            self.scheduler._telemetry = telemetry  # graftlint: disable=data-race -- pre-traffic wiring; scheduler is never rebound after __init__ and SLOScheduler guards its own state

    def _ensure_worker(self) -> None:
        if self._worker is None or not self._worker.is_alive():
            self._worker = threading.Thread(target=self._run, name="continuous-batcher", daemon=True)
            self._worker.start()

    def _submit(
        self,
        prompt_ids: Sequence[int],
        max_new_tokens: int,
        sink: Any,
        sampling: Optional[Dict[str, Any]] = None,
        priority: Any = None,
        deadline_ms: Optional[float] = None,
        request_id: Optional[str] = None,
    ) -> None:
        prompt = np.asarray(prompt_ids, dtype=np.int32).reshape(-1)
        # surface bad requests on the caller's side, not the worker's
        if prompt.size == 0:
            raise ValueError("empty prompt")
        self._engine.bucket_for(prompt.size)
        if self.supervisor is not None and self.supervisor.state == "failed":
            # the rebuild budget is exhausted: fail fast with the structured
            # terminal error instead of queueing work that can never run
            raise self.supervisor.unavailable_error()
        ticket = self.scheduler.make_ticket(
            prompt, int(max_new_tokens), sampling, sink,
            priority=priority, deadline_ms=deadline_ms,
        )
        telemetry = self._telemetry
        if telemetry is not None:
            from unionml_tpu.serving.scheduler import class_name

            # joins the fleet-opened trace when request_id is already traced
            # (failover keeps ONE trace across replicas); opens a fresh one
            # for a solo batcher
            ticket.request_id = telemetry.new_trace(
                request_id, cls=class_name(ticket.priority)
            )
            telemetry.note_tokens_in(ticket.request_id, int(prompt.size))
            pool_sig = self._engine.pool_signal()
            telemetry.span(
                ticket.request_id, "admission",
                prompt_tokens=int(prompt.size), budget=int(max_new_tokens),
                cls=class_name(ticket.priority),
                deadline_ms=deadline_ms,
                # journal v2: the pool arithmetic at admission time, so a
                # simulator replay needs no side channels (0 / None on dense)
                block_demand=self._engine.block_demand(
                    int(prompt.size), int(max_new_tokens)
                ),
                available_blocks=(
                    None if pool_sig is None else pool_sig["available_blocks"]
                ),
            )
        try:
            with self._lock:
                if self._closed:
                    raise EngineFailure("batcher is closed", reason="batcher_closed")
                # shed decisions raise HERE (caller side) while the close check
                # still holds, so a shed request never reaches a closed queue
                displaced = self.scheduler.submit(ticket)
        except Exception as exc:
            if telemetry is not None:
                # terminal shed span + journal entry (429/503 at the route);
                # recorded OUTSIDE both locks (telemetry is lock-leaf)
                reason = getattr(exc, "reason", "rejected")
                telemetry.sheds_total.inc(1.0, reason)
                telemetry.end_trace(ticket.request_id, "shed", reason=reason)
            raise
        if displaced is not None:
            # a full queue displaced its worst request in favor of this one:
            # fail it fast with the structured shed error (sink delivery is
            # thread-safe; displaced tickets are never resumes, so no pin)
            if telemetry is not None:
                telemetry.sheds_total.inc(1.0, "displaced")
                telemetry.end_trace(displaced.request_id, "shed", reason="displaced")
            self._deliver(displaced.sink, "fail", displaced.shed_exc)
        self._ensure_worker()
        self._work.set()

    def adopt_ticket(self, ticket: Any) -> None:
        """Adopt another batcher's orphaned ticket (fleet failover).

        The ticket arrives re-routed from a replica whose rebuild budget
        exhausted: its prompt is already the full transcript, its budget the
        unspent remainder, its deadline/priority/sink untouched, and its
        salvage pin released (pins never cross engines — this engine pays a
        fresh prefill, shortened by whatever prefix its own cache holds).
        Sinks are loop-bound, not engine-bound, so delivery continues
        seamlessly. Requeues through the scheduler's salvage path (bypassing
        the admission bound — the work is already partially paid for) and
        raises :class:`~unionml_tpu.serving.faults.EngineFailure` when this
        batcher is closed, so the caller can try the next survivor.
        """
        prompt = np.asarray(ticket.prompt, dtype=np.int32).reshape(-1)
        self._engine.bucket_for(prompt.size)  # unroutable here -> caller tries elsewhere
        with self._lock:
            if self._closed:
                raise EngineFailure("batcher is closed", reason="batcher_closed")
            self.scheduler.requeue(ticket, preemption=False)
        self._ensure_worker()
        self._work.set()

    async def generate(
        self,
        prompt_ids: Sequence[int],
        max_new_tokens: int,
        *,
        priority: Any = None,
        deadline_ms: Optional[float] = None,
        request_id: Optional[str] = None,
        **sampling,
    ) -> List[int]:
        loop = asyncio.get_running_loop()
        future: asyncio.Future = loop.create_future()
        self._submit(
            prompt_ids, max_new_tokens, _FutureSink(loop, future), sampling,
            priority=priority, deadline_ms=deadline_ms, request_id=request_id,
        )
        return await future

    async def stream(
        self,
        prompt_ids: Sequence[int],
        max_new_tokens: int,
        *,
        priority: Any = None,
        deadline_ms: Optional[float] = None,
        request_id: Optional[str] = None,
        **sampling,
    ):
        """Async iterator of tokens, yielded as the engine decodes them.

        The request shares slots (and decode steps) with every other in-flight
        request; per-token latency is one engine step. Abandoning the iterator
        early (client disconnect) cancels the request's decode slot.
        """
        loop = asyncio.get_running_loop()
        queue: "asyncio.Queue" = asyncio.Queue()
        sink = _QueueSink(loop, queue)
        self._submit(
            prompt_ids, max_new_tokens, sink, sampling,
            priority=priority, deadline_ms=deadline_ms, request_id=request_id,
        )
        try:
            while True:
                item = await queue.get()
                if item is _STREAM_DONE:
                    return
                if isinstance(item, BaseException):
                    raise item
                yield item
        finally:
            # reached on normal completion too (cancelling a finished request
            # is a no-op); on early exit it frees the slot for other requests
            sink.cancelled = True

    def _deliver(self, sink: Any, method: str, *args) -> bool:
        """Invoke a sink callback, absorbing consumer-side failures.

        A dead consumer (its event loop closed after a disconnect/early exit)
        raises from ``call_soon_threadsafe``; that must cost only this request —
        never the worker thread, which every other in-flight request depends on.
        """
        try:
            getattr(sink, method)(*args)
            return True
        except Exception:
            logger.warning("sink %s delivery failed (consumer gone?); dropping request", method)
            return False

    # owns: kv-pin
    def _release_ticket(self, ticket: Any) -> None:
        """Drop a dead ticket's engine-side state: a preempted checkpoint's
        eviction pin must not outlive its request (worker thread only)."""
        if ticket.resume is not None:
            self._engine.release_preempted(ticket.resume)
            ticket.resume = None

    # owns: trace
    def _tel_end(self, ticket: Any, status: str, reason: Optional[str] = None) -> None:
        """Close a ticket's trace on terminal delivery (no-op without telemetry
        or for untraced tickets; always called OUTSIDE the batcher lock)."""
        if self._telemetry is None or getattr(ticket, "request_id", None) is None:
            return
        if status == "shed" and reason is not None:
            self._telemetry.sheds_total.inc(1.0, reason)
        self._telemetry.end_trace(ticket.request_id, status, reason=reason)

    def _drain_orphans(self) -> None:
        """Unpin checkpoints whose tickets were dropped off-worker (close)."""
        with self._lock:
            orphans, self._orphans[:] = list(self._orphans), []
        for state in orphans:
            self._engine.release_preempted(state)

    def _enforce_deadlines(self) -> None:  # graftlint: off-path (scheduler policy, not steady-state decode)
        """Fail queued tickets and cancel running slots whose deadline passed.

        A request that can no longer meet its SLO only burns decode steps and
        queue positions other requests need — both sides fail fast with the
        structured :class:`DeadlineExceededError` (HTTP 504 at the route).
        """
        from unionml_tpu.serving.scheduler import DeadlineExceededError

        now = time.monotonic()
        for ticket in self.scheduler.take_expired(now):
            self._release_ticket(ticket)
            self._tel_end(ticket, "shed", "deadline_exceeded")
            self._deliver(
                ticket.sink, "fail",
                DeadlineExceededError("deadline expired while queued"),
            )
        for slot, ticket in list(self._slot_meta.items()):
            if ticket.expired(now):
                # cancel flushes the pipeline and drops this slot's own
                # buffered tokens; survivors' events are delivered by the
                # next step under the unchanged mapping
                self._engine.cancel(slot)
                self.scheduler.note_deadline_miss_running()
                self._sinks.pop(slot, None)  # graftlint: disable=data-race -- _sinks is worker-thread-only by design (declared at __init__); the api-side accesses are drain/close idle probes that tolerate staleness
                self._slot_meta.pop(slot, None)  # graftlint: disable=data-race -- worker-thread-only like _sinks (declared at __init__); tests drive _admit synchronously with no worker running
                self._tel_end(ticket, "shed", "deadline_exceeded")
                self._deliver(
                    ticket.sink, "fail",
                    DeadlineExceededError("deadline expired while decoding"),
                )

    def _maybe_preempt(self) -> None:  # graftlint: off-path (scheduler policy, not steady-state decode)
        """Preempt-to-prefix-cache: when a strictly-higher-class request waits
        with no free slot, checkpoint the worst running victim (lowest class,
        most tokens remaining) into the prefix cache, and re-queue it so its
        resume pays only a suffix prefill. One victim per admission round —
        the freed slot goes to the waiter before any further preemption."""
        if (
            self.scheduler.config.fifo
            or not self.scheduler.config.preempt
            or self._engine.prefix_cache is None
        ):
            return
        if self._engine.free_slots and not self._block_starved():
            return
        waiting = self.scheduler.best_waiting_priority()
        if waiting is None:
            return
        # victims: strictly lower class than the waiter, worst class first,
        # most remaining tokens first (least sunk work per token reclaimed)
        victims = sorted(
            (
                (ticket.priority, int(self._engine._remaining[slot]), slot, ticket)
                for slot, ticket in self._slot_meta.items()
                if ticket.priority > waiting and self._engine._active[slot]
            ),
            reverse=True,
        )
        for _, _, slot, ticket in victims:
            state = self._engine.preempt(slot)
            try:
                if self._engine.has_pending_events:
                    # the preempt flush ran under the OLD mapping: deliver the
                    # victim's (and survivors') flushed tokens before re-keying
                    self._dispatch_events(self._engine.take_pending_events())
                if state is None:
                    # retired during the flush (a slot freed anyway) or not
                    # checkpointable — the dispatch above reconciled either way
                    if self._engine.free_slots:
                        return
                    continue
                # the sink keeps every token it already received; the ticket's
                # prompt becomes the full transcript and its budget shrinks by
                # the tokens already delivered, so the resumed decode continues
                # the stream exactly where the preemption cut it
                sink = self._sinks.pop(slot, None)
                meta = self._slot_meta.pop(slot, ticket)
                generated = len(state.tokens) - len(meta.prompt)
                meta.prompt = np.asarray(state.tokens, dtype=np.int32)
                meta.budget = int(meta.budget) - max(0, generated)
                meta.resume = state
                meta.sink = sink if sink is not None else meta.sink
                self.scheduler.requeue(meta)
            except Exception as exc:
                # the checkpoint never reached the queue: drop its pin before
                # propagating, or the victim's blocks stay fenced forever —
                # and fail the victim's consumer (its sink left the slot maps
                # above, so the engine-failure sweep can no longer reach it)
                if state is not None:
                    self._engine.release_preempted(state)
                victim = self._sinks.pop(slot, None) or getattr(
                    ticket, "sink", None
                )
                self._slot_meta.pop(slot, None)
                if victim is not None:
                    self._deliver(victim, "fail", exc)
                self._tel_end(ticket, "error", "preempt_requeue_failed")
                raise
            return

    def _block_starved(self) -> bool:
        """True when the head queued ticket's conservative block demand
        exceeds what the paged pool could allocate right now — the signal
        that block pressure (not slot scarcity) is gating admission, which
        arms preempt-to-prefix-cache even with slots free. Always False on
        dense engines (no block accounting)."""
        avail = getattr(self._engine, "available_blocks", lambda: None)()
        if avail is None:
            return False
        head = self.scheduler.peek()
        if head is None:
            return False
        return self._engine.block_demand(len(head.prompt), head.budget) > avail

    def _admit(self) -> None:  # graftlint: off-path (admission, not steady-state decode)
        self._drain_orphans()
        self._enforce_deadlines()
        self._maybe_preempt()
        while True:
            free = self._engine.free_slots
            if not free:
                return
            batch = self.scheduler.pop(len(free))
            if not batch:
                return
            admissible = []
            blocked: List[Any] = []
            # paged admission gates on BLOCK demand too: tickets past the
            # pool's reclaimable budget requeue (in scheduler order) instead
            # of bouncing off the engine's pool_exhausted failure — they age
            # in the queue and admit as running requests retire
            avail = getattr(self._engine, "available_blocks", lambda: None)()
            for ticket in batch:
                if blocked:
                    blocked.append(ticket)  # keep scheduler order behind the blocker
                    continue
                if ticket.sink.cancelled:  # consumer gave up while queued
                    self._release_ticket(ticket)
                    self._tel_end(ticket, "cancelled")
                    continue
                try:
                    self._engine.validate_request(ticket.prompt, ticket.budget, **ticket.sampling)
                except Exception as exc:  # reject this request, keep serving others
                    self._release_ticket(ticket)
                    self._tel_end(ticket, "error", "invalid_request")
                    self._deliver(ticket.sink, "fail", exc)
                    continue
                if avail is not None:
                    demand = self._engine.block_demand(len(ticket.prompt), ticket.budget)
                    if demand > avail:
                        # head-of-line blocking on purpose: admitting smaller
                        # latecomers around a starved head would starve it
                        blocked.append(ticket)
                        continue
                    avail -= demand
                admissible.append(ticket)
            for ticket in blocked:
                self.scheduler.requeue(ticket, preemption=False)
            if admissible and not self._admit_batch(admissible):
                return  # engine failure ended this admission round
            if blocked:
                return  # the pool is the binding constraint: wait for retirements
            if not admissible:
                continue

    def _drain_flush_events(self) -> None:
        """Deliver events an admission-time pipeline flush buffered — under
        the OLD sink mapping, BEFORE any new sink takes over a slot."""
        if getattr(self._engine, "has_pending_events", False):
            self._dispatch_events(self._engine.take_pending_events())

    def _register(self, slot: int, ticket: Any) -> None:
        """Bind an admitted ticket to its slot (and retire its resume pin:
        the re-admission holds its own references on the blocks now)."""
        self._sinks[slot] = ticket.sink
        self._slot_meta[slot] = ticket
        self._engine.note_queue_wait(slot, ticket.queue_wait_ms)
        if self._telemetry is not None:
            # binds the trace to the slot AND flushes the admission-time
            # prefill/prefix spans the engine buffered for it
            self._engine.note_request_id(slot, ticket.request_id)
            self._telemetry.span(
                ticket.request_id, "admitted",
                slot=slot, resume=ticket.resume is not None,
            )
        if ticket.resume is not None:
            self._engine.release_preempted(ticket.resume)
            ticket.resume = None
        if hasattr(self._engine, "note_request_class"):
            from unionml_tpu.serving.scheduler import class_name

            # label the slot for the per-class acceptance gauge
            self._engine.note_request_class(slot, class_name(ticket.priority))

    def _spec_sampling(self, ticket: Any) -> Optional[Dict[str, Any]]:
        """The ticket's sampling dict with the per-class speculation default
        applied (``SchedulerConfig.speculative_classes``); a client's explicit
        ``speculative`` always wins, and engines without a speculative mode get
        the dict untouched (they reject unknown keys)."""
        if not hasattr(self._engine, "speculation_stats"):
            return ticket.sampling
        from unionml_tpu.serving.scheduler import class_name

        sampling = dict(ticket.sampling or {})
        sampling.setdefault(
            "speculative",
            class_name(ticket.priority) in self.scheduler.config.speculative_classes,
        )
        return sampling

    def _admit_batch(self, admissible: List[Any]) -> bool:  # graftlint: off-path (admission, not steady-state decode)
        """Admit popped tickets with per-request failure attribution.

        One admission call batches same-bucket prefills; when it fails
        WITHOUT an engine failure (the engine rolled this call back cleanly),
        the batch re-admits one request at a time so only the raiser fails —
        with a structured reason — and every sibling proceeds. An engine
        failure hands the un-admitted tickets to the recovery path (they
        requeue untouched) and returns False to end the admission round.
        """
        failures_before = getattr(self._engine, "failure_count", 0)
        try:
            slots = self._engine.admit_many(
                [(t.prompt, t.budget, self._spec_sampling(t)) for t in admissible]
            )
        except Exception as exc:
            if getattr(self._engine, "failure_count", 0) != failures_before:
                self._handle_engine_failure(exc, pending=admissible)
                return False
            self._drain_flush_events()
            if len(admissible) == 1:
                ticket = admissible[0]
                self._release_ticket(ticket)
                self._tel_end(ticket, "error", "prefill_failed")
                self._deliver(
                    ticket.sink, "fail", _as_engine_failure(exc, reason="prefill_failed")
                )
                return True
            for ticket in admissible:
                failures_before = getattr(self._engine, "failure_count", 0)
                try:
                    (slot,) = self._engine.admit_many(
                        [(ticket.prompt, ticket.budget, self._spec_sampling(ticket))]
                    )
                except Exception as one_exc:
                    if getattr(self._engine, "failure_count", 0) != failures_before:
                        self._handle_engine_failure(one_exc, pending=[ticket])
                        return False
                    self._drain_flush_events()
                    self._release_ticket(ticket)
                    self._tel_end(ticket, "error", "prefill_failed")
                    self._deliver(
                        ticket.sink, "fail",
                        _as_engine_failure(one_exc, reason="prefill_failed"),
                    )
                    continue
                self._drain_flush_events()
                self._register(slot, ticket)
            return True
        self._drain_flush_events()
        for slot, ticket in zip(slots, admissible):
            self._register(slot, ticket)
        return True

    def _fail_all(self, exc: Exception) -> None:  # graftlint: off-path (error path)
        """Fail every in-flight request (structured) and abandon the engine's
        slots — the unsupervised fallback when no recovery policy is attached."""
        failure = _as_engine_failure(exc)
        for ticket in self._slot_meta.values():
            self._tel_end(ticket, "error", failure.reason)
        for sink in self._sinks.values():
            self._deliver(sink, "fail", failure)
        self._sinks.clear()
        self._slot_meta.clear()
        self._engine.abort_all()

    # owns: kv-pin
    def _handle_engine_failure(self, exc: BaseException, pending: Sequence[Any] = ()) -> None:  # graftlint: off-path (error recovery)
        """Recover from an engine-wide failure.

        With a supervisor: every salvageable request becomes a RESUME ticket
        (its sink keeps the tokens already delivered; the transcript becomes
        the prompt, the unspent budget carries over, and the pinned salvage
        path shrinks the re-prefill to a suffix) re-queued through the
        scheduler — deadlines and priorities intact — after the engine is
        confirmed rebuilt (bounded-backoff retries when the in-place rebuild
        failed). Unsalvageable requests fail with a structured, machine-
        readable reason; rebuild exhaustion fails EVERYTHING (pending,
        resumes, the whole queue) and leaves the supervisor ``failed``.

        Without a supervisor: the old contract — all in-flight work fails,
        now with structured reasons — plus salvage-pin hygiene.

        ``pending`` carries popped-but-unadmitted tickets from a failed
        admission call; they re-queue untouched (no tokens were delivered).
        """
        engine = self._engine
        if hasattr(engine, "note_external_failure"):
            engine.note_external_failure()  # escalate poisoned out-of-band calls
        sup = self.supervisor
        if sup is None:
            if hasattr(engine, "discard_salvage"):
                engine.discard_salvage()
            failure = _as_engine_failure(exc)
            for ticket in pending:
                self._release_ticket(ticket)
                self._tel_end(ticket, "error", failure.reason)
                self._deliver(ticket.sink, "fail", failure)
            self._fail_all(exc)
            return
        sup.note_failure(exc)
        resumes: List[Any] = []
        for rec in (engine.take_salvage() if hasattr(engine, "take_salvage") else []):
            sink = self._sinks.pop(rec.slot, None)
            meta = self._slot_meta.pop(rec.slot, None)
            pin = PreemptedSlot(tokens=list(rec.tokens), path=rec.path)
            if sink is None or meta is None or sink.cancelled:
                engine.release_preempted(pin)  # no consumer: drop the checkpoint
                if meta is not None:
                    self._tel_end(meta, "cancelled")
                continue
            try:
                engine.validate_request(rec.tokens, max(1, int(rec.remaining)), **meta.sampling)
            except Exception as not_resumable:
                engine.release_preempted(pin)
                sup.note_request_failed()
                self._tel_end(meta, "error", "request_unrecoverable")
                self._deliver(
                    sink, "fail",
                    EngineFailure(
                        f"request not resumable after engine failure: {not_resumable}",
                        reason="request_unrecoverable", retryable=False,
                    ),
                )
                if meta.resume is not None:
                    engine.release_preempted(meta.resume)
                    meta.resume = None
                continue
            if meta.resume is not None:
                # preempt-then-failure: the fresher salvage checkpoint
                # supersedes the preemption's — its pin can go now
                engine.release_preempted(meta.resume)
            meta.prompt = np.asarray(rec.tokens, dtype=np.int32)
            meta.budget = int(rec.remaining)
            meta.resume = pin
            meta.sink = sink
            if self._telemetry is not None and meta.request_id is not None:
                # the trace stays OPEN across salvage: continuity from death to
                # resumed decode is exactly what the failover pins assert
                self._telemetry.span(
                    meta.request_id, "salvaged",
                    transcript_tokens=len(rec.tokens), remaining=int(rec.remaining),
                )
            resumes.append(meta)
        # any sink still mapped had nothing salvageable behind it: fail it
        failure = _as_engine_failure(exc)
        for slot, sink in list(self._sinks.items()):
            meta = self._slot_meta.pop(slot, None)
            if meta is not None:
                self._release_ticket(meta)
                self._tel_end(meta, "error", failure.reason)
            sup.note_request_failed()
            self._deliver(sink, "fail", failure)
        self._sinks.clear()
        self._slot_meta.clear()
        if getattr(engine, "failed", False):
            rebuilt = sup.run_rebuild(engine.rebuild)
        else:
            sup.note_rebuilt()  # the engine already rebuilt itself in place
            rebuilt = True
        if not rebuilt:
            # this engine is dead for good. Every ticket's salvage pin points
            # into THIS engine's block pool — a hand-off target can restore
            # nothing from it, and the pins must not outlive the replica — so
            # release them all; the transcript-as-prompt (set above) already
            # carries everything a resume needs on another engine.
            orphans: List[Any] = []
            for meta in resumes:
                if meta.resume is not None:
                    engine.release_preempted(meta.resume)
                    meta.resume = None
                orphans.append(meta)
            for ticket in list(pending) + self.scheduler.drain():
                self._release_ticket(ticket)
                orphans.append(ticket)
            handoff = self.on_tickets_orphaned
            unplaced: Sequence[Any] = orphans
            if handoff is not None and orphans:
                try:
                    unplaced = list(handoff(orphans))
                except Exception:
                    logger.exception("orphaned-ticket hand-off failed; failing all tickets")
                    unplaced = orphans
            placed = len(orphans) - len(unplaced)
            if placed > 0:
                sup.note_recovered(placed)
            unavailable = sup.unavailable_error()
            for ticket in unplaced:
                sup.note_request_failed()
                self._tel_end(ticket, "error", getattr(unavailable, "reason", "engine_failed"))
                self._deliver(ticket.sink, "fail", unavailable)
            return
        for meta in resumes:
            self.scheduler.requeue(meta, preemption=False)
        if resumes:
            sup.note_recovered(len(resumes))
        for ticket in pending:
            self.scheduler.requeue(ticket, preemption=False)

    def _dispatch_events(self, events) -> None:
        """Fan one step's events out to their sinks (cancel on dead consumers;
        engine-terminated requests fail with their structured reason)."""
        for event in events:
            sink = self._sinks.get(event.slot)
            if sink is None:
                continue
            if sink.cancelled:  # consumer abandoned the stream mid-decode
                del self._sinks[event.slot]
                meta = self._slot_meta.pop(event.slot, None)
                if meta is not None:
                    self._tel_end(meta, "cancelled")
                # a FINISHED event's slot already retired engine-side — and may
                # even hold a newly admitted request by the time a pipeline-
                # flushed event is delivered, so cancelling it would kill the
                # wrong occupant. Only a still-running slot needs the cancel.
                if not event.finished:
                    self._engine.cancel(event.slot)
                continue
            if event.error is not None:
                # the engine terminated this request (NaN quarantine, chunked-
                # prefill death): the slot is already free engine-side, so only
                # the consumer-side failure remains to deliver
                del self._sinks[event.slot]
                meta = self._slot_meta.pop(event.slot, None)
                if meta is not None:
                    self._release_ticket(meta)
                    self._tel_end(meta, "error", event.error)
                if self.supervisor is not None:
                    self.supervisor.note_request_failed()
                self._deliver(
                    sink, "fail",
                    EngineFailure(
                        f"request terminated by the engine: {event.error}",
                        reason=event.error,
                    ),
                )
                continue
            ok = True
            if event.emit:
                ok = self._deliver(sink, "emit", event.token)
            if not ok:
                del self._sinks[event.slot]
                meta = self._slot_meta.pop(event.slot, None)
                if meta is not None:
                    self._tel_end(meta, "cancelled")
                if not event.finished:
                    self._engine.cancel(event.slot)
                continue
            if event.finished:
                del self._sinks[event.slot]
                meta = self._slot_meta.pop(event.slot, None)
                if meta is not None:
                    self._tel_end(meta, "ok")
                self._deliver(sink, "finish")

    def _run(self) -> None:  # graftlint: hot-path
        while True:
            with self._lock:
                done = self._closed and not self.scheduler.depth and not self._sinks
            if done:
                self._drain_orphans()
                return
            try:
                self._admit()
            except Exception as exc:
                # _admit handles admission failures itself; what lands here is
                # scheduler-policy engine work (deadline cancel, preempt) dying
                logger.exception("admission round failed")
                self._handle_engine_failure(exc)
                continue
            if self._engine.num_active == 0 and (
                self._engine.has_pending_prefill
                or getattr(self._engine, "has_pending_events", False)
            ):
                # chunked prefills need ticks even with nothing decoding, and a
                # pipeline flush (cancel path) may have buffered events whose
                # sinks are still waiting
                try:
                    events = self._engine.step()
                except Exception as exc:
                    logger.exception("chunked-prefill tick failed")
                    self._handle_engine_failure(exc)
                    continue
                self._dispatch_events(events)
                continue
            if self._engine.num_active == 0:
                self._work.clear()
                # re-check under the flag: a request may have landed just now.
                # The bounded 0.5s wait doubles as the deadline-expiry tick for
                # queued requests while the engine idles.
                with self._lock:
                    if self.scheduler.depth or self._closed:
                        continue
                self._work.wait(timeout=0.5)
                continue
            try:
                # full house + queued work: shorten bursts so a retiring slot is
                # readmitted within a few steps — but not to 1, which would forfeit
                # the whole lookahead win for the entire duration of an overload
                contended = bool(self.scheduler.depth) and not self._engine.free_slots
                events = self._engine.step(
                    min(self._lookahead, 4) if contended else self._lookahead
                )
            except Exception as exc:  # recover (supervised) or fail loudly
                logger.exception("continuous-batching step failed")
                self._handle_engine_failure(exc)
                continue
            self._dispatch_events(events)

    def drain(self, timeout_s: float = 5.0) -> None:
        """Graceful shutdown, phase one: stop admitting NEW submissions (they
        fail fast with the structured ``batcher_closed`` error) while queued
        and running requests keep decoding to completion, for up to
        ``timeout_s``. Whatever remains after the window is failed promptly by
        the :meth:`close` this ends with — a bounded drain, never a hang."""
        with self._lock:
            self._closed = True
        self._work.set()
        deadline = time.monotonic() + max(0.0, float(timeout_s))
        while time.monotonic() < deadline:
            worker = self._worker
            if worker is None or not worker.is_alive():
                break  # nothing in flight can make progress anyway
            # advisory cross-thread reads: the worker owns these, but a stale
            # read only costs one extra 20ms poll
            if not self.scheduler.depth and not self._sinks and self._engine.num_active == 0:
                break
            time.sleep(0.02)
        self.close()

    def close(self) -> None:
        """Shut the batcher down: every still-QUEUED request fails promptly
        with the structured ``batcher_closed`` error (futures/streams must
        never hang on a closed batcher), running requests drain, and the
        worker exits. Preempted checkpoints of failed tickets are unpinned on
        the worker thread (the only prefix-cache mutator) when it is alive."""
        with self._lock:
            self._closed = True
        closed_exc = EngineFailure("batcher closed", reason="batcher_closed")
        orphans: List[Any] = []
        for ticket in self.scheduler.drain():
            if ticket.resume is not None:
                orphans.append(ticket.resume)
                ticket.resume = None
            self._tel_end(ticket, "shed", "batcher_closed")
            self._deliver(ticket.sink, "fail", closed_exc)
        worker = self._worker
        if orphans:
            if worker is not None and worker.is_alive():
                with self._lock:
                    self._orphans.extend(orphans)
            else:
                for state in orphans:
                    self._engine.release_preempted(state)
        self._work.set()
        if worker is not None:
            worker.join(timeout=5.0)
            if not worker.is_alive():
                # the worker exited without its final pass (e.g. it died on an
                # engine failure before close): nothing else touches the cache
                # now, so the orphaned pins can drop here
                self._drain_orphans()
        if self.supervisor is not None:
            self.supervisor.close()
