"""Native HTTP serving app (aiohttp): ``/``, ``/predict``, ``/health``.

Reference parity: ``unionml/fastapi.py:15-70`` — same endpoints, same request contract
(``inputs`` = reader kwargs, or ``features`` = raw features), same startup model-load
from ``UNIONML_MODEL_PATH`` or from backend lineage. Built on aiohttp rather than
FastAPI so the framework serves without optional deps; a FastAPI adapter with the same
handlers lives in :mod:`unionml_tpu.serving.fastapi_adapter`.

The prediction path goes through :class:`~unionml_tpu.serving.resident.ResidentPredictor`
— the resident XLA executable, not interpreted re-dispatch.
"""

import os
from http import HTTPStatus
from typing import Any, Optional

import numpy as np

from unionml_tpu._logging import logger
from unionml_tpu.serving.resident import ResidentPredictor

_INDEX_HTML = """
<html>
  <head><title>unionml-tpu</title></head>
  <body>
    <h1>unionml-tpu</h1>
    <p>TPU-native model training and serving</p>
  </body>
</html>
"""


def jsonable(value: Any) -> Any:
    """Convert predictions (device arrays, numpy, pandas) to JSON-serializable values."""
    import jax

    if isinstance(value, jax.Array):
        value = np.asarray(jax.device_get(value))
    if isinstance(value, np.ndarray):
        return value.tolist()
    if isinstance(value, (np.generic,)):
        return value.item()
    if hasattr(value, "to_dict") and not isinstance(value, dict):
        try:
            return value.to_dict(orient="records")
        except TypeError:
            return value.to_dict()
    if isinstance(value, (list, tuple)):
        return [jsonable(v) for v in value]
    if isinstance(value, dict):
        return {k: jsonable(v) for k, v in value.items()}
    return value


def load_model_artifact(
    model: Any,
    remote: bool = False,
    app_version: Optional[str] = None,
    model_version: str = "latest",
    model_path: Optional[str] = None,
) -> None:
    """Startup model resolution (``fastapi.py:22-34`` parity)."""
    if model.artifact is not None:
        return
    model_path = model_path or os.getenv("UNIONML_MODEL_PATH")
    if not remote:
        if model_path is None:
            raise ValueError(
                "Model artifact path not specified: pass --model-path to `unionml-tpu serve` (local mode)."
            )
        model.load(model_path)
    else:
        from unionml_tpu.remote import get_model_artifact

        model.artifact = get_model_artifact(model, app_version=app_version, model_version=model_version)


def build_aiohttp_app(
    model: Any,
    remote: bool = False,
    app_version: Optional[str] = None,
    model_version: str = "latest",
    resident: bool = True,
    coalesce: bool = True,
    max_batch: int = 64,
    max_wait_ms: float = 2.0,
    buckets: Optional[Any] = None,
    seq_buckets: Optional[Any] = None,
    example_features: Optional[Any] = None,
    generator: Optional[Any] = None,
    generate_lookahead: int = 1,
    generate_prefix_cache_blocks: int = 0,
    generate_prefix_block_size: int = 16,
    generate_scheduler: Optional[Any] = None,
    generate_supervisor: Optional[Any] = None,
    generate_drain_s: float = 5.0,
    generate_replicas: int = 1,
    generate_fleet_config: Optional[Any] = None,
    generate_telemetry: Any = True,
    generate_trace_journal: Optional[str] = None,
    retry_jitter_rng: Optional[Any] = None,
    mesh: Optional[Any] = None,
    param_specs: Optional[Any] = None,
):
    """Create the aiohttp application with a resident predictor.

    ``coalesce=True`` merges concurrent row-list ``features`` requests into shared
    predictor calls (see :mod:`unionml_tpu.serving.batcher`); requests whose payloads
    don't fit the row-list contract fall back to per-request prediction.

    ``mesh`` serves the resident predictor across a device mesh (see
    :class:`ResidentPredictor`): parameters commit to the mesh at startup
    (``param_specs`` lays them out, else replicated) and request batches shard
    over the ``data`` axis. The endpoint contract (``/predict``, ``/health``,
    ``/stats``) is unchanged above the sharded executor; for a mesh-sharded
    ``/generate`` pass a ``generator`` built with ``DecodeEngine(..., mesh=...)``.
    Under a mesh, coalesced flushes prefer multiples of the mesh's batch shards
    so merged batches shard evenly instead of padding up.

    ``seq_buckets`` enables sequence-length bucketing for tokenized inputs, and
    ``example_features`` (a request-shaped row list) drives startup warmup for
    multi-input models — see :class:`ResidentPredictor`.

    ``generator`` enables the continuous-batching ``POST /generate`` route for
    decoder models: a :class:`~unionml_tpu.serving.continuous.DecodeEngine`, a
    :class:`~unionml_tpu.serving.continuous.ContinuousBatcher`, or a zero-arg
    callable returning either — the callable form is evaluated at startup, AFTER
    the model artifact loads, so the engine can be built from trained variables.
    ``generate_lookahead`` sets the decode steps fused per device dispatch when
    the app wraps a bare engine (see :meth:`DecodeEngine.step`).

    ``generate_prefix_cache_blocks`` > 0 enables KV **prefix caching** on the
    served engine at startup (``generate_prefix_block_size`` tokens per block,
    see :meth:`DecodeEngine.enable_prefix_cache`) unless the engine already has
    one: requests sharing a prompt prefix (system prompts, chat history)
    restore its KV from a device block pool and prefill only their suffix.
    Cache hit/eviction counters surface under ``GET /stats`` →
    ``generation.prefix_cache``.

    ``generate_scheduler`` configures the SLO admission scheduler when the app
    wraps a bare engine (a
    :class:`~unionml_tpu.serving.scheduler.SchedulerConfig` or a prebuilt
    :class:`~unionml_tpu.serving.scheduler.SLOScheduler`; ``None`` = default
    policy). ``/generate`` payloads may carry ``priority``
    (``interactive``/``standard``/``batch``) and ``deadline_ms``; overload
    sheds map to HTTP 429/503 with ``Retry-After``, deadline expiry to 504,
    invalid requests to 400 — every error response shares ONE machine-readable
    envelope, ``{"error": {"code", "reason", "detail", "retry_after_ms"?}}``
    (``retry_after_ms`` is jittered so shed clients never retry in lockstep) —
    and scheduler counters surface under ``GET /stats`` →
    ``generation.scheduler``.

    ``generate_supervisor`` configures engine supervision when the app wraps a
    bare engine: ``None`` (default) builds an
    :class:`~unionml_tpu.serving.supervisor.EngineSupervisor` — engine
    failures salvage and RESUME every recoverable request token-identically,
    NaN-logits quarantine per request, a watchdog flags fetch stalls, and
    ``GET /healthz`` serves the health state machine (200 while
    ``ok``/``degraded``, 503 while ``rebuilding``/``failed``, with the last
    fault's reason). Pass a prebuilt supervisor to tune its knobs, or
    ``False`` to disable supervision. Shutdown drains gracefully: new
    submissions fail fast while in-flight work finishes for up to
    ``generate_drain_s`` seconds before the batcher closes. Robustness
    counters (faults injected/observed, rebuilds, recovered vs failed
    requests, quarantines, watchdog trips) surface under ``GET /stats`` →
    ``generation.robustness``.

    ``generate_replicas`` > 1 serves a FLEET
    (:class:`~unionml_tpu.serving.fleet.EngineFleet`): ``generator`` must
    then be a callable returning a bare ``DecodeEngine`` — it is invoked once
    per replica (receiving ``replica=i`` when its signature accepts it, so a
    factory can place each engine on its own sub-mesh; see
    :func:`~unionml_tpu.serving.fleet.split_mesh`) — or a prebuilt
    ``EngineFleet``. Requests route by prefix affinity, session stickiness
    (``/generate`` payloads may carry a ``session_id`` string), and
    load/health (``generate_fleet_config``, a
    :class:`~unionml_tpu.serving.fleet.FleetConfig`, tunes the router);
    ``/healthz`` and ``/stats`` → ``generation.fleet`` report per-replica
    state. Fleet replicas are always supervised (failover depends on it), so
    ``generate_supervisor=False`` is rejected, and ``generate_scheduler``
    must be a config, not a prebuilt scheduler instance.

    ``generate_telemetry`` wires the serving telemetry subsystem
    (:class:`~unionml_tpu.serving.telemetry.Telemetry`) into the generator at
    startup: per-request span traces (``GET /trace/{request_id}``,
    ``GET /traces/recent``), Prometheus metrics (``GET /metrics``), and a
    ``telemetry`` block under ``GET /stats`` that solo and fleet deployments
    share. ``True`` (default) builds one; pass a prebuilt ``Telemetry`` to
    share instruments with a harness, or ``False``/``None`` to disable — the
    request path then pays one host ``is not None`` branch per hook site and
    nothing else. ``generate_trace_journal`` names a JSONL file completed
    traces append to (schema v1; the replay-simulator input). Every
    ``/generate`` request is assigned a ``request_id`` (echoed in the
    response, in error envelopes, and in request-path log lines) that keys
    its trace.

    ``retry_jitter_rng`` (a ``random.Random``) seeds the ±25% Retry-After
    jitter on shed responses — by default a module-global RNG (production:
    de-correlated retries); a seeded instance makes shed envelopes
    reproducible for tests and A/B harnesses.
    """
    from aiohttp import web

    from unionml_tpu.serving.resident import DEFAULT_BUCKETS

    app = web.Application()
    predictor = (
        ResidentPredictor(
            model,
            buckets=buckets or DEFAULT_BUCKETS,
            seq_buckets=seq_buckets,
            example_features=example_features,
            mesh=mesh,
            param_specs=param_specs,
        )
        if resident
        else None
    )
    batcher = None
    if coalesce and predictor is not None:
        from unionml_tpu.serving.batcher import RequestBatcher

        preferred_multiple = None
        if mesh is not None:
            from unionml_tpu.parallel.mesh import batch_axis_size

            n_shards = batch_axis_size(mesh)
            preferred_multiple = n_shards if n_shards > 1 else None
        batcher = RequestBatcher(
            lambda rows: predictor.predict(features=rows),
            max_batch=max_batch,
            max_wait_ms=max_wait_ms,
            preferred_multiple=preferred_multiple,
        )

    async def on_startup(app):
        load_model_artifact(model, remote=remote, app_version=app_version, model_version=model_version)
        if predictor is not None:
            # graftlint: disable=async-blocking -- startup hook: the warmup compile+hard_sync runs before the server accepts any traffic, so blocking the (idle) loop here is the point
            predictor.setup()
        if generator is not None:
            import inspect

            from unionml_tpu.serving.continuous import ContinuousBatcher, DecodeEngine
            from unionml_tpu.serving.fleet import EngineFleet
            from unionml_tpu.serving.scheduler import SLOScheduler
            from unionml_tpu.serving.supervisor import EngineSupervisor
            from unionml_tpu.serving.telemetry import Telemetry

            telemetry = None
            if generate_telemetry:
                telemetry = (
                    generate_telemetry
                    if isinstance(generate_telemetry, Telemetry)
                    else Telemetry(journal_path=generate_trace_journal)
                )

            def _enable_cache(target):
                if (
                    generate_prefix_cache_blocks
                    and isinstance(target, DecodeEngine)
                    and target.prefix_cache is None
                ):
                    target.enable_prefix_cache(
                        generate_prefix_cache_blocks, generate_prefix_block_size
                    )

            prebuilt = isinstance(generator, (DecodeEngine, ContinuousBatcher, EngineFleet))
            if generate_replicas > 1 and not prebuilt:
                # fleet mode: the factory builds one bare engine per replica
                # (each on its own sub-mesh when the factory takes `replica`)
                if generate_supervisor is not None:
                    # False would disable the failover layer the fleet is
                    # built on; a single prebuilt supervisor can't be shared
                    # across replicas (pass supervisors= to EngineFleet)
                    raise ValueError(
                        "generate_replicas > 1 builds one supervisor per "
                        "replica; generate_supervisor must be left None"
                    )
                if isinstance(generate_scheduler, SLOScheduler):
                    raise ValueError(
                        "generate_replicas > 1 needs a SchedulerConfig (each "
                        "replica owns its own scheduler), not an SLOScheduler"
                    )
                takes_replica = "replica" in inspect.signature(generator).parameters
                engines = []
                for i in range(int(generate_replicas)):
                    engine = generator(replica=i) if takes_replica else generator()
                    if not isinstance(engine, DecodeEngine):
                        raise TypeError(
                            f"fleet generator must return a DecodeEngine per "
                            f"replica, got {type(engine)!r}"
                        )
                    _enable_cache(engine)
                    engines.append(engine)
                built = EngineFleet(
                    engines,
                    config=generate_fleet_config,
                    lookahead=generate_lookahead,
                    scheduler=generate_scheduler,
                    telemetry=telemetry,
                )
            else:
                built = generator() if callable(generator) and not prebuilt else generator
                if isinstance(built, EngineFleet):
                    for rep in built.replicas:
                        _enable_cache(rep.engine)
                else:
                    _enable_cache(built.engine if isinstance(built, ContinuousBatcher) else built)
                if isinstance(built, DecodeEngine):
                    # supervision is ON by default for app-owned batchers: engine
                    # failures recover instead of failing the house (False opts out)
                    supervisor = generate_supervisor
                    if supervisor is None:
                        supervisor = EngineSupervisor()
                    elif supervisor is False:
                        supervisor = None
                    built = ContinuousBatcher(
                        built, lookahead=generate_lookahead, scheduler=generate_scheduler,
                        supervisor=supervisor, telemetry=telemetry,
                    )
            if telemetry is not None:
                # prebuilt batchers/fleets get the same wiring post-hoc (no-op
                # when the caller already attached one — theirs wins)
                attach = getattr(built, "attach_telemetry", None)
                if callable(attach):
                    attach(telemetry)
            app["telemetry"] = getattr(built, "_telemetry", None) or telemetry
            app["continuous_batcher"] = built
        logger.info("Serving app ready (model=%s).", model.name)

    async def on_cleanup(app):
        if batcher is not None:
            batcher.close()
        gen = app.get("continuous_batcher")
        if gen is not None:
            # graceful drain: stop admitting, let in-flight work finish (or
            # time out into prompt structured failures), then close
            drain = getattr(gen, "drain", None)
            if callable(drain):
                # graftlint: disable=async-blocking -- shutdown hook: the server already stopped accepting; blocking the (dying) loop for the bounded drain is the point
                drain(generate_drain_s)
            else:
                # graftlint: disable=async-blocking -- shutdown hook, same contract as drain above
                gen.close()

    app.on_startup.append(on_startup)
    app.on_cleanup.append(on_cleanup)

    async def index(request):
        return web.Response(text=_INDEX_HTML, content_type="text/html")

    async def health(request):
        if model.artifact is None:
            return web.json_response({"detail": "Model artifact not found."}, status=500)
        return web.json_response({"message": HTTPStatus.OK.phrase, "status": HTTPStatus.OK.value})

    async def healthz(request):
        """Load-balancer health: the supervisor's state machine, 503 while the
        engine cannot serve (``rebuilding``/``failed``) so a router drains
        this replica instead of timing out against it. Apps without a
        supervised generator report on the model artifact alone."""
        gen = request.app.get("continuous_batcher")
        if gen is not None and getattr(gen, "is_fleet", False):
            # fleet shape: per-replica supervisor states; the fleet serves
            # (200) while ANY replica does — "degraded" flags reduced capacity
            body = gen.healthz()
            return web.json_response(
                body, status=200 if body["state"] in ("ok", "degraded") else 503
            )
        sup = getattr(gen, "supervisor", None) if gen is not None else None
        if sup is None:
            state = "ok" if model.artifact is not None else "failed"
            body = {"state": state, "supervised": False, "last_fault": None}
        else:
            stats = sup.stats()
            body = {
                "state": stats["health"],
                "supervised": True,
                "last_fault": sup.last_fault,
                "watchdog_trips": stats["watchdog_trips"],
                "rebuilds": stats["rebuilds"],
            }
        serving = body["state"] in ("ok", "degraded")
        return web.json_response(body, status=200 if serving else 503)

    async def predict(request):
        try:
            payload = await request.json()
        except Exception as exc:
            return web.json_response({"detail": f"Request body must be JSON: {exc}"}, status=422)
        inputs = payload.get("inputs")
        features = payload.get("features")
        if inputs is None and features is None:
            return web.json_response({"detail": "inputs or features must be supplied."}, status=500)
        import asyncio

        loop = asyncio.get_running_loop()
        try:
            # empty {} means reader-defaults ONLY when no features came along —
            # a boilerplate empty inputs key must not shadow a real features payload
            if inputs is not None and (inputs or features is None):
                # off the event loop: compiled predictor calls block for milliseconds+
                result = await loop.run_in_executor(
                    None,
                    lambda: predictor.predict(**inputs) if predictor is not None else model.predict(**inputs),
                )
            else:
                result = None
                if batcher is not None and isinstance(features, list):
                    try:
                        result = await batcher.submit(features)
                    except Exception as exc:
                        logger.info("Coalesced path failed (%s); serving this request directly.", exc)
                if result is None:
                    # model.predict runs the feature pipeline itself; don't pre-process here
                    result = await loop.run_in_executor(
                        None,
                        lambda: predictor.predict(features=features)
                        if predictor is not None
                        else model.predict(features=features),
                    )
            # jsonable() may device_get prediction arrays (graftlint
            # async-blocking true positive, fixed): fetch off the event loop,
            # like the predictor calls above
            payload = await loop.run_in_executor(None, jsonable, result)
            return web.json_response(payload)
        except Exception as exc:
            logger.exception("Prediction failed")
            return web.json_response({"detail": f"Prediction failed: {exc}"}, status=500)

    def _error_response(status, reason, detail, retry_after_s=None, request_id=None):
        """The ONE machine-readable error envelope every non-200 on this app
        uses — 400/429/500/503/504 all share it, so clients parse one shape:

            {"error": {"code": int, "reason": slug, "detail": str,
                       "retry_after_ms": int?, "request_id": str?}}

        ``request_id`` (present on every ``/generate`` failure) keys the
        request's span trace — ``GET /trace/{request_id}`` answers "what
        happened to THIS request" for sheds and failures alike.

        ``retry_after_ms`` (and the ``Retry-After`` header) carry ±25% JITTER:
        a shed wave handed one exact retry delay would come back as a
        synchronized thundering herd — the spread de-correlates the retries.
        The jitter draws from ``retry_jitter_rng`` when the app was built
        with one (seeded tests assert exact envelopes); default stays the
        module-global RNG.
        """
        import random

        error = {"code": int(status), "reason": reason, "detail": detail}
        if request_id is not None:
            error["request_id"] = request_id
        headers = {}
        if retry_after_s:
            draw = retry_jitter_rng.random if retry_jitter_rng is not None else random.random
            jittered = float(retry_after_s) * (0.75 + 0.5 * draw())
            error["retry_after_ms"] = int(jittered * 1000)
            headers["Retry-After"] = str(max(1, round(jittered)))
        return web.json_response({"error": error}, status=status, headers=headers)

    def _bad_request(detail, reason="invalid_request", request_id=None):
        """Client-side rejection: machine-readable ``reason`` + human detail."""
        return _error_response(400, reason, detail, request_id=request_id)

    def _scheduling_response(exc, request_id=None):
        """Map a structured scheduling rejection to its HTTP contract:
        queue-full sheds are 429, infeasible-deadline sheds are 503 (both with
        jittered ``Retry-After``), and deadline expiry is 504 — each carrying
        the error's machine-readable ``reason`` so clients can branch without
        parsing prose."""
        from unionml_tpu.serving.scheduler import (
            DeadlineExceededError,
            DeadlineInfeasibleError,
            QueueFullError,
        )

        if isinstance(exc, QueueFullError):
            status = 429
        elif isinstance(exc, DeadlineInfeasibleError):
            status = 503
        elif isinstance(exc, DeadlineExceededError):
            status = 504
        else:
            status = 500
        return _error_response(
            status, getattr(exc, "reason", "scheduling"), str(exc),
            retry_after_s=getattr(exc, "retry_after_s", None),
            request_id=request_id,
        )

    def _engine_failure_response(exc, request_id=None):
        """An engine-side structured failure: 503 when a retry can plausibly
        succeed (rebuilding, transient fault — another replica, or this one in
        a moment), 500 when it cannot — either way the reason slug travels,
        never a generic stringified 500."""
        retryable = bool(getattr(exc, "retryable", False))
        return _error_response(
            503 if retryable else 500, getattr(exc, "reason", "engine_failure"), str(exc),
            retry_after_s=1.0 if retryable else None,
            request_id=request_id,
        )

    async def generate_route(request):
        from unionml_tpu.serving.faults import EngineFailure
        from unionml_tpu.serving.scheduler import SchedulingError, parse_priority
        from unionml_tpu.serving.telemetry import new_request_id

        # minted at route entry so EVERY outcome — 400s included — carries an
        # id the client can quote; for a single-prompt request the same id
        # keys the span trace (GET /trace/{request_id})
        request_id = new_request_id()
        gen = request.app.get("continuous_batcher")
        if gen is None:
            return _error_response(
                404, "not_enabled", "Generation is not enabled on this app.",
                request_id=request_id,
            )
        try:
            payload = await request.json()
        except Exception as exc:
            return _bad_request(
                f"Request body must be JSON: {exc}", reason="invalid_json",
                request_id=request_id,
            )
        prompt_ids = payload.get("prompt_ids")
        prompts = payload.get("prompts")
        if prompt_ids is None and prompts is None:
            return _bad_request(
                "prompt_ids (one prompt) or prompts (a batch) must be supplied.",
                request_id=request_id,
            )
        import asyncio

        try:
            max_new = int(payload.get("max_new_tokens", 32))
        except (TypeError, ValueError):
            return _bad_request("max_new_tokens must be an integer.", request_id=request_id)
        if max_new < 1:
            # pre-validated here so the streaming path can reject BEFORE
            # committing a 200 status line (the engine's check would be too late)
            return _bad_request("max_new_tokens must be >= 1.", request_id=request_id)

        try:
            # validate EVERY prompt before scheduling any: a bad prompt in a
            # batch must not leave its siblings burning decode slots for a
            # response that will never be delivered (TypeError covers
            # non-numeric tokens / a non-list prompts value)
            for p in [prompt_ids] if prompt_ids is not None else prompts:
                seq = np.asarray(p, dtype=np.int32).reshape(-1)
                if seq.size == 0:
                    raise ValueError("empty prompt")
                if seq.size >= gen.engine.max_len:
                    raise ValueError(f"prompt length {seq.size} >= max_len ({gen.engine.max_len})")
                gen.engine.bucket_for(seq.size)
        except (TypeError, ValueError) as exc:
            return _bad_request(f"invalid prompt payload: {exc}", request_id=request_id)

        # optional SLO fields: a priority class and a wall-clock deadline
        # budget (ms, arrival -> completion), forwarded to the generator's
        # scheduler only when present so custom generators without the
        # scheduler kwargs keep working
        slo = {}
        if payload.get("priority") is not None:
            try:
                slo["priority"] = parse_priority(payload["priority"])
            except ValueError as exc:
                return _bad_request(str(exc), request_id=request_id)
        if payload.get("deadline_ms") is not None:
            deadline_ms = payload["deadline_ms"]
            if (
                isinstance(deadline_ms, bool)
                or not isinstance(deadline_ms, (int, float))
                or deadline_ms <= 0
            ):
                return _bad_request(
                    f"deadline_ms must be a positive number, got {deadline_ms!r}",
                    request_id=request_id,
                )
            slo["deadline_ms"] = float(deadline_ms)
        if payload.get("session_id") is not None:
            session_id = payload["session_id"]
            if not isinstance(session_id, str) or not session_id:
                return _bad_request(
                    f"session_id must be a non-empty string, got {session_id!r}",
                    request_id=request_id,
                )
            # session stickiness is a fleet-router concept; forwarded only to
            # a fleet generator (a single batcher has no session kwarg, and a
            # sessionless deployment should not reject the field)
            if getattr(gen, "is_fleet", False):
                slo["session_id"] = session_id

        # optional per-request sampling controls (applied to every prompt in a
        # batch); absent keys defer to the engine's construction-time settings
        from unionml_tpu.ops.sampling import validate_sampling

        try:
            temp, top_k, top_p = validate_sampling(
                payload.get("temperature"),
                payload.get("top_k") if payload.get("top_k") is not None else 0,
                payload.get("top_p") if payload.get("top_p") is not None else 1.0,
            )
        except (TypeError, ValueError) as exc:
            return _bad_request(f"invalid sampling params: {exc}", request_id=request_id)
        sampling = {}
        if payload.get("temperature") is not None:
            sampling["temperature"] = temp
        if payload.get("top_k") is not None:
            sampling["top_k"] = top_k
        if payload.get("top_p") is not None:
            sampling["top_p"] = top_p
        stream = bool(payload.get("stream"))
        if stream and prompt_ids is None:
            return _bad_request(
                "stream=true requires a single prompt_ids prompt.", request_id=request_id
            )
        # forward the route's id into the generator's trace when it can carry
        # it (single prompt only: each prompt of a batch opens its OWN trace,
        # while the route-level id still identifies the HTTP request)
        rid_kw = (
            {"request_id": request_id}
            if getattr(gen, "accepts_request_id", False)
            else {}
        )
        if stream:
            import contextlib
            import json as _json

            # pull the FIRST token before committing the 200 status line, so
            # scheduling rejections (queue full / infeasible or expired
            # deadline) surface as their real 429/503/504 statuses instead of
            # an in-band error on a 200 stream
            stream_it = gen.stream(prompt_ids, max_new, **slo, **sampling, **rid_kw)
            exhausted, first = False, None
            try:
                first = await anext(stream_it)
            except StopAsyncIteration:
                exhausted = True  # zero emitted tokens (e.g. immediate eos)
            except SchedulingError as exc:
                await stream_it.aclose()
                return _scheduling_response(exc, request_id=request_id)
            except EngineFailure as exc:
                await stream_it.aclose()
                return _engine_failure_response(exc, request_id=request_id)
            except ValueError as exc:
                await stream_it.aclose()
                return _bad_request(str(exc), request_id=request_id)
            except Exception as exc:
                await stream_it.aclose()
                logger.exception("Generation failed (request_id=%s)", request_id)
                return _error_response(
                    500, "internal", f"Generation failed: {exc}", request_id=request_id
                )

            # ndjson chunks: one {"token": N} line per decoded token, then a
            # {"done": true, "tokens": [...]} trailer. Failures from here on
            # can only be reported in-band as an {"error": ...} line (the
            # status line is already out)
            response = web.StreamResponse()
            response.content_type = "application/x-ndjson"
            await response.prepare(request)
            tokens = []
            try:
                # aclosing guarantees the stream iterator closes promptly on an
                # early exit (client disconnect -> write raises), which cancels
                # the request's decode slot
                async with contextlib.aclosing(stream_it) as it:
                    if not exhausted:
                        tokens.append(first)
                        await response.write((_json.dumps({"token": first}) + "\n").encode())
                        async for token in it:
                            tokens.append(token)
                            await response.write((_json.dumps({"token": token}) + "\n").encode())
                await response.write(
                    (_json.dumps({"done": True, "tokens": tokens}) + "\n").encode()
                )
            except Exception as exc:
                logger.warning(
                    "Streaming generation ended early (request_id=%s): %s", request_id, exc
                )
                line = {"error": str(exc), "request_id": request_id}
                reason = getattr(exc, "reason", None)
                if reason is not None:
                    # a deadline expiring (or the engine failing) mid-stream
                    # lands here: the status is committed, so the reason slug
                    # travels in-band instead
                    line["reason"] = reason
                try:  # the transport may be the thing that failed
                    await response.write((_json.dumps(line) + "\n").encode())
                except Exception:  # graftlint: disable=swallowed-exception -- writing the in-band error line to a transport that may itself be the failure: nothing is left to tell
                    pass
            try:
                await response.write_eof()
            except Exception:  # graftlint: disable=swallowed-exception -- eof on a possibly-dead transport: the request is already finished either way
                pass
            return response
        try:
            if prompt_ids is not None:
                tokens = await gen.generate(prompt_ids, max_new, **slo, **sampling, **rid_kw)
                return web.json_response({"tokens": tokens, "request_id": request_id})
            completions = await asyncio.gather(
                *(gen.generate(p, max_new, **slo, **sampling) for p in prompts)
            )
            return web.json_response(
                {"completions": list(completions), "request_id": request_id}
            )
        except SchedulingError as exc:  # structured shed / deadline rejection
            return _scheduling_response(exc, request_id=request_id)
        except EngineFailure as exc:  # engine-side structured failure (recovery taxonomy)
            return _engine_failure_response(exc, request_id=request_id)
        except ValueError as exc:  # bad request (empty/oversized prompt, bad budget)
            return _bad_request(str(exc), request_id=request_id)
        except Exception as exc:  # engine/worker failures are SERVER errors
            logger.exception("Generation failed (request_id=%s)", request_id)
            return _error_response(
                500, "internal", f"Generation failed: {exc}", request_id=request_id
            )

    async def stats(request):
        payload = {"model": model.name, "resident": predictor is not None}
        if predictor is not None and hasattr(predictor, "device_stats"):
            # server-side device latency (dispatch + fetch), split from HTTP RTT
            payload["device_latency"] = predictor.device_stats()
        gen = request.app.get("continuous_batcher")
        if gen is not None and getattr(gen, "is_fleet", False):
            # fleet shape: aggregate counters + generation.fleet with the
            # router block and per-replica scheduler/supervisor/cache state
            payload["generation"] = gen.stats()
        elif gen is not None:
            # every generator kind (continuous engine, speculative facade)
            # surfaces the same counter set; getattr defaults keep the route
            # total even for a custom generator exposing only the core triple
            payload["generation"] = {
                "num_slots": gen.engine.num_slots,
                "active": gen.engine.num_active,
                "max_len": gen.engine.max_len,
                "requests_admitted": getattr(gen.engine, "requests_admitted", 0),
                "tokens_decoded": getattr(gen.engine, "tokens_decoded", 0),
            }
            spec_stats = getattr(gen.engine, "speculation_stats", None)
            if callable(spec_stats):
                # speculative decoding observability: acceptance EMA, current
                # adaptive γ, round/fallback counters, and the accepted-tokens-
                # per-target-step ratio the bench gates on
                payload["generation"]["speculation"] = spec_stats()
            pipeline_stats = getattr(gen.engine, "pipeline_stats", None)
            if callable(pipeline_stats):
                # pipelined-decode observability: depth, host-gap EMA (ms the
                # device queue sat empty before a dispatch), fetch-block EMA,
                # and device-idle dispatch counters
                payload["generation"]["pipeline"] = pipeline_stats()
            if getattr(gen.engine, "prefix_cache", None) is not None:
                # hit rate + eviction churn for the KV prefix cache, plus the
                # engine's FLOP counter the hits shrink
                payload["generation"]["prefix_cache"] = gen.engine.prefix_cache.stats()
                payload["generation"]["prefill_tokens_computed"] = (
                    gen.engine.prefill_tokens_computed
                )
                kv_stats = getattr(gen.engine, "kv_pool_stats", None)
                if callable(kv_stats):
                    # pool dtype + resident bytes (stored vs priced at the
                    # dense compute dtype) — the kv_quantize="int8" saving
                    payload["generation"]["prefix_cache"].update(kv_stats())
            sched = getattr(gen, "scheduler", None)
            if sched is not None and callable(getattr(sched, "stats", None)):
                # SLO scheduler observability: per-class queue depth,
                # queue-wait EMA, shed / preemption / deadline-miss counters —
                # the same block whichever generator kind is plugged in
                payload["generation"]["scheduler"] = sched.stats()
            # robustness observability: engine-side failure/quarantine/fault
            # counters merged with the supervisor's health + recovery counters
            robustness = {}
            engine_stats = getattr(gen.engine, "robustness_stats", None)
            if callable(engine_stats):
                robustness.update(engine_stats())
            sup = getattr(gen, "supervisor", None)
            if sup is not None and callable(getattr(sup, "stats", None)):
                robustness.update(sup.stats())
            if robustness:
                payload["generation"]["robustness"] = robustness
        tel = request.app.get("telemetry")
        if tel is not None:
            # the ONE schema solo and fleet share: trace/journal state plus a
            # snapshot of every registry instrument (the same counters the
            # Prometheus /metrics endpoint renders), so a client reads one
            # block whichever deployment shape is behind the route
            payload["telemetry"] = {**tel.stats(), "metrics": tel.metrics.snapshot()}
            if "generation" in payload and getattr(tel, "slo", None) is not None:
                # per-class SLO attainment + multi-window burn rate, identical
                # solo/fleet (the tracker sits on the shared Telemetry, above
                # whichever generator shape feeds it)
                payload["generation"]["slo"] = tel.slo.report()
        if batcher is not None:
            payload["coalescing"] = dict(batcher.stats)
            if batcher.ema_gap_ms is not None:
                payload["coalescing"]["ema_gap_ms"] = round(batcher.ema_gap_ms, 3)
        return web.json_response(payload)

    async def metrics_route(request):
        """``GET /metrics``: Prometheus text exposition (format 0.0.4) of the
        serving registry — one scrape target whichever generator shape
        (solo engine, fleet) is behind the app."""
        tel = request.app.get("telemetry")
        if tel is None:
            return _error_response(404, "not_enabled", "Telemetry is not enabled on this app.")
        return web.Response(
            body=tel.metrics.render().encode("utf-8"),
            headers={"Content-Type": "text/plain; version=0.0.4; charset=utf-8"},
        )

    async def trace_route(request):
        """``GET /trace/{request_id}``: the request's full span tree (active
        or recently completed) — admission, queue wait, routing, prefix
        restore, prefill chunks, decode, preemption/quarantine/failover, and
        the terminal status."""
        tel = request.app.get("telemetry")
        if tel is None:
            return _error_response(404, "not_enabled", "Telemetry is not enabled on this app.")
        rid = request.match_info["request_id"]
        trace = tel.get_trace(rid)
        if trace is None:
            return _error_response(
                404, "trace_not_found",
                f"no active or recent trace for request_id {rid!r} "
                f"(the journal ring may have evicted it)",
                request_id=rid,
            )
        return web.json_response(trace)

    async def traces_recent(request):
        """``GET /traces/recent?n=K``: the journal ring's most recent completed
        traces, newest first (JSONL schema v1 objects)."""
        tel = request.app.get("telemetry")
        if tel is None:
            return _error_response(404, "not_enabled", "Telemetry is not enabled on this app.")
        try:
            n = int(request.query.get("n", 50))
        except (TypeError, ValueError):
            return _bad_request("n must be an integer.")
        return web.json_response({"traces": tel.recent(n)})

    app.router.add_get("/", index)
    app.router.add_get("/health", health)
    app.router.add_get("/healthz", healthz)
    app.router.add_get("/stats", stats)
    app.router.add_get("/metrics", metrics_route)
    app.router.add_get("/trace/{request_id}", trace_route)
    app.router.add_get("/traces/recent", traces_recent)
    app.router.add_post("/predict", predict)
    app.router.add_post("/generate", generate_route)
    app["unionml_model"] = model
    app["resident_predictor"] = predictor
    app["request_batcher"] = batcher
    app["telemetry"] = None  # set at startup when a generator is wired
    return app


def run_app(app, host: str = "127.0.0.1", port: int = 8000) -> None:
    from aiohttp import web

    web.run_app(app, host=host, port=port)
